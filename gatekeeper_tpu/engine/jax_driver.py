"""The ``jax`` driver: vectorized device engine with scalar fallback.

This plugs the TPU pipeline into the same Driver seam the reference
exposes for engines (vendor/.../drivers/interface.go:21-33 — the local
OPA driver and the remote HTTP driver are the two reference
implementations; this is the third kind the seam was designed for).

Audit dataflow (replacing the single-threaded topdown cross-product,
reference client.go:584-607 + regolib/src.go:38-52):

  1. per template kind: lowered program + bindings (columns, host
     tables, per-constraint tensors) — cached by (table generation,
     constraint-set version), so steady-state audits re-run only the
     jitted executable;
  2. device: violation mask [n_constraints, n_resources], ANDed with
     the vectorized match mask (engine/match.py);
  3. host: only the violating pairs are re-evaluated with the scalar
     oracle to produce exact messages/details (the device mask may
     over-approximate; over-approximated pairs simply format to
     nothing).  With a per-constraint limit (the audit manager's cap,
     reference manager.go:35) the host formats at most
     limit x n_constraints pairs regardless of inventory size.

Templates outside the lowerable subset run on the scalar oracle
restricted to match-mask candidates — same results, no silent behavior
split (SURVEY §7 hard-part 6).  data.inventory joins in the
duplicate-detection shape DO lower (ir/lower.py `_try_inventory_join`);
the per-template bucket is pinned in library/lowering_buckets.json.

The review path delegates to the scalar engine: single-review latency
is interpreter-bound and the reference's semantics (autoreject,
matching, tracing) are already exact there.  Micro-batched admission
rides the audit kernels via webhook batching (pkg webhook).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import numpy as np

from gatekeeper_tpu.api.templates import CompiledTemplate
from gatekeeper_tpu.client.interface import QueryOpts
from gatekeeper_tpu.client.local_driver import (LocalDriver, TargetState,
                                                locked, locked_read)
from gatekeeper_tpu.client.types import Result, enforcement_action_of
from gatekeeper_tpu.engine.veval import ProgramExecutor
from gatekeeper_tpu.errors import ExternalDataError
from gatekeeper_tpu.ir.lower import CannotLower, lower_template
from gatekeeper_tpu.ir.prep import build_bindings
from gatekeeper_tpu.rego.values import freeze
from gatekeeper_tpu.utils.metrics import Metrics


class _TrivialMatch:
    """Sentinel mask: every alive row matches every constraint (no
    spec.match anywhere).  Indexable like the real mask so host-side
    candidate checks stay uniform."""

    def __getitem__(self, _idx):
        return True

    def __bool__(self):
        return True


TRIVIAL_MATCH = _TrivialMatch()


class _RowOrder:
    """Dict-like row -> sorted-key position, backed by an inverse
    permutation array (building a 1M-entry Python dict is a measurable
    cold-start tax; formatting only ever probes a capped handful)."""

    __slots__ = ("_pos", "_n")

    def __init__(self, ordered_rows: np.ndarray):
        n = int(ordered_rows.max()) + 1 if len(ordered_rows) else 0
        self._pos = np.full((n,), -1, dtype=np.int64)
        self._pos[ordered_rows] = np.arange(len(ordered_rows), dtype=np.int64)
        self._n = len(ordered_rows)

    def __len__(self) -> int:
        return self._n

    def __contains__(self, row) -> bool:
        return 0 <= row < len(self._pos) and self._pos[row] >= 0

    def __getitem__(self, row) -> int:
        p = self._pos[row] if 0 <= row < len(self._pos) else -1
        if p < 0:
            raise KeyError(row)
        return int(p)

SMALL_WORKLOAD_EVALS = 20_000
"""Below this many (resource, constraint) pairs per kind, the scalar
engine beats the device path: a single device dispatch+fetch costs a
fixed ~100ms through a tunneled accelerator, which only amortizes over
enough work.  The scalar path produces identical results (it is the
oracle), so routing is purely a latency decision."""

MIRROR_EAGER_MIN_ROWS = 50_000
"""Batch ingests at/above this row count eagerly materialize the
columnar mirror (element axes + per-kind bindings) and kick background
executable prewarms — the first audit after a restart then spends its
wall on dispatch + fetch, not on prep the store could have done at
write time (deliberately NOT test-overridable via
SMALL_WORKLOAD_EVALS: tiny test ingests must stay cheap)."""

def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


REVIEW_BATCH_MIN_EVALS = _env_int(
    "GATEKEEPER_REVIEW_BATCH_MIN_EVALS", 200_000)
"""Below this many (review, constraint) pairs, a coalesced admission
batch stays on the scalar engine.

Measured on the v5e behind the ~100ms-per-fetch tunnel
(bench_admission_device_batch, with BOTH routing thresholds zeroed so
every batch size actually runs the device path): with 200 constraints
the device path only reaches scalar parity around batch 1024 (~200k
evals) — per-batch prep + the fetch round-trip dominate below that.

DELIBERATE SCOPE: with the webhook's default --max-batch 64 (and 200
constraints = 12.8k evals), admission therefore never routes to a
TUNNELED device — that dead zone is physics, not an accident: one
tunnel round-trip (~100ms) costs more than the whole 64-review batch
on the scalar engine (p50 well under 1ms/review).  On co-located
TPU the crossover drops sharply; set
GATEKEEPER_REVIEW_BATCH_MIN_EVALS from the crossover table bench.py
emits (detail.admission_device_batch) for that transport.  See
README "Device-batched admission"."""

DEFAULT_PREWARM_CAP = 20
"""Cap assumed for prewarmed audit executables — the audit manager's
per-constraint violation cap (reference pkg/audit/manager.go:35)."""

FULL_SWEEP_SERIAL = os.environ.get("GATEKEEPER_FULL_SWEEP_SERIAL") == "1"
"""Diagnostic baseline: run a forced-full sweep (QueryOpts.full) with
NO pipelining — each kind's host prep, H2D upload, and device execution
complete before the next kind's prep starts.  bench.py measures this
no-overlap serial number against the pipelined full sweep; it is the
measurement the pipeline exists to beat.  Never enable in production."""


class _ResolvedHandle:
    """Pre-resolved stand-in for a PendingTopK/PendingMask handle:
    selective invalidation replays a payload captured on a previous
    sweep instead of dispatching, and the format path only ever calls
    ``.get()``/``.block()``."""

    def __init__(self, payload):
        self._payload = payload

    def get(self):
        return self._payload

    def block(self):
        return self


SWEEP_CACHE_MAX_BYTES = 64 * 1024 * 1024
"""Per-kind payload cap for the selective-invalidation sweep cache —
top-k payloads are tiny; uncapped [C, R] masks at cluster scale are
not worth holding for a maybe-reuse."""


class JaxTargetState(TargetState):
    def __init__(self):
        super().__init__()
        self.con_version: dict[str, int] = {}      # kind -> bump on change
        self.bindings_cache: dict[str, tuple] = {}  # kind -> (cache key, b)
        self.bindings_retired: dict[str, tuple] = {}  # kind -> (ver, old b)
        self.mask_cache: dict[str, tuple] = {}
        # kind -> the padded mask currently installed as a bindings
        # __match__ array: that buffer may still be referenced by host
        # formatting and by the device cache, so the mask ping-pong must
        # never overwrite it in place
        self.installed_match: dict[str, object] = {}
        self.rank_cache: tuple | None = None       # (generation, rank arr)
        self.order_cache: tuple | None = None      # (gen, ordered_rows, row_order)
        self.fmt_cache: dict[str, tuple] = {}      # kind -> (con_ver, {(cname,row): (ver, results)})
        self.match_engine = None
        # kind -> Stage-5 dependency footprint (analysis/footprint.py)
        self.footprints: dict[str, object] = {}
        # kind -> Stage-6 partition plan (analysis/shardplan.py)
        self.shardplans: dict[str, object] = {}
        # kind -> Stage-7 compile-surface certificate
        # (analysis/compilesurface.py)
        self.compilesurfaces: dict[str, object] = {}
        # kind -> Stage-8 memory-surface certificate
        # (analysis/memsurface.py)
        self.memsurfaces: dict[str, object] = {}
        # kind -> last device sweep payload + guards, for
        # footprint-driven selective invalidation (_selective_reuse)
        self.sweep_cache: dict[str, dict] = {}
        # continuous enforcement (enforce/ledger.py): the target's
        # VerdictLedger (created on the first paged sweep), a restored
        # pagemap snapshot awaiting per-kind adoption, and the overflow
        # counter watermark already exported to metrics
        self.ledger = None
        self.ledger_restored: dict | None = None
        self.dirtylog_overflows_seen = 0
        # device-resident paged store (enforce/devpages.py): per-kind
        # KindPages (resident mask + page table + inv-join inputs),
        # and snapshot geometry awaiting adoption on warm restart
        self.devpages: dict[str, object] = {}
        self.devpages_geom: dict | None = None
        # dedup shared-conjunct columns carried across full sweeps:
        # digest -> (gen, remap, shape, col) — churn re-evaluates only
        # the dirty-row slice (policyset.eval_shared_host rows=...)
        self.dedup_shared_cache: dict = {}

    def bump(self, kind: str) -> None:
        self.con_version[kind] = self.con_version.get(kind, 0) + 1


class JaxDriver(LocalDriver):
    """Driver with device-evaluated audit; construction mirrors
    local.New (drivers/local/local.go:28) with tracing default."""

    def __init__(self, tracing: bool = False):
        super().__init__(tracing=tracing)
        mesh = None
        # bounded bring-up (utils/device_probe): a backend that errors
        # OR hangs must not block construction — the reference's driver
        # always constructs (drivers/local/local.go:28-48), and SURVEY
        # §5 requires CPU fallback on device failure.  scalar_only
        # (a property over the backend supervisor) routes every
        # evaluation through the scalar oracle, which never touches
        # jax.  Unlike the old cached bool, the supervisor can bring a
        # degraded backend home: each dispatch re-consults it, and a
        # recovery re-jits through _on_backend_recovered.
        from gatekeeper_tpu.utils.device_probe import probe_devices
        from gatekeeper_tpu.resilience.supervisor import get_supervisor
        res = probe_devices()
        self.supervisor = get_supervisor()
        if not res.ok:
            from gatekeeper_tpu.utils.log import logger
            logger("engine").warning(
                "device backend unavailable; scalar-only engine",
                reason=res.reason)
        else:
            # GATEKEEPER_SHARDS selects the mesh: 0/unset keeps the
            # legacy all-device mesh when multiple devices exist; 1
            # forces the unsharded oracle (no mesh even multi-device);
            # N >= 2 builds the Stage-6 row-only simulated mesh the
            # partition plans are certified against.
            n_shards = _env_int("GATEKEEPER_SHARDS", 0)
            if n_shards >= 2:
                from gatekeeper_tpu.parallel.sharding import make_sim_mesh
                mesh = make_sim_mesh(n_shards)
            elif n_shards == 0 and res.n_devices > 1:
                from gatekeeper_tpu.parallel.sharding import make_mesh
                mesh = make_mesh()      # a real failure here should raise
        self.supervisor.add_recovery_listener(self, "_on_backend_recovered")
        self.executor = ProgramExecutor(mesh=mesh)
        self.metrics = Metrics()
        # Stage-7 retrace sentinel: consulted by the executor ONLY on a
        # jit cache miss; a signature outside the installed
        # CompileSurface certificate is counted + flight-recorded here
        # (strict-mode refusal happens at the executor seam)
        self.executor.surface_guard = self._surface_guard
        # serializes reader-side cache fills (bindings/mask delta prep):
        # racing audit readers would otherwise interleave interner
        # appends and column/cache mutations across different kinds —
        # NOT the identical computation the RWLock benign-race argument
        # assumes.  Execution and host formatting stay concurrent.
        import threading as _threading
        self._prep_lock = _threading.Lock()
        # predict_review_batch_seconds memo: (n, #templates, #cons) ->
        # summed cost units (scale applied fresh each call)
        self._predict_cache: dict[tuple, float] = {}
        # one-shot background churn-delta prewarm after the first sweep
        # (shape changes later recompile lazily on the sweep, as before)
        self._delta_warmed = False
        # cross-template dedup plan memo: target -> (policyset digest,
        # plan).  The digest is a pure function of the installed set, so
        # template/constraint churn invalidates by key mismatch — no
        # staleness window.  prepare_audit fills it at startup; the
        # sweep consults it before building.
        self._dedup_plan_memo: dict = {}
        # per-phase breakdown of the most recent audit sweep (the audit
        # manager copies host_prep_s/h2d_s/device_s/overlap_fraction
        # into its sweep report; phase timings are only measured on
        # forced-full sweeps — {"full": False} otherwise)
        self.last_sweep_phases: dict = {}

    # ------------------------------------------------------------------

    @property
    def scalar_only(self) -> bool:
        """Is the device path unavailable *right now*?  A property, not
        a construction-time bool: serving paths re-consult the backend
        supervisor per dispatch, so a mid-sweep degradation routes the
        remaining kinds through the scalar oracle and a recovery routes
        later sweeps back onto the device."""
        return not self.supervisor.use_device()

    def _on_backend_recovered(self) -> None:
        """Recovery listener: compiled executables (and uploaded
        buffers, via the bindings they hang off) may reference the dead
        backend's client — drop them so the next dispatch re-jits onto
        the recovered backend.  The XLA persistent cache and the warm
        IR snapshots make that re-jit cheap."""
        try:
            with self._prep_lock:
                self.executor.reset_for_recovery()
                for st in self.state.values():
                    st.bindings_cache.clear()
                    st.bindings_retired.clear()
                    st.mask_cache.clear()
                    st.installed_match.clear()
                    st.rank_cache = None
                    st.order_cache = None
                self._dedup_plan_memo.clear()
            self.metrics.counter("backend_rejits").inc()
        except Exception as e:   # noqa: BLE001 — recovery cleanup must
            from gatekeeper_tpu.utils.log import logger   # never throw
            logger("engine").warning("post-recovery re-jit reset failed",
                                     error=e)

    def init(self, targets) -> None:
        self.targets = dict(targets)
        for name in targets:
            self.state.setdefault(name, JaxTargetState())

    @locked
    def save_store_snapshot(self, target: str) -> bool:
        """Persist the target's columnar store (rows + interned string
        table) for warm restart.  No-op unless GATEKEEPER_SNAPSHOT_DIR
        is set."""
        from gatekeeper_tpu.resilience import snapshot as _snap
        if not _snap.enabled():
            return False
        st = self._state(target)
        ok = _snap.save_store(target, st.table.snapshot_state())
        if ok and isinstance(st, JaxTargetState):
            from gatekeeper_tpu.enforce.ledger import pages_mode as _pg
            if st.ledger is not None and st.ledger.entries:
                # companion pagemap tier: the ledger's confirmed
                # verdicts ride the same snapshot so a warm restart
                # adopts them (per kind, revalidated by constraint
                # digest + row count) instead of paying a cold build.
                # Each kind is stamped with the watch RV watermark the
                # verdicts were built at: the reactor forces one kind
                # resync if its first observed event does not extend it
                payload = st.ledger.snapshot_payload()
                wm = st.table.rv_watermark()
                # ledger entries are keyed by constraint kind while the
                # store watermark is keyed by resource kind, so each
                # entry gets the global epoch (RVs are cluster-global)
                # and the per-resource-kind map rides along under a
                # reserved key for the reactor's per-stream floors
                wm_max = max(wm.values(), default=0)
                for kind, p in payload.items():
                    p["rv"] = max(int(p.get("rv", 0) or 0), wm_max)
                    # device-pagemap geometry rides the pg tier: a warm
                    # restart adopting the verdicts also adopts the
                    # paged layout (slot capacity, page shape, free
                    # list) so the first device sweep rebuilds nothing
                    kp = st.devpages.get(kind)
                    if kp is not None and getattr(kp, "slots", 0):
                        p["devpages"] = kp.geometry()
                if wm:
                    payload["__rv__"] = dict(wm)
                _snap.save_pagemap(target, payload)
            elif _pg():
                # pages-on deployment snapshotted before the first
                # sweep built a ledger: persist an empty pagemap so the
                # companion-tier restore is a hit with zero adoptions,
                # not a spurious tier miss
                _snap.save_pagemap(target, {})
        return ok

    @locked
    def restore_store_snapshot(self, target: str) -> bool:
        """Warm restart: rebuild the target's columnar store from the
        on-disk snapshot instead of replaying the full inventory.
        Only valid on a fresh (empty) store; returns False on miss,
        disabled persistence, or a non-empty table."""
        from gatekeeper_tpu.resilience import snapshot as _snap
        st = self._state(target)
        if len(st.table) > 0:
            return False
        hit = _snap.load_store(target)
        if hit is None:
            return False
        st.table.restore_state(hit[0])
        if isinstance(st, JaxTargetState):
            from gatekeeper_tpu.enforce.ledger import pages_mode as _pg
            # the pagemap tier only exists for paged deployments — with
            # pages off the ledger is never consulted, so don't charge
            # a tier miss against the warm-restart counters
            if _pg():
                hitpg = _snap.load_pagemap(target)
                st.ledger_restored = hitpg[0] if hitpg is not None \
                    else None
        return True

    @locked
    def adopt_store(self, target: str, state: dict) -> None:
        """Swap the target's columnar store for a fresh table built
        from a ``snapshot_state()`` payload — the
        load-snapshot-as-secondary-store path (whatif/replay.py).
        Unlike restore_store_snapshot this is valid on a non-empty
        driver: every table-derived cache layer is dropped, because the
        new table's generation counters restart and would otherwise
        collide with cached keys from the old table."""
        from gatekeeper_tpu.store.table import ResourceTable
        st = self._state(target)
        st.table = ResourceTable.from_state(state)
        st._inv_cache = None
        if isinstance(st, JaxTargetState):
            st.bindings_cache = {}
            st.bindings_retired = {}
            st.mask_cache = {}
            st.installed_match = {}
            st.rank_cache = None
            st.order_cache = None
            st.fmt_cache = {}
            st.match_engine = None
            st.sweep_cache = {}
            # the ledger's row ids and generation guards are meaningless
            # against the swapped table (its counters restart)
            st.ledger = None
            st.ledger_restored = None
            st.dirtylog_overflows_seen = 0
            for kind in list(st.templates):
                st.bump(kind)

    @locked
    def put_template(self, target: str, kind: str, compiled: CompiledTemplate) -> None:
        if compiled.vectorized is None:
            from gatekeeper_tpu.resilience import snapshot as _snap
            hit = _snap.load_template_ir(kind, target, compiled.source)
            if hit is not None:
                # warm restart: lowering AND stage-2 verification are
                # skipped — the snapshot stores the verified outcome
                # (possibly None: a known-scalar-only certificate)
                compiled.vectorized = hit[0]
                self.metrics.counter("template_ir_snapshot_hits").inc()
            else:
                try:
                    compiled.vectorized = lower_template(
                        compiled.module, compiled.interp)
                except CannotLower:
                    compiled.vectorized = None  # scalar fallback
                if compiled.vectorized is not None:
                    compiled.vectorized = self._verify_lowered(
                        kind, compiled.vectorized)
                _snap.save_template_ir(kind, target, compiled.source,
                                       compiled.vectorized)
            # stage 4 runs on BOTH paths: the cert snapshot tier (not
            # the IR tier) is what makes the warm restart skip it
            if compiled.vectorized is not None:
                compiled.vectorized = self._certify_lowered(kind, compiled)
        st = self._state(target)
        # stage 5 (dependency footprint) also runs on both paths — the
        # fp snapshot tier keeps warm restarts at zero re-analyses
        if isinstance(st, JaxTargetState):
            fp = None
            if compiled.vectorized is not None:
                fp = self._footprint_lowered(kind, compiled)
            if fp is not None:
                st.footprints[kind] = fp
            else:
                st.footprints.pop(kind, None)
            # stage 6 (partition plan): certifies HOW the lowered
            # program shards along the resource axis; the sp snapshot
            # tier keeps warm restarts at zero re-analyses
            sp = None
            if compiled.vectorized is not None:
                sp = self._shardplan_lowered(kind, compiled)
            if sp is not None:
                st.shardplans[kind] = sp
            else:
                st.shardplans.pop(kind, None)
            # stage 7 (compile surface): certifies the finite signature
            # set the jitted programs can be entered with; the cs
            # snapshot tier keeps warm restarts at zero re-analyses.
            # Scalar pins get the trivial empty-surface certificate.
            cs_cert = self._compilesurface_lowered(kind, compiled)
            if cs_cert is not None:
                st.compilesurfaces[kind] = cs_cert
            else:
                st.compilesurfaces.pop(kind, None)
            # stage 8 (memory surface): certifies the conservative
            # peak-HBM bytes of every certified signature; the ms
            # snapshot tier keeps warm restarts at zero re-analyses.
            # Strict mode rejects installs whose worst-signature peak
            # exceeds the budget (hbm_budget_exceeded).
            ms_cert = self._memsurface_lowered(kind, compiled)
            if ms_cert is not None:
                st.memsurfaces[kind] = ms_cert
            else:
                st.memsurfaces.pop(kind, None)
            st.sweep_cache.pop(kind, None)
        st.templates[kind] = compiled
        st.bump(kind)

    def _footprint_lowered(self, kind: str, compiled: CompiledTemplate):
        """Stage-5 dependency analysis (analysis/footprint.py) behind
        GATEKEEPER_FOOTPRINT=off|on|strict.  on: compute the read-set /
        row-locality footprint (enables selective invalidation); strict:
        additionally perturbation-validate it and FAIL the install on
        any violation — a violation means the analysis itself is wrong,
        and serving selective sweeps from a wrong read-set would skip
        real re-evaluations."""
        from gatekeeper_tpu.analysis import footprint
        if footprint.mode() == "off":
            return None
        try:
            fp = footprint.certify(kind, compiled, compiled.vectorized)
        except Exception as e:   # noqa: BLE001 — analysis must not take
            # template install down with it; no footprint just means no
            # selective reuse for this kind
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "footprint analysis errored", kind=kind, err=str(e))
            self.metrics.counter("footprint_errors").inc()
            return None
        bad = footprint.violations_for(kind)
        if bad:
            self.metrics.counter("footprint_violations").inc(len(bad))
            if footprint.mode() == "strict":
                from gatekeeper_tpu.analysis.diagnostics import Diagnostic
                from gatekeeper_tpu.errors import VetError
                raise VetError([Diagnostic(code="footprint_violation",
                                           severity="error",
                                           message=v.format())
                                for v in bad])
            return None
        if not fp.row_local:
            self.metrics.counter("footprint_cross_row").inc()
        return fp

    def _shardplan_lowered(self, kind: str, compiled: CompiledTemplate):
        """Stage-6 partition-plan certification (analysis/shardplan.py)
        behind GATEKEEPER_SHARDPLAN=off|warn|strict.  Unlike the other
        stages this one NEVER fails an install: a missing/invalid plan
        only pins the kind to the replicated path (sharding is a
        performance contract, not a semantic one — the replicated path
        is always correct).  strict: the plan is executed on a 2-shard
        simulated mesh at install; any divergence is recorded and the
        kind pins replicated.  Ineligible plans (cross-row templates)
        ARE returned — the sweep reads plan.eligible."""
        from gatekeeper_tpu.analysis import shardplan
        if shardplan.mode() == "off":
            return None
        try:
            plan = shardplan.certify(kind, compiled, compiled.vectorized)
        except Exception as e:   # noqa: BLE001 — analysis must not take
            # template install down with it; no plan just means the
            # kind stays on the replicated path
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "shardplan analysis errored", kind=kind, err=str(e))
            self.metrics.counter("shardplan_errors").inc()
            return None
        bad = shardplan.violations_for(kind)
        if bad:
            self.metrics.counter("shardplan_violations").inc(len(bad))
            from gatekeeper_tpu.utils.log import logger
            for v in bad:
                logger("engine.jax_driver").warning(
                    "shardplan invalid; kind pinned to replicated path",
                    kind=kind, note=v.note)
            return None
        if not plan.eligible:
            self.metrics.counter("shardplan_ineligible").inc()
        return plan

    def _compilesurface_lowered(self, kind: str,
                                compiled: CompiledTemplate):
        """Stage-7 compile-surface certification
        (analysis/compilesurface.py) behind
        GATEKEEPER_COMPILE_SURFACE=off|warn|strict.  Like stage 6 this
        NEVER fails an install: an unbounded (or errored) surface only
        excludes the kind from AOT precompilation and retrace gating —
        it keeps serving through the lazy-recompile path, which is
        always correct."""
        from gatekeeper_tpu.analysis import compilesurface
        if compilesurface.mode() == "off":
            return None
        if compiled.vectorized is None:
            return compilesurface.scalar_surface(kind)
        try:
            cert = compilesurface.certify(kind, compiled,
                                          compiled.vectorized)
        except Exception as e:   # noqa: BLE001 — analysis must not take
            # template install down with it; no certificate just means
            # no AOT prewarm or retrace gating for this kind
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "compile-surface analysis errored", kind=kind,
                err=str(e))
            self.metrics.counter("compilesurface_errors").inc()
            return None
        if not cert.bounded:
            self.metrics.counter("compile_surface_unbounded").inc()
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "compile surface unbounded; kind excluded from AOT "
                "precompile and retrace gating", kind=kind,
                reason=cert.reason)
        return cert

    def _memsurface_lowered(self, kind: str, compiled: CompiledTemplate):
        """Stage-8 memory-surface certification (analysis/memsurface.py)
        behind GATEKEEPER_HBM_BUDGET=off|warn|strict.  warn (default):
        certify the conservative peak-HBM bytes and count budget
        breaches but serve anyway; strict: a template whose
        worst-signature peak exceeds GATEKEEPER_HBM_BUDGET_BYTES fails
        the install with ``hbm_budget_exceeded`` — the reconciler
        expands the VetError into status.byPod[].errors."""
        from gatekeeper_tpu.analysis import memsurface
        if memsurface.mode() == "off":
            return None
        if compiled.vectorized is None:
            return memsurface.scalar_surface(kind)
        try:
            cert = memsurface.certify(kind, compiled, compiled.vectorized)
        except Exception as e:   # noqa: BLE001 — analysis must not take
            # template install down with it; no certificate just means
            # no budget gating or residency planning for this kind
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "memory-surface analysis errored", kind=kind, err=str(e))
            self.metrics.counter("memsurface_errors").inc()
            return None
        reason = memsurface.budget_reason(cert)
        if reason is not None:
            self.metrics.counter("hbm_budget_exceeded").inc()
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "memory surface exceeds HBM budget", kind=kind,
                reason=reason)
            if memsurface.mode() == "strict":
                from gatekeeper_tpu.analysis.diagnostics import Diagnostic
                from gatekeeper_tpu.errors import VetError
                raise VetError([Diagnostic(code="hbm_budget_exceeded",
                                           severity="error",
                                           message=f"{kind}: {reason}")])
        return cert

    def _surface_guard(self, program, arrays,
                       delta_k: int | None = None) -> bool:
        """Executor cache-miss hook: True when the dispatch signature
        is inside the installed certificate (or the program is
        unguarded).  An uncertified signature is counted and
        flight-recorded; the executor decides refusal (strict) vs the
        lazy-recompile fallback (warn)."""
        from gatekeeper_tpu.analysis import compilesurface
        try:
            ok = compilesurface.dispatch_certified(program, arrays,
                                                   delta_k=delta_k)
        except Exception:   # noqa: BLE001 — the sentinel must never
            return True     # take a legitimate dispatch down
        if ok:
            return True
        compilesurface.uncertified_total += 1
        self.metrics.counter("retrace_uncertified_total").inc()
        try:
            from gatekeeper_tpu.obs.flightrecorder import record_event
            record_event(
                "retrace_uncertified",
                shapes={nm: tuple(int(d) for d in arrays[nm].shape)
                        for nm in sorted(arrays)},
                delta_k=delta_k, mode=compilesurface.mode())
        except Exception:   # noqa: BLE001
            pass
        return False

    def _certify_lowered(self, kind: str, compiled: CompiledTemplate):
        """Stage-4 translation validation (analysis/transval.py) behind
        GATEKEEPER_TRANSVAL=off|warn|strict.  strict: a counterexample
        pins the template to the scalar oracle exactly like CannotLower
        (and the reconciler surfaces `translation_unvalidated`); warn:
        log and serve on device anyway.  Certificates are memoized
        in-process and through the cert snapshot tier, so warm restarts
        run zero validations."""
        from gatekeeper_tpu.analysis import transval
        tv_mode = transval.mode()
        if tv_mode not in ("warn", "strict"):
            return compiled.vectorized
        lowered = transval.maybe_miscompiled(kind, compiled.vectorized)
        try:
            result = transval.certify(kind, compiled, lowered)
        except Exception as e:   # noqa: BLE001 — validation must not
            # take template install down with it; an inconclusive run
            # certifies nothing, so strict mode still pins
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "translation validation errored", kind=kind, err=str(e))
            self.metrics.counter("transval_errors").inc()
            return None if tv_mode == "strict" else compiled.vectorized
        if isinstance(result, transval.Certificate):
            self.metrics.counter("transval_certified").inc()
            return compiled.vectorized
        self.metrics.counter("transval_counterexamples").inc()
        from gatekeeper_tpu.utils.log import logger
        logger("engine.jax_driver").warning(
            "translation validation found a counterexample",
            kind=kind, note=result.note, expected=result.expected,
            actual=result.actual, mode=tv_mode)
        if tv_mode == "strict":
            return None   # scalar pin — identical to CannotLower
        return compiled.vectorized

    @staticmethod
    def _verify_lowered(kind: str, lowered):
        """Stage-2 IR verification (analysis/ir_verifier.py) on every
        program before it can reach jit.  Structural checks only — the
        engine has no provider registry in scope.  A malformed program
        falls back to the scalar oracle (identical semantics, no device
        path) unless GATEKEEPER_IR_VERIFY=strict, which raises instead;
        GATEKEEPER_IR_VERIFY=off skips the pass."""
        import os
        mode = os.environ.get("GATEKEEPER_IR_VERIFY", "fallback")
        if mode == "off":
            return lowered
        from gatekeeper_tpu.analysis import verify_program
        from gatekeeper_tpu.analysis.diagnostics import format_all
        diags = verify_program(lowered, providers=None, file=kind)
        if not diags:
            return lowered
        if mode == "strict":
            from gatekeeper_tpu.errors import VetError
            raise VetError(diags)
        import logging
        logging.getLogger(__name__).warning(
            "IR verification failed for %s; falling back to the scalar "
            "oracle:\n%s", kind, format_all(diags))
        return None

    @locked
    def delete_template(self, target: str, kind: str) -> None:
        super().delete_template(target, kind)
        st = self._state(target)
        st.bump(kind)

    @locked
    def put_constraint(self, target: str, kind: str, name: str, constraint: dict) -> None:
        self._footprint_constraint(target, kind, constraint)
        super().put_constraint(target, kind, name, constraint)
        self._state(target).bump(kind)

    def _footprint_constraint(self, target: str, kind: str,
                              constraint: dict) -> None:
        """Strict-mode footprint re-validation at constraint install.

        The footprint claims to cover EVERY constraint of the kind, but
        install order puts templates before constraints, so the
        template-install validation ran against the empty default
        parameter document — under which many templates never fire and
        the perturbation check is vacuous.  The first real parameter
        document is a new operating point: re-certify against it (the
        memo/snapshot make the honest case free) and reject the
        constraint if the claimed read-set fails — a wrong read-set
        would make selective sweeps skip real re-evaluations."""
        from gatekeeper_tpu.analysis import footprint
        if footprint.mode() != "strict":
            return
        st = self._state(target)
        if not isinstance(st, JaxTargetState):
            return
        compiled = st.templates.get(kind)
        if compiled is None or compiled.vectorized is None:
            return
        try:
            fp = footprint.certify(kind, compiled, compiled.vectorized,
                                   constraints=[constraint])
        except Exception as e:   # noqa: BLE001 — analysis failure only
            # disables selective reuse for the kind, never the install
            from gatekeeper_tpu.utils.log import logger
            logger("engine.jax_driver").warning(
                "footprint re-validation errored", kind=kind, err=str(e))
            self.metrics.counter("footprint_errors").inc()
            st.footprints.pop(kind, None)
            st.sweep_cache.pop(kind, None)
            return
        bad = footprint.violations_for(kind)
        if bad:
            self.metrics.counter("footprint_violations").inc(len(bad))
            st.footprints.pop(kind, None)
            st.sweep_cache.pop(kind, None)
            from gatekeeper_tpu.analysis.diagnostics import Diagnostic
            from gatekeeper_tpu.errors import VetError
            raise VetError([Diagnostic(code="footprint_violation",
                                       severity="error",
                                       message=v.format())
                            for v in bad])
        st.footprints[kind] = fp

    @locked
    def delete_constraint(self, target: str, kind: str, name: str) -> None:
        super().delete_constraint(target, kind, name)
        self._state(target).bump(kind)

    # ------------------------------------------------------------------

    def _match_engine(self, st: JaxTargetState, target: str):
        if st.match_engine is None:
            st.match_engine = self.targets[target].make_match_engine(st.table)
        return st.match_engine

    def _kind_constraints(self, st: TargetState, kind: str) -> list[dict]:
        return [st.constraints[kind][n] for n in sorted(st.constraints.get(kind, {}))]

    def _kind_mask(self, st: JaxTargetState, target: str, kind: str,
                   constraints: list[dict]):
        """(mask [C, n_rows] view, dirty rows | None, padded).  The mask
        is kept in its padded [c_pad, r_pad] form (the device layout) and
        delta-maintained under churn: one copy + dirty-column writes per
        generation instead of full re-matching + re-padding.  Delta is
        bypassed when a Namespace object changed (namespaceSelector
        results of unchanged rows may shift, table.namespaces_dirty_since)
        or rows were remapped."""
        from gatekeeper_tpu.ir.prep import audit_pads
        from gatekeeper_tpu.store.table import delta_worthwhile
        engine = self._match_engine(st, target)
        if engine is None:
            return None, None, None
        if all(not (c.get("spec") or {}).get("match") for c in constraints):
            # no constraint carries match criteria: every alive resource
            # matches (kinds default to wildcard, target.go:147-173).
            # TRIVIAL sentinel: the device gates on __alive__ alone and
            # no [C, R] mask is built or shipped — at 1M rows the mask
            # upload dominates cold start through a thin transport.
            return TRIVIAL_MATCH, None, None
        table = st.table
        gen, remap = table.generation, table.remap_generation
        conver = self.con_version_of(st, kind)
        n = table.n_rows
        n_con = len(constraints)
        r_pad, c_pad = audit_pads(n, n_con)
        hit = st.mask_cache.get(kind)
        if hit is not None and hit[0] == (gen, conver):
            padded = hit[2]
            return padded[:n_con, :n], None, padded
        if hit is not None and hit[1] == (conver, remap) \
                and hit[2].shape == (c_pad, r_pad):
            prev_gen = hit[0][0]
            old = hit[3]            # retired (gen, padded) or None
            # ping-pong: overwrite the retired buffer (two updates old)
            # at the rows dirty since ITS generation — O(|dirty|) writes
            # instead of an O(c_pad*r_pad) copy.  Requires (a) no
            # Namespace churn since the buffer's generation
            # (namespaceSelector results of untouched rows would be
            # stale in it) and (b) the buffer not being the one
            # currently installed in the bindings arrays (host/device
            # references must see immutable content).
            if old is not None and old[1].shape == (c_pad, r_pad) \
                    and old[1] is not hit[2] \
                    and old[1] is not st.installed_match.get(kind) \
                    and not table.namespaces_dirty_since(old[0]):
                target, since = old[1], min(old[0], prev_gen)
            elif not table.namespaces_dirty_since(prev_gen):
                target, since = None, prev_gen     # copy-on-write path
            else:
                target = since = -1                # full rebuild
            if since != -1:
                rows = table.dirty_rows_since(since)
                if delta_worthwhile(len(rows), n):
                    sub, rows = engine.mask_rows_since(constraints, since) \
                        if len(rows) else (None, rows)
                    if target is None:
                        target = hit[2].copy()
                    if len(rows):
                        # flat scatter: one 1-D fancy write beats the
                        # 2-D cross-product indexing at [C, 10k] scale
                        flat = (np.arange(n_con, dtype=np.int64)[:, None]
                                * target.shape[1] + rows[None, :]).ravel()
                        target.ravel()[flat] = sub.ravel()
                    base_rows = rows if since == prev_gen else \
                        table.dirty_rows_since(prev_gen)
                    st.mask_cache[kind] = ((gen, conver), (conver, remap),
                                           target, (prev_gen, hit[2]))
                    # the delta is only meaningful relative to hit[2]:
                    # the device-sync consumer must verify ITS base is
                    # that exact buffer (scalar-sweep interludes advance
                    # the mask without advancing the device)
                    return target[:n_con, :n], (hit[2], base_rows), target
        padded = np.zeros((c_pad, r_pad), dtype=bool)
        padded[:n_con, :n] = engine.mask(constraints)
        st.mask_cache[kind] = ((gen, conver), (conver, remap), padded, None)
        return padded[:n_con, :n], None, padded

    @staticmethod
    def _binding_delta_on() -> bool:
        """GATEKEEPER_BINDING_DELTA: the incremental update_bindings
        chain (O(dirty) host work + row-sized device scatters).  ``off``
        rebuilds bindings whole on every store generation — the
        bit-identical oracle for the delta chain, and the re-stage
        comparator the devpages_churn bench measures H2D against."""
        import os
        return os.environ.get(
            "GATEKEEPER_BINDING_DELTA", "on").lower() not in ("off", "0")

    def _kind_bindings(self, st: JaxTargetState, kind: str,
                       compiled: CompiledTemplate, constraints: list[dict]):
        """Per-kind bindings with incremental churn updates.  Retired
        bindings (two updates old) are recycled as write buffers
        (ping-pong): the driver hands out only the newest bindings per
        kind and device arrays are immutable snapshots, so overwriting
        the retired generation's numpy buffers is safe — and it turns
        per-sweep full-array copies into O(|dirty|) writes."""
        from gatekeeper_tpu.ir.prep import update_bindings
        key = (st.table.generation, self.con_version_of(st, kind))
        hit = st.bindings_cache.get(kind)
        if hit is not None and hit[0] == key:
            return hit[1]
        if hit is not None and hit[0][1] == key[1] \
                and self._binding_delta_on():
            retired = st.bindings_retired.get(kind)
            recycle = retired[1] if retired is not None \
                and retired[0] == key[1] else None
            b = update_bindings(compiled.vectorized.spec, st.table,
                                constraints, hit[1], recycle=recycle)
            if b is not None:
                # carry the gate-source identities so unchanged gates
                # keep their device copies through the delta chain
                for attr in ("_match_src", "_rank_src"):
                    if attr in hit[1].__dict__:
                        b.__dict__[attr] = hit[1].__dict__[attr]
                self.metrics.counter("bindings_delta_updates").inc()
                st.bindings_retired[kind] = (key[1], hit[1])
                st.bindings_cache[kind] = (key, b)
                return b
        bindings = build_bindings(compiled.vectorized.spec, st.table, constraints)
        self.metrics.counter("bindings_full_builds").inc()
        st.bindings_retired.pop(kind, None)
        st.bindings_cache[kind] = (key, bindings)
        return bindings

    def _selective_reuse(self, st: JaxTargetState, kind: str,
                         compiled: CompiledTemplate,
                         constraints: list[dict], limit):
        """Footprint-driven selective invalidation: return the cached
        sweep entry + bindings when this kind's verdicts provably
        cannot have changed since they were captured — no dirty column
        path (store.table.dirty_paths_since) intersects the template's
        validated read-set (footprint object paths + the constraint
        match criteria paths), the key set / row ids / constraint set
        are unchanged, and the template is row-local with no external
        providers or inventory reads (their inputs live outside the
        table's column diff).  Caller holds ``_prep_lock``."""
        ent = st.sweep_cache.get(kind)
        if ent is None:
            return None
        fp = st.footprints.get(kind)
        if fp is None or not fp.row_local or fp.providers \
                or compiled.uses_inventory:
            return None
        table = st.table
        conver = self.con_version_of(st, kind)
        if ent["conver"] != conver or ent["limit"] != limit \
                or ent["kgen"] != table.key_generation \
                or ent["remap"] != table.remap_generation \
                or ent["n_rows"] != table.n_rows:
            return None
        if table.generation != ent["gen"]:
            if table.namespaces_dirty_since(ent["gen"]):
                return None
            changed = table.dirty_paths_since(ent["gen"])
            if changed is None:     # window predates the path log
                return None
            from gatekeeper_tpu.analysis.footprint import (MATCH_PATHS,
                                                           paths_intersect)
            read = set(fp.object_paths()) | set(MATCH_PATHS)
            for c in changed:
                if any(paths_intersect(c, r) for r in read):
                    return None
        hitb = st.bindings_cache.get(kind)
        if hitb is None or hitb[1] is not ent["bindings"]:
            return None
        # refresh the cache key to the current generation: the dirty
        # columns provably don't feed this kind, so its bindings are
        # already current.  Safe for later delta chains — a future
        # update_bindings derives its dirty window from the bindings'
        # own delta_state, not from this key.
        st.bindings_cache[kind] = ((table.generation, conver), hitb[1])
        ent["gen"] = table.generation
        self.metrics.counter("footprint_kind_sweeps_skipped").inc()
        return ent, hitb[1]

    def _capture_sweep(self, st: JaxTargetState, kind: str,
                       compiled: CompiledTemplate, mode: str, spec,
                       payload, limit) -> None:
        """Store one kind's resolved device payload + reuse guards so a
        later churn sweep whose dirty columns miss this kind's read-set
        can replay it (_selective_reuse).  Only row-local templates
        without provider/inventory reads are eligible — everything the
        payload depends on is then visible to the table's column
        diff."""
        fp = st.footprints.get(kind)
        if fp is None or not fp.row_local or fp.providers \
                or compiled.uses_inventory:
            return
        parts = payload if isinstance(payload, tuple) else (payload,)
        try:
            nbytes = sum(int(getattr(a, "nbytes", 0)) for a in parts)
        except Exception:   # noqa: BLE001 — exotic payload: don't cache
            return
        if nbytes > SWEEP_CACHE_MAX_BYTES:
            return
        table = st.table
        with self._prep_lock:
            st.sweep_cache[kind] = {
                "mode": mode, "payload": payload, "prog": spec[4],
                "bindings": spec[5], "mask": spec[6],
                "gen": table.generation, "kgen": table.key_generation,
                "remap": table.remap_generation, "n_rows": table.n_rows,
                "conver": self.con_version_of(st, kind), "limit": limit,
            }

    def _devpages_active(self, compiled: CompiledTemplate) -> bool:
        """Device-resident pages are usable for this template right
        now: GATEKEEPER_DEVPAGES on, a lowered program to evaluate, and
        a live device backend (scalar-only degradation keeps every kind
        on the host-paged oracle)."""
        from gatekeeper_tpu.enforce.devpages import devpages_mode
        return devpages_mode() and compiled.vectorized is not None \
            and not self.scalar_only

    @staticmethod
    def _inv_join_only(fp, compiled: CompiledTemplate) -> bool:
        """True when a template is cross-row SOLELY through lowered
        inventory joins (spec.inv_joins) — the one cross-row shape the
        devpages delta kernel evaluates in-jit (_inv_join_mask), which
        is what makes e.g. K8sUniqueIngressHost page-eligible.  Any
        other cross-row reason (or an inventory read the lowering did
        not capture as a join) keeps the kind ineligible."""
        if compiled.vectorized is None:
            return False
        if not getattr(compiled.vectorized.spec, "inv_joins", ()):
            return False
        reasons = tuple(getattr(fp, "cross_row_reasons", ()) or ())
        return bool(reasons) and all(
            r.startswith("inventory join") for r in reasons)

    def _pages_ineligible(self, st: JaxTargetState, kind: str,
                          compiled: CompiledTemplate) -> str | None:
        """None when the kind can serve from the VerdictLedger, else
        the fallback reason.  Same gates as footprint selective reuse:
        only a row-local template with no provider/inventory reads has
        verdicts that per-page re-evaluation can maintain exactly.
        Under GATEKEEPER_DEVPAGES one relaxation: a kind whose only
        cross-row dependency is a lowered inventory join is admitted —
        the in-jit join sees the whole table every delta sweep, so page
        locality is not assumed (and on devpages fallback such a kind
        takes a full rebuild, never the host page loop)."""
        if compiled.vectorized is None:
            return "scalar-pin"
        fp = st.footprints.get(kind)
        if fp is None:
            return "no-footprint"
        dev_ij = self._devpages_active(compiled) \
            and self._inv_join_only(fp, compiled)
        if not fp.row_local and not dev_ij:
            return "cross-row"
        if fp.providers:
            return "external-providers"
        if compiled.uses_inventory and not dev_ij:
            return "inventory-read"
        return None

    @staticmethod
    def _observable_kinds(compiled: CompiledTemplate,
                          constraints: list[dict]) -> frozenset | None:
        """Resource kinds whose churn can change this template kind's
        verdicts: the union of every constraint's ``spec.match.kinds``
        plus the kinds its inventory joins read.  None = wildcard
        (some constraint matches every kind — cannot scope).  Drives
        the per-kind widen scoping: a dirty-log widen marker whose
        churned-kind union is disjoint from this set is skippable."""
        out: set[str] = set()
        if compiled.vectorized is not None:
            for ij in getattr(compiled.vectorized.spec, "inv_joins", ()):
                out.add(ij.kind)
        for c in constraints:
            match = (c.get("spec") or {}).get("match") or {}
            kl = match.get("kinds")
            if not isinstance(kl, list):
                return None         # absent/malformed kinds: wildcard
            for ks in kl:
                knames = (ks or {}).get("kinds") or []
                if "*" in knames:
                    return None
                out.update(k for k in knames if isinstance(k, str))
        return frozenset(out)

    def _devpages_reject(self, dv: dict, kind: str, reason: str) -> None:
        """Record one kind falling back from the device-resident path
        (stats + flight recorder + labeled counter)."""
        dv["kinds_fallback"] += 1
        dv["fallback_reasons"][kind] = reason
        self.metrics.counter("devpages_fallbacks", kind=kind).inc()
        from gatekeeper_tpu.obs.flightrecorder import record_event
        record_event("devpages_fallback", kind=kind, reason=reason)

    def _devpaged_kind(self, st, target, handler, compiled, constraints,
                       kind, led, ent, conver, rcache, pg, dv,
                       refresh_only: bool = False) -> bool:
        """One kind's sweep on the device-resident paged store.

        The kind's columns stay resident as fixed-geometry page arrays
        (the bindings delta chain scatters row-sized records to dirty
        slots — veval._scatter_rows), inventory-join input records ride
        the same discipline, and ONE jitted call (eval_mask_delta)
        computes the violation mask, gathers it through the on-device
        page table and returns the compact appear/clear delta stream
        against the previous resident mask.  Consumption preserves the
        ledger's exact-event contract:

          * dirty rows with any candidate bit, and every ``+`` delta
            row, re-confirm through the exact scalar path
            (_ledger_apply_row) — messages stay oracle-identical;
          * dirty rows with NO candidate bit are direct full-row clears
            (mask bit 0 = definitely no violation — sound by the
            over-approximation contract);
          * ``-`` deltas on non-dirty rows (cross-row inventory-join
            flips) drop just that constraint's verdicts — same
            identity, so no phantom clear+appear pair.

        The resident mask deliberately excludes ``__match__``: every
        match input is row-local (a flip dirties its own row, which the
        confirm covers; namespaceSelector churn rebuilt upstream), so
        the [C, R] match matrix never rides H2D on churn.

        Returns True when the ledger was brought current (or, with
        ``refresh_only``, the resident state rebuilt after a host full
        build); False = caller falls back.  Raising is also a fallback
        — the caller drops the kind's device state and recovers."""
        from gatekeeper_tpu.enforce import devpages as _dvp
        import jax.numpy as jnp
        table = st.table
        ex = self.executor
        kp = st.devpages.get(kind)
        if kp is None:
            kp = _dvp.KindPages(kind=kind)
            if st.devpages_geom:
                geom = st.devpages_geom.pop(kind, None)
                if isinstance(geom, dict) and kp.adopt_geometry(geom):
                    dv["geometry_adopted"] += 1
            st.devpages[kind] = kp
        h2d0 = ex.h2d_bytes
        sc0, sr0 = ex.h2d_scatter_bytes, ex.h2d_scatter_rows
        try:
            bindings = self._kind_bindings(st, kind, compiled, constraints)
        except ExternalDataError:
            self._devpages_reject(dv, kind, "external-data-failure")
            return False
        if bindings.f32_unsafe:
            self._devpages_reject(dv, kind, "f32-unsafe")
            return False
        r_pad, c_pad = bindings.r_pad, bindings.c_pad
        # inventory-join device input records (r:ij.<join>.*): cold
        # upload once, then row-sized scatters of just the changed
        # entries — rebound per update, never mutated in place
        spec = compiled.vectorized.spec
        ij_specs_raw = tuple(getattr(spec, "inv_joins", ()))
        ij_dev: dict = {}
        for req in ij_specs_raw:
            host = _dvp.build_inv_join_inputs(req, table, r_pad)
            for nm, arr in host.items():
                prev_h = kp.ij_host.get(nm)
                prev_d = kp.ij_dev.get(nm)
                if prev_h is None or prev_d is None \
                        or prev_h.shape != arr.shape:
                    dev = ex._put(nm, arr, False)
                elif np.array_equal(prev_h, arr):
                    dev = prev_d
                else:
                    changed = np.nonzero(prev_h != arr)[0]
                    from gatekeeper_tpu.analysis.costmodel import \
                        scatter_worthwhile
                    if scatter_worthwhile(len(changed), arr.shape[0]):
                        dev = ex._scatter_rows(nm, prev_d, arr, changed,
                                               False)
                    else:
                        dev = ex._put(nm, arr, False)
                kp.ij_host = {**kp.ij_host, nm: arr}
                kp.ij_dev = {**kp.ij_dev, nm: dev}
                ij_dev[nm] = dev
        if ij_specs_raw:
            dv["inv_joins_device"] += len(ij_specs_raw)
        # on-device page table: row -> slot indirection ([r_pad] int32,
        # identity while row ids are stable); rebuilt — rebound, not
        # mutated — on remap or slot-capacity change
        if kp.page_table is None or kp.slots != r_pad \
                or kp.remap != table.remap_generation:
            kp.page_table = ex._put(
                "__pagetable__", np.arange(r_pad, dtype=np.int32), False)
            kp.slots = r_pad
            kp.page_rows = table.page_rows
            kp.n_pages = table.n_pages
        kp.free = tuple(table.free_slots())
        # Stage-8 residency planning: under a devpages budget whose
        # certified claim the full resident mask exceeds, the mask
        # lives split across a hot device slot buffer and a host spill
        # mirror (enforce/devpages.ResidencyPlanner) and is
        # reconstructed bit-identically here before the delta sweep
        planner = kp.resident
        budget = _dvp.residency_budget_bytes()
        if budget is None:
            planner = kp.resident = None
        elif planner is None \
                or not planner.compatible(c_pad, r_pad, table.page_rows):
            planner = _dvp.ResidencyPlanner(
                budget, c_pad, r_pad, table.page_rows,
                cert=st.memsurfaces.get(kind))
            kp.resident = planner
        planner_holds = planner is not None \
            and planner.holds(c_pad, r_pad)
        _rs_sp0 = planner.spills if planner is not None else 0
        _rs_rs0 = planner.restores if planner is not None else 0
        have_mask = planner_holds or (
            kp.mask is not None
            and tuple(kp.mask.shape) == (c_pad, r_pad))
        mask_valid = (have_mask and kp.gen == ent.gen
                      and kp.remap == table.remap_generation
                      and kp.conver == conver
                      and kp.c_pad == c_pad and kp.slots == r_pad)
        if refresh_only or not mask_valid:
            # allocs-ok: cold rebuild after geometry/generation change
            old_mask = jnp.zeros((c_pad, r_pad), dtype=bool)
        elif planner_holds:
            old_mask = planner.expand(ex)
        else:
            old_mask = kp.mask
        ij_sig = tuple((req.name, bool(req.exclude_same_name))
                       for req in ij_specs_raw)
        k = max(kp.k, _dvp.DELTA_K_MIN)
        dirty = table.dirty_rows_since(ent.gen) \
            if not refresh_only else np.empty((0,), dtype=np.int64)
        new_mask, idx, signs, count, row_any = ex.eval_mask_delta(
            compiled.vectorized.program, bindings, None, old_mask,
            kp.page_table, k, ij_sig, ij_dev)
        if count > k and not refresh_only:
            # compact stream overflowed the compiled width: one
            # recompile at the next bucket, then re-dispatch
            dv["delta_overflows"] += 1
            k = _dvp.delta_bucket(count) * _dvp.DELTA_K_LADDER
            if k > (c_pad * r_pad):
                k = c_pad * r_pad
            kp.k = k
            new_mask, idx, signs, count, row_any = ex.eval_mask_delta(
                compiled.vectorized.program, bindings, None, old_mask,
                kp.page_table, k, ij_sig, ij_dev)
            if count > k:
                self._devpages_reject(dv, kind, "delta-overflow")
                return False
        if not refresh_only:
            n_rows = table.n_rows
            cnames = [(c.get("metadata") or {}).get("name", "")
                      for c in constraints]
            valid = int(min(count, k))
            plus_rows: set[int] = set()
            plus_bits: set[tuple[int, int]] = set()
            minus_by_row: dict[int, list[str]] = {}
            for i in range(valid):
                flat = int(idx[i])
                if flat < 0:
                    continue
                ci, row = flat // r_pad, flat % r_pad
                if ci >= len(cnames) or row >= n_rows:
                    continue    # padded constraint/row space
                if bool(signs[i]):
                    plus_rows.add(row)
                    plus_bits.add((ci, row))
                else:
                    minus_by_row.setdefault(row, []).append(cnames[ci])
            dirty_set = set(int(r) for r in dirty)
            confirm = {r for r in dirty_set if bool(row_any[r])} \
                | plus_rows
            n_evals = 0
            involved = sorted(dirty_set | plus_rows | set(minus_by_row))
            for row in involved:
                if row in confirm:
                    n_evals += self._ledger_apply_row(
                        st, target, handler, compiled, constraints,
                        kind, led, rcache, row, pg)
                elif row in dirty_set:
                    # no candidate bit anywhere on a dirty row: the
                    # device proved no constraint can violate — direct
                    # full-row clear, no scalar eval
                    meta = table.meta_at(row)
                    ident = () if meta is None \
                        else (meta.namespace, meta.name)
                    pg["events"] += len(led.set_row(kind, row, ident, {}))
                    dv["direct_clears"] += 1
                else:
                    # '-' delta on a clean row: that constraint's bit
                    # went definitely-no-violation — drop exactly its
                    # verdicts, same identity (no clear+appear pair)
                    old = ent.rows.get(row)
                    if old is None:
                        continue
                    ident, by_c = old
                    drop = set(minus_by_row[row])
                    new_by_c = {cn: rs for cn, rs in by_c.items()
                                if cn not in drop}
                    if len(new_by_c) != len(by_c):
                        pg["events"] += len(
                            led.set_row(kind, row, ident, new_by_c))
                        dv["direct_clears"] += 1
            if not mask_valid and ent.rows:
                # reconcile sweep (restart/resize/toggle): the previous
                # resident mask is unknown, so '-' deltas don't exist —
                # prune stale ledger verdicts by the new mask's bits
                # instead (vs zeros, every 1-bit is in the '+' stream)
                cset = set(cnames)
                cidx = {cn: i for i, cn in enumerate(cnames)}
                for row, (ident, by_c) in list(ent.rows.items()):
                    if row in confirm or row in dirty_set:
                        continue
                    new_by_c = {cn: rs for cn, rs in by_c.items()
                                if cn in cset
                                and (cidx[cn], row) in plus_bits}
                    if len(new_by_c) != len(by_c):
                        pg["events"] += len(
                            led.set_row(kind, row, ident, new_by_c))
                        dv["direct_clears"] += 1
            dv["delta_events"] += int(count)
            dv["scatter_rows"] += ex.h2d_scatter_rows - sr0
            dv["rows_confirmed"] += len(confirm)
            pg["rows_reevaluated"] += len(confirm)
            pg["evaluations_saved"] += \
                max(0, n_rows - len(confirm)) * len(constraints)
            pg["pages_skipped"] += max(
                0, table.n_pages
                - len({r // table.page_rows for r in involved}))
        else:
            dv["mask_builds"] += 1
        if planner is not None and planner.active:
            # LRU bump the pages this sweep actually touched, then
            # split the fresh mask across the slot buffer and the
            # host spill mirror — the full-size device array is
            # released (the certified resident claim is what stays)
            if not refresh_only:
                planner.touch({r // planner.page_rows
                               for r in involved})
            planner.store(new_mask)
            kp.mask = None
            dv["resident_spills"] += planner.spills - _rs_sp0
            dv["resident_restores"] += planner.restores - _rs_rs0
            dv["resident_pages_device"] += len(planner.slot_of)
        else:
            kp.mask = new_mask
        kp.gen = table.generation
        kp.remap = table.remap_generation
        kp.conver = conver
        kp.c_pad = c_pad
        kp.n_pages = table.n_pages
        kp.page_rows = table.page_rows
        dv["kinds_device"] += 1
        dv["h2d_bytes"] += (ex.h2d_bytes - h2d0) \
            + (ex.h2d_scatter_bytes - sc0)
        dv["h2d_scatter_bytes"] += ex.h2d_scatter_bytes - sc0
        return True

    def _paged_kind(self, st, target, handler, compiled, constraints,
                    ordered_rows, row_order, kind, limit, tagged, rcache,
                    pg, dirty_pages_out, dv=None) -> None:
        """Serve one kind from the VerdictLedger, first applying the
        deltas for every page dirtied since the entry's generation.
        Rows re-evaluate through the exact scalar path (match + oracle
        + fmt memo), so the ledger holds exactly the confirmed
        violating rows; capped output walks them in rank order —
        bit-identical to the full path's top-k + refill emission."""
        from gatekeeper_tpu.analysis.footprint import (MATCH_PATHS,
                                                       paths_intersect)
        from gatekeeper_tpu.enforce.ledger import (VerdictLedger,
                                                   constraints_digest)
        table = st.table
        if st.ledger is None:
            st.ledger = VerdictLedger(target)
        led = st.ledger
        ent = led.entry(kind)
        conver = self.con_version_of(st, kind)
        condigest = constraints_digest(constraints)
        if ent.gen < 0 and st.ledger_restored:
            # warm restart: adopt the snapshot's verdicts when the
            # constraint set (by content) and row space still match the
            # restored table — a hit means zero cold full builds
            payload = st.ledger_restored.pop(kind, None)
            if payload is not None and led.adopt(kind, payload, condigest,
                                                 table, conver):
                ent = led.entry(kind)
                geom = payload.get("devpages") \
                    if isinstance(payload, dict) else None
                if isinstance(geom, dict):
                    if dv is not None:
                        # adopt the device-pagemap geometry now: a
                        # clean warm restart may have nothing dirty, so
                        # the first devpages sweep (which would pop a
                        # stash) can be arbitrarily far away
                        from gatekeeper_tpu.enforce import \
                            devpages as _dvp_mod
                        kp = st.devpages.get(kind)
                        if kp is None:
                            kp = _dvp_mod.KindPages(kind=kind)
                            st.devpages[kind] = kp
                        if kp.adopt_geometry(geom):
                            dv["geometry_adopted"] += 1
                    else:
                        # devpages off this sweep: stash for the first
                        # devpages sweep to adopt instead of deriving
                        # the paged layout cold
                        if st.devpages_geom is None:
                            st.devpages_geom = {}
                        st.devpages_geom[kind] = geom
        rebuild = None
        if ent.gen < 0:
            rebuild = "cold"
        elif ent.conver != conver or ent.condigest != condigest:
            rebuild = "constraints-changed"
        elif ent.remap != table.remap_generation:
            rebuild = "rows-remapped"
        elif table.namespaces_dirty_since(ent.gen):
            # namespace label edits shift namespaceSelector matching of
            # OTHER rows — page locality doesn't hold
            rebuild = "namespace-churn"
        entries = None
        if rebuild is None and table.generation != ent.gen:
            entries = table.dirty_page_entries_since(ent.gen)
            if entries is None:
                # window predates the log floor: the dirty PAGES are
                # unattributable, but the row space itself is intact
                # (a shrink would have bumped remap_generation and been
                # caught above), so rebuild the kind page-by-page
                # through the normal delta path below — every page
                # re-evaluates, warming the review cache incrementally
                # and clearing dead rows via their own page's re-eval —
                # instead of one monolithic whole-kind build.  (A cap-
                # overflow widen no longer lands here: the log keeps a
                # paths=None marker carrying the dropped half's exact
                # page/kind unions, scoped per kind in the loop below.)
                pg["widen_fallbacks"] += 1
                self.metrics.counter("widen_fallbacks", kind=kind).inc()
                entries = [(table.generation, None,
                            frozenset(range(table.n_pages)), None)]
        dev_done = False
        if rebuild is None and entries and dv is not None \
                and self._devpages_active(compiled):
            # device-resident delta path: scatter-update the resident
            # columns, compute mask + delta in one jitted call, consume
            # the compact stream.  Falls back to the host page loop on
            # any failure — except for cross-row (inventory-join)
            # kinds, whose verdicts the page loop cannot maintain
            # (page locality is exactly what the device delta waived),
            # so those take one full rebuild instead.
            try:
                dev_done = self._devpaged_kind(
                    st, target, handler, compiled, constraints, kind,
                    led, ent, conver, rcache, pg, dv)
            except Exception as e:  # noqa: BLE001 — devpages is the
                st.devpages.pop(kind, None)         # gated experiment
                self._devpages_reject(dv, kind, f"error: {e!r}")
                dev_done = False
            if not dev_done:
                fp = st.footprints.get(kind)
                if fp is None or not fp.row_local:
                    rebuild = "devpages-fallback"
        n_evals = 0
        if dev_done:
            pass        # ledger brought current on the device path
        elif rebuild is not None:
            # full build: clear rows that died since (sorted — the
            # canonical event order puts dead-row clears first), then
            # every live row in rank order
            for row in sorted(ent.rows):
                if row >= table.n_rows or table.meta_at(row) is None:
                    pg["events"] += len(led.set_row(kind, row, (), {}))
            for row in ordered_rows:
                n_evals += self._ledger_apply_row(
                    st, target, handler, compiled, constraints, kind, led,
                    rcache, row, pg)
            ent.full_builds += 1
            pg["full_builds"] += 1
            pg["pages_evaluated"] += table.n_pages
            pg["rows_reevaluated"] += len(ordered_rows)
            if dv is not None and self._devpages_active(compiled):
                # refresh the device-resident mask after a host full
                # build so the NEXT sweep deltas instead of reconciling
                try:
                    self._devpaged_kind(
                        st, target, handler, compiled, constraints,
                        kind, led, ent, conver, rcache, pg, dv,
                        refresh_only=True)
                except Exception:   # noqa: BLE001 — refresh is advisory
                    st.devpages.pop(kind, None)
        elif entries:
            fp = st.footprints[kind]
            read = set(fp.object_paths()) | set(MATCH_PATHS)
            obs_kinds = self._observable_kinds(compiled, constraints)
            kgen_changed = ent.kgen != table.key_generation
            pages: set[int] = set()
            for _g, paths, pgs, ekinds in entries:
                if paths is None:
                    # cap-overflow widen marker: its paths are
                    # unattributable (treat as every path), but its
                    # resource-kind union is exact — a template whose
                    # observable kinds (match criteria + inventory
                    # joins) are disjoint skips the dropped half
                    # outright instead of re-evaluating its pages
                    if ekinds is not None and obs_kinds is not None \
                            and not (obs_kinds & ekinds):
                        continue
                    pg["widen_fallbacks"] += 1
                    self.metrics.counter("widen_fallbacks",
                                         kind=kind).inc()
                    pages |= pgs
                    continue
                # page filtering by read-set intersection is only exact
                # for pure replaces: a bulk entry mixing inserts (empty
                # paths) with non-intersecting edits can't attribute
                # pages, so key-set churn includes every touched page
                if kgen_changed or not paths or any(
                        paths_intersect(p, r) for p in paths
                        for r in read):
                    pages |= pgs
            R = table.page_rows
            n_rows = table.n_rows
            rows_seen = 0
            for p in sorted(pages):
                start, end = p * R, (p + 1) * R
                if start >= n_rows:
                    continue    # stale page beyond the row space
                pg["rows_padded"] += max(0, end - n_rows)
                for row in range(start, min(end, n_rows)):
                    n_evals += self._ledger_apply_row(
                        st, target, handler, compiled, constraints, kind,
                        led, rcache, row, pg)
                    rows_seen += 1
            dirty_pages_out |= pages
            pg["pages_evaluated"] += len(pages)
            pg["pages_skipped"] += max(0, table.n_pages - len(pages))
            pg["rows_reevaluated"] += rows_seen
            pg["evaluations_saved"] += \
                max(0, len(ordered_rows) - rows_seen) * len(constraints)
        else:
            # generation unchanged (or every entry already applied):
            # the ledger is current — pure serve
            pg["pages_skipped"] += table.n_pages
            pg["evaluations_saved"] += len(ordered_rows) * len(constraints)
        ent.gen = table.generation
        ent.kgen = table.key_generation
        ent.remap = table.remap_generation
        ent.n_rows = table.n_rows
        ent.conver = conver
        ent.condigest = condigest
        if tagged is not None:
            # sweep caller: emit capped results.  The reactor passes
            # None — it maintains verdicts between sweeps; formatting
            # happens when the next audit serves from the ledger.
            self._ledger_serve(ent, constraints, row_order, kind, limit,
                               tagged)

    def _ledger_apply_row(self, st, target, handler, compiled, constraints,
                          kind, led, rcache, row, pg) -> int:
        """Re-evaluate one row against the kind's constraints through
        the exact scalar path and replace its ledger verdicts, emitting
        the delta events.  Returns evaluations performed."""
        table = st.table
        meta = table.meta_at(row)
        if meta is None:
            pg["events"] += len(led.set_row(kind, row, (), {}))
            return 0
        pair = self._row_review(st, handler, row, rcache)
        if pair is None:
            pg["events"] += len(led.set_row(kind, row, (), {}))
            return 0
        review, frozen, shared = pair
        by_c: dict[str, list] = {}
        n_evals = 0
        for c in constraints:
            if not any(True for _ in handler.matching_constraints(
                    review, [c], table)):
                continue
            n_evals += 1
            results = self._pair_results(st, target, kind, compiled, c,
                                         row, review, frozen, None, shared)
            if results:
                by_c[(c.get("metadata") or {}).get("name", "")] = results
        pg["events"] += len(led.set_row(kind, row,
                                        (meta.namespace, meta.name), by_c))
        return n_evals

    def _ledger_serve(self, ent, constraints, row_order, kind, limit,
                      tagged) -> None:
        """Emit capped results from the ledger's confirmed rows.  Rank
        order + whole-row emission with the cap checked at the top of
        the loop reproduces _format_topk/_scalar_kind exactly (top-k by
        rank plus full-mask refill IS "walk confirmed rows in rank
        order until the result count reaches the cap")."""
        for c in constraints:
            cname = (c.get("metadata") or {}).get("name", "")
            rows = [row for row, (_ident, by_c) in ent.rows.items()
                    if cname in by_c and row in row_order]
            rows.sort(key=row_order.__getitem__)
            emitted = 0
            for row in rows:
                if limit is not None and emitted >= limit:
                    break
                results = ent.rows[row][1][cname]
                for r in results:
                    # fresh copies: downstream sets .resource and owns
                    # result.metadata; the ledger's canon stays pristine
                    tagged.append(((row_order[row], kind, cname),
                                   dataclasses.replace(
                                       r, metadata=dict(r.metadata))))
                emitted += len(results)

    # ------------------------------------------------------------------
    # continuous-enforcement entry points (enforce/reactor.py)

    def react_kind(self, target: str,
                   kind: str | None = None) -> dict | None:
        """Rung 1 of the reactor's resync ladder: fold the store's
        dirty pages into the VerdictLedger for one kind (every eligible
        kind when None) with no sweep in between — the single-event →
        single-page re-eval path.  Serving is skipped (verdicts are
        *maintained*; the next audit formats from the updated ledger).
        Returns the paged accounting dict, or None when pages are off
        or nothing was eligible."""
        from gatekeeper_tpu.enforce.ledger import pages_mode
        if not pages_mode():
            return None
        st = self._state(target)
        if not isinstance(st, JaxTargetState):
            return None
        handler = self.targets[target]
        pg = {"pages_evaluated": 0, "pages_skipped": 0, "rows_padded": 0,
              "rows_reevaluated": 0, "evaluations_saved": 0,
              "widen_fallbacks": 0, "full_builds": 0, "events": 0}
        dirty: set[int] = set()
        from gatekeeper_tpu.enforce.devpages import (
            devpages_mode as _dv_mode, fresh_stats as _dv_fresh)
        dv = _dv_fresh() if (_dv_mode() and not self.scalar_only) else None
        reacted = 0
        with self._prep_lock:
            ordered_rows, row_order = self._ensure_order(st)
            kinds = [kind] if kind is not None else sorted(st.templates)
            rcache: dict[int, tuple] = {}
            for k in kinds:
                compiled = st.templates.get(k)
                if compiled is None:
                    continue
                constraints = self._kind_constraints(st, k)
                if not constraints:
                    continue
                if self._pages_ineligible(st, k, compiled) is not None:
                    continue
                self._paged_kind(st, target, handler, compiled,
                                 constraints, ordered_rows, row_order, k,
                                 None, None, rcache, pg, dirty, dv)
                reacted += 1
        if reacted == 0:
            return None
        pg["kinds"] = reacted
        pg["dirty_pages"] = len(dirty)
        if dv is not None:
            pg["devpages"] = dv
        m = self.metrics
        m.counter("reactor_reacts_total").inc()
        if st.ledger is not None:
            m.gauge("ledger_violations").set(
                st.ledger.total_violations())
        return pg

    def resync_kind(self, target: str,
                    kind: str | None = None) -> dict | None:
        """Rungs 2/3: force a whole-kind rebuild that DIFF-APPLIES
        against the existing ledger rows — the entry is marked cold but
        keeps its verdicts, so a clean resync emits zero events and a
        divergent one emits exactly the true appear/clear delta, never
        a drop-and-replay phantom storm.  Pending snapshot adoptions
        for the kind are discarded: a resync exists precisely because
        adopted state is suspect."""
        from gatekeeper_tpu.enforce.ledger import pages_mode
        if not pages_mode():
            return None
        st = self._state(target)
        if not isinstance(st, JaxTargetState):
            return None
        with self._prep_lock:
            led = st.ledger
            kinds = [kind] if kind is not None else sorted(st.templates)
            for k in kinds:
                if st.ledger_restored:
                    st.ledger_restored.pop(k, None)
                if led is not None:
                    ent = led.entries.get(k)
                    if ent is not None:
                        ent.gen = -1
        # _prep_lock released: react_kind re-acquires it (plain Lock,
        # not reentrant)
        out = self.react_kind(target, kind)
        self.metrics.counter("reactor_resyncs_total").inc()
        return out

    @locked_read
    def devpages_report(self, target: str) -> dict:
        """Per-kind device-residency eligibility for ``probe --pages``:
        kind -> None (device-resident eligible) or the blocking
        reason.  Reflects the live gates — with GATEKEEPER_DEVPAGES
        off the cross-row relaxation is off too, so an inventory-join
        kind reports its host-path reason."""
        from gatekeeper_tpu.enforce.devpages import devpages_mode
        st = self._state(target)
        out: dict[str, str | None] = {}
        if not isinstance(st, JaxTargetState):
            return out
        on = devpages_mode()
        for kind in sorted(st.templates):
            compiled = st.templates[kind]
            reason = self._pages_ineligible(st, kind, compiled)
            if reason is None:
                if not on:
                    reason = "devpages-off"
                elif self.scalar_only:
                    reason = "scalar-only"
                elif compiled.vectorized is None:
                    reason = "not-vectorized"
            out[kind] = reason
        return out

    @locked_read
    def page_of_object(self, target: str, obj: Any) -> int | None:
        """Row page an event object lands in — the reactor's
        coalescing hint.  None when unhandled or not resident."""
        handler = self.targets.get(target)
        if handler is None:
            return None
        try:
            key, _meta, _doc = handler.process_data(obj)
        except Exception:   # noqa: BLE001 — unhandled/malformed event
            return None
        st = self._state(target)
        row = st.table.lookup(key)
        return None if row is None else st.table.page_of(row)

    @locked_read
    def kind_residents(self, target: str, api_version: str,
                       kind: str) -> list[str]:
        """Store keys of every resident row of (apiVersion, kind) — the
        deletion scan for a rung-2 relist (Client.sync_kind)."""
        st = self._state(target)
        table = st.table
        out: list[str] = []
        for key, row in list(table.rows_items()):
            meta = table.meta_at(row)
            if meta is not None and meta.kind == kind \
                    and meta.api_version == api_version:
                out.append(key)
        return out

    def ledger_rv(self, target: str, kind: str) -> int:
        """The kind's adopted/live RV watermark (0 = none recorded) —
        seeds the reactor's first-event staleness check on restart."""
        st = self._state(target)
        if not isinstance(st, JaxTargetState):
            return 0
        led = st.ledger
        if led is not None:
            ent = led.entries.get(kind)
            if ent is not None and ent.rv:
                return int(ent.rv)
        if st.ledger_restored:
            # resource-kind floors from the snapshot's watermark map
            # (reactor streams are keyed by resource kind; the ledger
            # entries below are keyed by constraint kind)
            wm = st.ledger_restored.get("__rv__")
            if isinstance(wm, dict) and kind in wm:
                try:
                    return int(wm[kind] or 0)
                except (TypeError, ValueError):
                    return 0
            payload = st.ledger_restored.get(kind)
            if isinstance(payload, dict):
                try:
                    return int(payload.get("rv", 0) or 0)
                except (TypeError, ValueError):
                    return 0
        return 0

    def _ensure_order(self, st):
        """Sorted-cache-key row order (matches the scalar driver) with
        its key_generation-keyed cache; pure updates never re-sort."""
        kgen = st.table.key_generation
        if st.order_cache is not None and st.order_cache[0] == kgen:
            _, ordered_rows, row_order = st.order_cache
            return ordered_rows, row_order
        items = list(st.table.rows_items())
        if len(items) > 65536:
            # numpy lexicographic sort of the key strings: ~4s of
            # Python tuple-sort at 1M rows becomes ~0.5s
            keys = np.array([k for k, _ in items])
            rows_arr = np.fromiter((r for _, r in items),
                                   dtype=np.int64, count=len(items))
            order = np.argsort(keys, kind="stable")
            ordered_np = rows_arr[order]
            ordered_rows = ordered_np.tolist()
            row_order = _RowOrder(ordered_np)
        else:
            ordered_rows = [row for _, row in sorted(items)]
            row_order = {row: i for i, row in enumerate(ordered_rows)}
        st.order_cache = (kgen, ordered_rows, row_order)
        return ordered_rows, row_order

    def _prefetch_axes(self, st) -> None:
        """Union-prefetch the element-axis extractions: kinds sharing
        an axis (spec.containers for most of the library) pay ONE
        full-table walk, not one per kind — per-kind build_bindings
        then slices the table's superset cache."""
        axis_union: dict[tuple, set] = {}
        for kind in st.templates:
            lowered = st.templates[kind].vectorized
            if lowered is None or not self._kind_constraints(st, kind):
                continue
            abase = dict(lowered.spec.axes)
            for axis, base in lowered.spec.axes:
                axis_union.setdefault(base, set())
            for ec in lowered.spec.e_cols:
                axis_union[abase[ec.axis]].add((ec.rel, ec.mode))
        for base, rels in axis_union.items():
            # only when nothing is cached yet (first build): on churn,
            # delta sweeps never need a full re-extraction (the dirty
            # rows re-extract inside update_bindings), and a full
            # rebuild's first elem_arrays call re-walks the union once
            # by itself (prefetch_elem_arrays carries coverage)
            if base not in st.table._elem_cache:
                st.table.prefetch_elem_arrays(base, sorted(rels))

    def _ext_specs(self, st) -> list[tuple[str, tuple[str, ...]]]:
        """(provider, review-column path) pairs across the target's
        lowered kinds — the sweep-level key-collection scan of the
        two-phase external-data design."""
        specs: list[tuple[str, tuple[str, ...]]] = []
        for kind in sorted(st.templates):
            lowered = st.templates[kind].vectorized
            if lowered is None or not self._kind_constraints(st, kind):
                continue
            for tr in lowered.spec.tables:
                if not tr.ext_providers or not tr.src.startswith("r:val:"):
                    # e-col-keyed lookups are rare; the build-time
                    # prefetch hook still batches them per table build
                    continue
                path = tuple(tr.src[len("r:val:"):].split("."))
                for provider in tr.ext_providers:
                    specs.append((provider, path))
        return specs

    def _prefetch_external(self, st) -> dict | None:
        """Bulk-warm every (provider, distinct key) pair the sweep's
        external-data tables will gather — one batched round per
        provider, overlapped with host prep (the caller submits this to
        the sweep pool; single-flight in the provider cache dedupes
        against the build-time hook racing it).  Returns stats for the
        audit report, or None when there is nothing to do."""
        from gatekeeper_tpu.externaldata.runtime import get_runtime
        rt = get_runtime()
        if rt is None:
            return None
        specs = self._ext_specs(st)
        if not specs:
            return None
        import time as _time
        from gatekeeper_tpu.ir.encode import decode_value
        from gatekeeper_tpu.store.columns import ColSpec
        t0 = _time.perf_counter()
        interner = st.table.interner
        by_provider: dict[str, dict] = {}
        for provider, path in specs:
            want = by_provider.setdefault(provider, {})
            ids = np.unique(st.table.column(ColSpec(path, "val")).ids)
            for uid in ids[ids >= 0].tolist():
                v = decode_value(interner.string(uid))
                if isinstance(v, str):
                    want[v] = True
        n_keys = 0
        for provider, want in by_provider.items():
            n_keys += len(want)
            if want:
                rt.prefetch(provider, list(want))
        return {"providers": len(by_provider), "keys": n_keys,
                "prefetch_s": round(_time.perf_counter() - t0, 6)}

    @staticmethod
    def _external_sweep_stats(ext_fut) -> dict | None:
        """Sweep-report payload: the overlapped bulk-warm's numbers plus
        every provider's breaker state / cache hit ratio / fetch
        timings.  None when no runtime or no provider is configured."""
        from gatekeeper_tpu.externaldata.runtime import get_runtime
        rt = get_runtime()
        if rt is None or not rt.provider_names():
            return None
        bulk = None
        if ext_fut is not None and ext_fut.done():
            try:
                bulk = ext_fut.result()
            except Exception:   # noqa: BLE001 — report-only path
                bulk = None
        out: dict = {"providers": rt.stats()}
        if bulk is not None:
            out["bulk_prefetch"] = bulk
        return out

    @locked_read
    def prefetch_external_for_reviews(self, target: str,
                                      reviews: list[dict]) -> None:
        """Batched external-data warm for an admission micro-batch: one
        fetch round per provider covering every key any review in the
        batch will look up.  Wired ahead of MicroBatcher evaluation so
        fetch latency is paid once per batch — including batches small
        enough to fall back to per-review scalar queries, which would
        otherwise fetch key-by-key."""
        from gatekeeper_tpu.externaldata.runtime import get_runtime
        rt = get_runtime()
        if rt is None:
            return
        st = self._state(target)
        if not isinstance(st, JaxTargetState):
            return
        specs = self._ext_specs(st)
        if not specs:
            return
        from gatekeeper_tpu.store.columns import iter_path
        by_provider: dict[str, dict] = {}
        for provider, path in specs:
            want = by_provider.setdefault(provider, {})
            for rv in reviews:
                obj = rv.get("object") if isinstance(rv, dict) else None
                if not isinstance(obj, dict):
                    continue
                for v in iter_path(obj, path):
                    if isinstance(v, str):
                        want[v] = True
        for provider, want in by_provider.items():
            if want:
                rt.prefetch(provider, list(want))

    @locked
    def put_data_batch(self, target: str, entries) -> None:
        # the parent method is itself @locked and the RW lock is not
        # reentrant — call its unwrapped body under OUR writer hold
        LocalDriver.put_data_batch.__wrapped__(self, target, entries)
        st = self._state(target)
        # keyed to the BATCH size, not the table size: a steady stream
        # of small watch batches on a large table must not re-run
        # mirror prep under the writer lock on every write
        if isinstance(st, JaxTargetState) \
                and len(entries) >= MIRROR_EAGER_MIN_ROWS:
            self._materialize_mirror(st)

    def _materialize_mirror(self, st) -> None:
        """Eagerly build what the first audit would otherwise build
        lazily: the shared element-axis extraction and each kind's
        bindings (a columnar store maintains its mirror on write — the
        reference's informer caches do the same on the watch path).
        Executable compiles/reloads are then kicked on a background
        thread: they release the GIL (compile-service RPC / tunnel
        executable load), so by the time the first sweep dispatches,
        its executables are compiled or in flight."""
        import time as _time
        _t0 = _time.perf_counter()
        self._prefetch_axes(st)
        warm: list[tuple] = []
        with self._prep_lock:
            for kind in sorted(st.templates):
                compiled = st.templates[kind]
                cons = self._kind_constraints(st, kind)
                if compiled.vectorized is None or not cons:
                    continue
                if self.scalar_only or \
                        st.table.n_rows * len(cons) < SMALL_WORKLOAD_EVALS:
                    continue
                bindings = self._kind_bindings(st, kind, compiled, cons)
                # mirror the dispatch-time gate set: kinds with match
                # criteria get a __match__ binding at _install_gates
                with_match = any((c.get("spec") or {}).get("match")
                                 for c in cons)
                warm.append((compiled.vectorized.program, bindings,
                             with_match))
        # the sorted row order + rank gate are table-derived too
        _, row_order = self._ensure_order(st)
        self._row_rank(st, row_order)
        self.metrics.timer("mirror_materialize").observe(
            _time.perf_counter() - _t0)
        if warm and self.executor.mesh is None:
            from gatekeeper_tpu.engine.veval import ProgramExecutor

            def _warm_one(prog, bindings, with_match):
                if self.executor._shutdown.is_set():
                    return
                try:
                    self.executor.prewarm_audit_exec(
                        prog, bindings, DEFAULT_PREWARM_CAP,
                        with_match=with_match)
                    # upload the binding arrays while the GIL is free —
                    # the first dispatch then reuses the per-bindings
                    # device cache instead of paying the tunnel
                    # transfer inside the sweep
                    self.executor._arrays(bindings, None, None)
                except Exception:
                    pass        # warmup is best-effort
            # a few worker threads over a shared queue: ONE sequential
            # warm thread serializes a 40-kind library's compiles in
            # front of the first audit's single-flight waits (measured
            # 87s library cold), while one-thread-per-kind thrashes the
            # GIL with 40 concurrent traces.  Compile requests overlap
            # ~1.4x through the serialized service; loads more.
            q = list(warm)
            qlock = __import__("threading").Lock()

            def _drain_q():
                while True:
                    with qlock:
                        if not q:
                            return
                        prog, bindings, with_match = q.pop(0)
                    _warm_one(prog, bindings, with_match)
            for _ in range(min(4, len(warm))):
                ProgramExecutor.spawn_bg(_drain_q, "ingest-prewarm")

    def _install_gates(self, st, kind: str, bindings,
                       mask: np.ndarray | None,
                       mask_delta: tuple | None,
                       rank: np.ndarray | None,
                       padded: np.ndarray | None = None) -> None:
        """Attach the padded match mask and rank as regular bindings
        arrays ("__match__", "__rank__") so they ride the same per-name
        device cache + scatter-update path as the columns (the executor
        then needs no separate match/rank plumbing, and the sharded
        path shards them by their declared axes).  `padded` is the
        mask's canonical padded form from _kind_mask, installed without
        any copy; `mask_delta` = (base_buffer, rows) states which buffer
        the dirty rows are relative to — a device scatter is recorded
        ONLY when the bindings' inherited __match__ IS that buffer
        (scalar-sweep interludes advance the mask cache without touching
        the device; syncing the wrong frame would under-approximate)."""
        # NOTE: bindings.arrays / base_dirty are REBOUND (never mutated
        # in place): concurrent readers (RWLock shares queries) may be
        # iterating the old dicts — racing installs produce identical
        # dicts and last-write-wins is benign, mid-iteration mutation
        # would not be.
        d = bindings.__dict__
        if mask is TRIVIAL_MATCH:
            if "__match__" in bindings.arrays:
                # constraints lost their match criteria: drop the stale
                # gate (alive-only gating is exact now)
                bindings.arrays = {k: v for k, v in bindings.arrays.items()
                                   if k != "__match__"}
                d.pop("_match_src", None)
            st.installed_match.pop(kind, None)
            mask = None
        if mask is not None and bindings.arrays.get("__match__") is not padded \
                and d.get("_match_src") is not mask:
            if padded is None or \
                    padded.shape != (bindings.c_pad, bindings.r_pad):
                padded = np.zeros((bindings.c_pad, bindings.r_pad),
                                  dtype=bool)
                padded[: mask.shape[0], : mask.shape[1]] = mask
            old = bindings.arrays.get("__match__")
            bindings.arrays = {**bindings.arrays, "__match__": padded}
            d["_match_src"] = mask
            st.installed_match[kind] = padded
            if bindings.base is not None and mask_delta is not None \
                    and old is not None and old is mask_delta[0] \
                    and old.shape == padded.shape:
                bindings.base_dirty = {**bindings.base_dirty,
                                       "__match__": mask_delta[1]}
        if rank is not None and d.get("_rank_src") is not rank:
            from gatekeeper_tpu.engine.veval import pad_rank
            bindings.arrays = {**bindings.arrays,
                               "__rank__": pad_rank(rank, bindings.r_pad)}
            d["_rank_src"] = rank

    def _audit_dedup_plan(self, st, target: str):
        """The cross-template predicate dedup plan for the currently
        installed set, or None.  Caller holds ``_prep_lock``.  The plan
        is a pure function of the installed set — it is memoized by the
        set digest (churn invalidates by key mismatch) and persisted to
        the warm-restart snapshot tier, so a restarted pod loads it
        instead of re-running the whole-policy-set analysis."""
        try:
            from gatekeeper_tpu.analysis.policyset import build_dedup_plan
            dkinds = {}
            for k in st.templates:
                cons = self._kind_constraints(st, k)
                if st.templates[k].vectorized is not None and cons:
                    dkinds[k] = (st.templates[k].vectorized, cons)
            if not dkinds:
                return None
            import json as _json
            from gatekeeper_tpu.resilience import snapshot as _snap
            parts = [
                f"{k}|"
                f"{_snap.template_digest(k, target, st.templates[k].source)}|"
                + _json.dumps(cons, sort_keys=True, default=str)
                for k, (_, cons) in dkinds.items()]
            pdigest = _snap.policyset_digest(parts)
            memo = self._dedup_plan_memo.get(target)
            if memo is not None and memo[0] == pdigest:
                return memo[1]
            hit = _snap.load_dedup_plan(pdigest)
            if hit is not None:
                plan = hit[0]
            else:
                plan = build_dedup_plan(dkinds)
                _snap.save_dedup_plan(pdigest, plan)
            self._dedup_plan_memo[target] = (pdigest, plan)
            # plan changed: drop cross-sweep shared columns whose
            # digest is no longer in the live group set
            live = set(plan.groups)
            for d in list(st.dedup_shared_cache):
                if d not in live:
                    del st.dedup_shared_cache[d]
            return plan
        except Exception:
            # dedup is an optimization; the original programs are
            # always a valid fallback
            return None

    @locked_read
    def prepare_audit(self, target: str) -> bool:
        """Pre-build the serving structures a full audit sweep needs —
        today the cross-template dedup plan — so a (re)started pod pays
        that cost at startup, before declaring itself ready, instead of
        inside its first sweep.  Warm restarts load the plan from the
        snapshot tier; cold starts run the analysis here.  Returns True
        when a plan is ready (False: scalar-only, dedup off, or nothing
        lowered — the sweep then runs without a plan, as always)."""
        st = self.state.get(target)
        if st is None or self.scalar_only:
            return False
        # Stage-7: AOT-compile the certified signatures of the current
        # geometry before declaring ready (warm restarts skip via the
        # cs-tier geometry stamp — zero startup compiles)
        self._precompile(st, target)
        if os.environ.get("GATEKEEPER_DEDUP", "on") == "off":
            return False
        with self._prep_lock:
            return self._audit_dedup_plan(st, target) is not None

    @locked_read
    def precompile(self, target: str) -> int:
        """AOT-lower and compile every Stage-7-certified signature of
        the target's current geometry (the install/warm-restart seam,
        also reached through :meth:`prepare_audit`).  Returns the
        number of AOT compiles issued — 0 on a warm restart whose
        geometry stamp is already in the cs snapshot tier."""
        st = self.state.get(target)
        if st is None:
            return 0
        return self._precompile(st, target)

    def _precompile(self, st, target: str) -> int:
        from gatekeeper_tpu.analysis import compilesurface
        if compilesurface.mode() == "off" or self.scalar_only \
                or not isinstance(st, JaxTargetState) \
                or self.executor.mesh is not None:
            return 0
        entries: list[tuple] = []
        with self._prep_lock:
            for kind in sorted(st.templates):
                compiled = st.templates[kind]
                cert = st.compilesurfaces.get(kind)
                cons = self._kind_constraints(st, kind)
                if compiled.vectorized is None or not cons:
                    continue
                if cert is None or not cert.bounded \
                        or getattr(cert, "scalar_pin", False):
                    continue
                try:
                    bindings = self._kind_bindings(st, kind, compiled,
                                                   cons)
                except Exception:   # noqa: BLE001 — prewarm is an
                    continue        # optimization, never a gate
                # mirror the dispatch-time gate set (_install_gates):
                # kinds with match criteria get a __match__ binding
                with_match = any((c.get("spec") or {}).get("match")
                                 for c in cons)
                entries.append((kind, cert.digest,
                                compiled.vectorized.program, bindings,
                                with_match))
        if not entries:
            return 0
        import hashlib as _hashlib
        from gatekeeper_tpu.resilience import snapshot as _snap
        geom = sorted((kind, dg, b.c_pad, b.r_pad, wm)
                      for kind, dg, _p, b, wm in entries)
        stamp = _hashlib.sha256(repr(geom).encode()).hexdigest()
        if _snap.load_compilesurface(f"aot:{target}:{stamp}") is not None:
            # warm restart at the same certified geometry: zero AOT
            # compiles here — first dispatches reload their executables
            # through the persistent compile cache instead of paying a
            # startup compile storm
            return 0
        n = 0
        for _kind, _dg, prog, bindings, wm in entries:
            try:
                self.executor.prewarm_audit_exec(
                    prog, bindings, DEFAULT_PREWARM_CAP, with_match=wm)
                compilesurface.precompiles_run += 1
                n += 1
            except Exception:   # noqa: BLE001 — best-effort
                continue
        self.metrics.counter("compile_surface_precompiles").inc(n)
        _snap.save_compilesurface(f"aot:{target}:{stamp}",
                                  {"target": target, "n": n})
        return n

    def certified_review_rungs(self, target: str,
                               max_n: int | None = None
                               ) -> list[int] | None:
        """Batch sizes whose padded review signature is inside the
        Stage-7 certified surface — the rungs the micro-batcher's
        ``_fit_to_deadline`` may shrink along.  Review mini-tables pad
        to ``bucket(B)`` (minimum 8), so the rungs are 1 plus the
        power-of-two ladder up to the rows cap.  None when the stage is
        off, nothing is certified yet, or any installed template's
        surface is unbounded (the batcher then falls back to blind
        halving)."""
        from gatekeeper_tpu.analysis import compilesurface
        from gatekeeper_tpu.ir import prep as _prep
        if compilesurface.mode() == "off":
            return None
        st = self.state.get(target)
        if not isinstance(st, JaxTargetState):
            return None
        certs = [st.compilesurfaces.get(k) for k in st.templates]
        have = [c for c in certs if c is not None]
        if not have or any(not c.bounded for c in have):
            return None
        rungs = [1] + list(_prep.bucket_ladder(
            8, compilesurface._cap("r")))
        if max_n is not None:
            rungs = [r for r in rungs if r <= max_n] or [1]
        cap = self.memsurface_review_cap(target)
        if cap is not None:
            rungs = [r for r in rungs if r <= cap] or [1]
        return rungs

    @locked_read
    def memsurface_review_cap(self, target: str) -> int | None:
        """Stage-8 consumer 2: the largest certified review-batch rung
        whose worst per-kind dispatch footprint fits the HBM budget
        left after the installed set's certified resident arrays.  A
        review batch pads its mini-table to ``bucket(B)`` rows and
        dispatches one kind at a time, so the in-flight claim is the
        max (not sum) over installed kinds of the peak at that row
        geometry.  None when the stage is off or nothing is certified
        (the batcher then caps only by the Stage-7 rung ladder)."""
        from gatekeeper_tpu.analysis import memsurface
        from gatekeeper_tpu.ir import prep as _prep
        if memsurface.mode() == "off":
            return None
        st = self.state.get(target)
        if not isinstance(st, JaxTargetState):
            return None
        certs = [c for c in st.memsurfaces.values()
                 if isinstance(c, memsurface.MemorySurface)
                 and not c.scalar_pin]
        if not certs:
            return None
        remaining = memsurface.budget_bytes() - sum(
            c.resident_bytes(memsurface.cap_dims()) for c in certs)
        if remaining <= 0:
            return 1
        rungs = [1] + list(_prep.bucket_ladder(
            8, memsurface._cap("r")))
        best = 1
        for rung in rungs:
            dims = memsurface.cap_dims()
            dims["r"] = _prep.bucket(max(rung, 1))
            claim = max(c.peak_bytes(dims, devpages=False)
                        for c in certs)
            if claim <= remaining:
                best = rung
            else:
                break
        return best

    def memsurface_sweep_order(self, st, kinds: list[str]) -> list[str]:
        """Stage-8 consumer 3: order full-sweep kind dispatch so
        concurrent in-flight footprints stay under budget.  JAX
        dispatch is async — while kind i's program drains, kind i+1's
        uploads and intermediates are already materializing, so the
        transient claim of *adjacent* kinds coexists.  Weaving the
        certified-peak order (largest, smallest, second-largest, ...)
        minimizes the worst adjacent-pair sum without changing the
        result: phase-2 formatting re-sorts tagged results into a
        total order, so any dispatch permutation is parity-safe on
        the full path.  Falls back to sorted order when the stage is
        off or any kind lacks a certificate (determinism over
        cleverness)."""
        from gatekeeper_tpu.analysis import memsurface
        base = sorted(kinds)
        if memsurface.mode() == "off" or len(base) < 3:
            return base
        peaks = {}
        for k in base:
            cert = st.memsurfaces.get(k)
            if not isinstance(cert, memsurface.MemorySurface):
                return base
            peaks[k] = 0 if cert.scalar_pin else cert.peak_bytes()
        ranked = sorted(base, key=lambda k: (-peaks[k], k))
        woven: list[str] = []
        lo, hi = 0, len(ranked) - 1
        while lo <= hi:
            woven.append(ranked[lo])
            if lo != hi:
                woven.append(ranked[hi])
            lo += 1
            hi -= 1
        self.metrics.counter("memsurface_sweep_reorders").inc()
        return woven

    def _shared_col(self, st, plan, kind: str, digest: str, bindings):
        """One shared conjunct's host column, page-partitioned ACROSS
        sweeps: a geometry-stable cache hit re-evaluates only the rows
        the store dirtied since the cached generation and splices them
        into a COPY (the previous sweep's bindings may still reference
        the cached array).  Sound because shared subtrees are
        row-local by construction (_SHAREABLE_OPS: own columns +
        digest-stable interner tables; the interner is append-only, so
        an unchanged row's ids resolve identically) — a changed row is
        always in dirty_rows_since.  Eviction is the key itself: a
        constraint-set change changes the digest, a remap or resize
        misses the guards."""
        from gatekeeper_tpu.analysis.policyset import eval_shared_host
        g = plan.groups[digest]
        member = g.members[kind]
        table = st.table
        want_shape = (bindings.r_pad, bindings.e_pads.get(g.axis)) \
            if g.ekind == "e" else (bindings.r_pad,)
        hit = st.dedup_shared_cache.get(digest)
        if hit is not None:
            c_gen, c_remap, c_col = hit
            if c_remap == table.remap_generation \
                    and c_col.shape == want_shape:
                if c_gen == table.generation:
                    return c_col
                dirty = table.dirty_rows_since(c_gen)
                if len(dirty) <= max(64, table.n_rows // 4):
                    sub = eval_shared_host(
                        plan.originals[kind], member.node_idx,
                        bindings.arrays, g.ekind, rows=dirty)
                    col = c_col.copy()
                    col[dirty] = sub
                    st.dedup_shared_cache[digest] = (
                        table.generation, table.remap_generation, col)
                    self.metrics.counter(
                        "dedup_shared_delta_evals").inc()
                    return col
        col = eval_shared_host(plan.originals[kind], member.node_idx,
                               bindings.arrays, g.ekind)
        st.dedup_shared_cache[digest] = (
            table.generation, table.remap_generation, col)
        return col

    def _apply_dedup(self, st, plan, kind: str, bindings,
                     shared_cols: dict, applied: dict):
        """Swap one kind's program for its dedup rewrite
        (analysis/policyset.py), injecting the shared predicate columns
        as plain bool bindings.  The column for a digest is computed
        ONCE per sweep — on the host, from the first member kind's
        bound arrays (the numpy twin of the device evaluator) — and
        handed to every member; member kinds bind identical arrays for
        identical canonical inputs (same inventory, same interner, same
        row bucket), which the shape guard re-checks per kind.  Across
        sweeps the per-digest column is cached and churn re-evals only
        dirty rows (_shared_col), so shared-conjunct host-eval is
        O(dirty), not O(rows/sweep).  Any mismatch or twin failure
        keeps the kind on its original program.  Returns the rewritten
        Program or None."""
        add: dict = {}
        try:
            for digest in plan.kind_digests[kind]:
                g = plan.groups[digest]
                col = shared_cols.get(digest)
                if col is None:
                    col = self._shared_col(st, plan, kind, digest,
                                           bindings)
                    shared_cols[digest] = col
                if g.ekind == "e":
                    if col.shape != (bindings.r_pad,
                                     bindings.e_pads.get(g.axis)):
                        return None
                elif col.shape != (bindings.r_pad,):
                    return None
                add[g.binding] = col
        except Exception:
            return None     # dedup is an optimization, never a failure
        # rebind, never mutate (see the _install_gates NOTE): readers
        # may hold the previous arrays dict
        bindings.arrays = {**bindings.arrays, **add}
        for digest in plan.kind_digests[kind]:
            applied[digest] = applied.get(digest, 0) \
                + plan.groups[digest].members[kind].sites
        return plan.rewritten[kind]

    @staticmethod
    def _twin_bindings_equal(a, b) -> bool:
        """True when two kinds' bound arrays are bit-identical — same
        names, shapes, dtypes, contents.  Shared dedup columns are the
        same objects in both dicts, so identity short-circuits the
        common case; everything else pays one host memcmp."""
        if a is None or b is None:
            return False
        if a.c_pad != b.c_pad or a.r_pad != b.r_pad:
            return False
        if set(a.arrays) != set(b.arrays):
            return False
        for name, x in a.arrays.items():
            y = b.arrays[name]
            if x is y:
                continue
            try:
                xa, ya = np.asarray(x), np.asarray(y)
            except Exception:
                return False
            if xa.shape != ya.shape or xa.dtype != ya.dtype \
                    or not np.array_equal(xa, ya):
                return False
        return True

    def _twin_future(self, twin_src: dict, mode: str, kind: str,
                     prog, bindings, specs: list, futures: list):
        """Whole-kind dispatch sharing for what-if (shadow) sweeps.

        A shadow install stages the candidate set's kinds beside the
        live set under mangled names (analysis/policyset.shadow_kind).
        For every template the candidate did NOT change, the shadow
        twin lowers to the same program (cache keys match — kind names
        never reach the IR) over bit-identical bound arrays, so its
        device dispatch would recompute the live kind's payload
        exactly.  This seam detects that case after gate install and
        dedup rewrite, and aliases the shadow kind to the live twin's
        in-flight future instead of dispatching — the combined
        live+shadow sweep then pays device time only for kinds the
        candidate actually changed.  Handles resolve idempotently
        (PendingTopK/PendingMask.get is a pure D2H read), so both
        slots format from the one payload.  Each alias gets a fresh
        chained Future: phase 2 keys its completion map by future
        object, and a shared object would collapse two slots into one.

        Live (unmangled) kinds register; shadow kinds return a chained
        Future when their twin matches, else None (normal dispatch).
        Any comparison failure falls back to dispatching — sharing is
        an optimization, never a correctness dependency."""
        from gatekeeper_tpu.analysis.policyset import split_shadow_kind
        base, tag = split_shadow_kind(kind)
        if tag is None:
            twin_src[(kind, mode)] = len(futures)
            return None
        si = twin_src.get((base, mode))
        if si is None:
            return None
        src_fut = futures[si]
        if src_fut is None:
            return None
        s_prog, s_bind = specs[si][4], specs[si][5]
        try:
            if s_prog is None or s_prog.cache_key() != prog.cache_key():
                return None
        except Exception:
            return None
        if not self._twin_bindings_equal(s_bind, bindings):
            return None
        import concurrent.futures
        self.metrics.counter("whatif_twin_dispatches_shared").inc()
        out: "concurrent.futures.Future" = concurrent.futures.Future()

        def _chain(src, out=out):
            exc = src.exception()
            if exc is not None:
                out.set_exception(exc)
            else:
                out.set_result(src.result())

        src_fut.add_done_callback(_chain)
        return out

    # ------------------------------------------------------------------

    @locked_read
    def query_audit(self, target: str,
                    opts: QueryOpts | None = None) -> tuple[list[Result], str | None]:
        import time as _time
        _t0 = _time.perf_counter()
        st = self._state(target)
        if not isinstance(st, JaxTargetState):
            return super().query_audit(target, opts)
        handler = self.targets[target]
        tracing = opts.tracing if opts is not None else self.default_tracing
        limit = opts.limit_per_constraint if opts is not None else None
        full = opts.full if opts is not None else False
        trace: list | None = [] if tracing else None

        if full:
            # Forced full sweep: drop every layer of sweep memoization
            # for this target.  Rebind (never .clear()) so concurrent
            # readers keep the dicts they already hold.  Fresh Bindings
            # built after this carry no per-executor device caches and
            # no persistent violation masks, so host prep, H2D upload,
            # and device evaluation all genuinely re-run; fmt_cache goes
            # too, so every violating pair re-formats through the scalar
            # oracle.  rank/order caches stay — they derive from the
            # table (row keys), not from any evaluation.
            with self._prep_lock:
                st.mask_cache = {}
                st.bindings_cache = {}
                st.bindings_retired = {}
                st.installed_match = {}
                st.fmt_cache = {}

        # row ordering matches the scalar driver (sorted cache keys) so
        # both drivers return identical result lists; the 1M-row sort +
        # index dict are keyed on key_generation — pure updates (the
        # dominant churn in a live cluster) never re-sort
        m = self.metrics
        _tphase = _time.perf_counter()

        def _phase(name):
            # wall-clock audit phase timers: order/prep+dispatch-submit,
            # handle-resolve (device upload+exec+compile wait), format
            nonlocal _tphase
            now = _time.perf_counter()
            m.timer(name).observe(now - _tphase)
            _tphase = now

        ordered_rows, row_order = self._ensure_order(st)
        rank = self._row_rank(st, row_order)
        # sweep root span, entered manually so the 300-line pipeline
        # body below keeps its indentation; closed in the finally.
        # Child spans on pool threads parent via _sweep_ctx (context
        # vars don't flow into pre-existing worker threads).
        from gatekeeper_tpu.obs.trace import get_tracer as _get_tracer
        _tracer = _get_tracer()
        _sweep_cm = _tracer.span("audit.sweep", cat="audit", target=target,
                                 full=full, rows=len(ordered_rows))
        _sweep_sp = _sweep_cm.__enter__()
        _sweep_ctx = _tracer.current()
        self.executor.sweep_active.set()
        try:

            # phase 1: dispatch every kind's device evaluation without
            # blocking — one packed-fetch round-trip per kind, all in
            # flight at once (run_topk_async; the tunnel latency of fetch
            # N overlaps the execution of fetch N+1).  Dispatches run on a
            # thread pool so first-time jit traces / XLA compiles of
            # different kinds overlap (a 30-template library would
            # otherwise pay its compiles serially on a cold start).
            import threading as _threading
            # full-sweep pipeline phase accumulators: host_prep on the
            # sweep thread, h2d/device on whichever pool worker runs the
            # kind (hence the lock).  Their SUM exceeding the pipeline
            # wall is the overlap the pipeline buys.
            ph = {"host_prep_s": 0.0, "h2d_s": 0.0, "device_s": 0.0,
                  "h2d_bytes": 0}
            # per-kind measured device block seconds (full sweeps) —
            # ground truth for the attribution drift report
            per_kind_dev: dict[str, float] = {}
            ph_lock = _threading.Lock()
            serial_full = full and FULL_SWEEP_SERIAL

            def _launch(mode, prog, bindings):
                if mode == "topk":
                    return self.executor.run_topk_async(prog, bindings, limit)
                return self.executor.run_async(prog, bindings)

            def dispatch(spec):
                mode, kind, _, _, prog, bindings, mask = spec
                # match/rank gates ride bindings.arrays (_install_gates)
                if mode not in ("topk", "mask"):
                    return None
                if not full:
                    with _tracer.span("device.dispatch", cat="device",
                                      parent=_sweep_ctx, kind=kind,
                                      mode=mode):
                        return _launch(mode, prog, bindings)
                # full sweep: meter the two device-side pipeline stages
                # where they run (concurrently across kinds).
                # stage_uploads enqueues this kind's H2D transfers as
                # its own stage — the _arrays call inside run_*_async
                # then hits the device cache — and block() rides until
                # the result is device-resident, so device_s is
                # per-kind device occupancy, not host-fetch wall (the
                # D2H copy stays async and is collected in phase 2).
                t0 = _time.perf_counter()
                self.executor.stage_uploads(bindings)
                t1 = _time.perf_counter()
                h = _launch(mode, prog, bindings).block()
                t2 = _time.perf_counter()
                _tracer.add_complete("kind.h2d", cat="h2d", t0=t0, t1=t1,
                                     parent=_sweep_ctx, kind=kind)
                _tracer.add_complete("kind.device", cat="device", t0=t1,
                                     t1=t2, parent=_sweep_ctx, kind=kind,
                                     mode=mode)
                with ph_lock:
                    ph["h2d_s"] += t1 - t0
                    ph["device_s"] += t2 - t1
                    ph["h2d_bytes"] += bindings.nbytes()
                    per_kind_dev[kind] = \
                        per_kind_dev.get(kind, 0.0) + (t2 - t1)
                return h

            def _prep_done(kind, t0):
                # close one kind's host-prep region: meter it into the
                # pipeline phase sum and record the span
                now = _time.perf_counter()
                _tracer.add_complete("kind.host_prep", cat="host_prep",
                                     t0=t0, t1=now, parent=_sweep_ctx,
                                     kind=kind)
                ph["host_prep_s"] += now - t0

            # prep + dispatch interleaved: each kind's device step is
            # submitted the moment its bindings are ready, so kind N's
            # device execution (and any cold compile, on the pool) overlaps
            # kind N+1's host prep — on churned sweeps the host delta work
            # hides most of the device time instead of serializing before it
            import concurrent.futures
            pool = concurrent.futures.ThreadPoolExecutor(max_workers=8)
            specs: list[tuple] = []
            futures: list = []
            # bulk external-data warm, overlapped with host prep: by the
            # time a kind's build loop asks for a key it is a cache hit
            # (or a single-flight wait on this very fetch)
            def _ext_prefetch():
                with _tracer.span("external.prefetch", cat="external",
                                  parent=_sweep_ctx):
                    return self._prefetch_external(st)
            ext_fut = pool.submit(_ext_prefetch)
            # cross-host collective ordering: on a mesh spanning
            # processes, collective launches must happen in the SAME
            # order on every process (see veval._COLLECTIVE_EXEC_LOCK
            # scope note).  The kind loop below is sorted, so inline
            # dispatch from this one thread is deterministic; the
            # threaded pool (whose completion order is not) stays for
            # single-process meshes where only mutual exclusion matters.
            from gatekeeper_tpu.engine.veval import mesh_spans_processes
            ordered_dispatch = mesh_spans_processes(self.executor.mesh)
            if limit is not None and not self.scalar_only \
                    and self.executor.mesh is None:
                # the shared top-k reduce executable's shape bucket is known
                # before any prep — compile it concurrently with host prep
                # (its XLA compile is the longest pole of a cold audit)
                from gatekeeper_tpu.ir.prep import audit_pads
                n_rows = st.table.n_rows
                pads = set()
                for kind in st.templates:
                    n_con = len(st.constraints.get(kind, {}))
                    if not n_con or n_rows * n_con < SMALL_WORKLOAD_EVALS:
                        continue
                    pads.add(audit_pads(n_rows, n_con))
                # dedupe by bucket: kinds overwhelmingly share one shape,
                # and duplicate submissions would park pool workers on the
                # single-flight wait, starving the dispatch futures
                for r_pad, c_pad in pads:
                    pool.submit(self.executor.prewarm_reduce, limit, c_pad,
                                r_pad)
            # cross-template predicate dedup (analysis/policyset.py):
            # full sweeps only — the plan is a pure function of the
            # installed set, rebuilt each time (milliseconds), so there
            # is no cached plan to go stale under template churn.
            # GATEKEEPER_DEDUP=off is the parity oracle's kill switch.
            dedup_plan = None
            dedup_shared_cols: dict = {}
            dedup_applied: dict = {}
            dedup_host_s = 0.0
            # footprint-driven selective invalidation (analysis/
            # footprint.py): a non-full sweep replays a kind's cached
            # device payload when no dirty column path intersects its
            # validated read-set (_selective_reuse).
            # GATEKEEPER_FOOTPRINT=off is the bit-identical oracle.
            from gatekeeper_tpu.analysis.footprint import mode as _fp_mode
            fp_enabled = not self.scalar_only and _fp_mode() != "off"
            fp_skipped: list[str] = []
            fp_saved = 0
            # continuous enforcement (enforce/): eligible kinds skip
            # the per-kind device sweep entirely — only dirty pages ×
            # affected constraints re-evaluate, and capped results are
            # served from the VerdictLedger's confirmed violation set.
            # GATEKEEPER_PAGES=off is the bit-identical oracle (the
            # legacy path below, including footprint selective reuse).
            from gatekeeper_tpu.enforce.ledger import pages_mode as _pg_mode
            pg_on = _pg_mode()
            pg_kinds: list[str] = []
            pg_fallback: dict[str, str] = {}
            pg_stats = {"pages_evaluated": 0, "pages_skipped": 0,
                        "rows_padded": 0, "rows_reevaluated": 0,
                        "evaluations_saved": 0, "widen_fallbacks": 0,
                        "full_builds": 0, "events": 0}
            pg_dirty_pages: set[int] = set()
            from gatekeeper_tpu.enforce.devpages import (
                devpages_mode as _dv_mode, fresh_stats as _dv_fresh)
            dv_on = pg_on and _dv_mode() and not self.scalar_only
            dv_stats = _dv_fresh() if dv_on else None
            # what-if twin sharing (whatif/shadow.py): when shadow
            # kinds are staged, an unchanged twin aliases the live
            # kind's dispatch instead of re-running it on device.
            # GATEKEEPER_WHATIF_SHARE=off is the parity oracle.
            _twin_src: dict | None = None
            twin_shared: list[str] = []
            if full and not self.scalar_only and \
                    os.environ.get("GATEKEEPER_WHATIF_SHARE", "on") != "off":
                from gatekeeper_tpu.analysis.policyset import is_shadow_kind
                if any(is_shadow_kind(k) for k in st.templates):
                    _twin_src = {}
            # Stage-6 plan gating (analysis/shardplan.py): on a mesh,
            # a kind's bindings shard only when its partition plan
            # certifies eligibility; uncertified/ineligible kinds pin
            # to the replicated (single-device) path.
            # GATEKEEPER_SHARDPLAN=off is the oracle: everything
            # shards exactly as before this stage.
            from gatekeeper_tpu.analysis.shardplan import mode as _sp_mode
            sp_gate = self.executor.mesh is not None and \
                _sp_mode() != "off"
            sp_sharded: list[str] = []
            sp_replicated: list[str] = []
            sp_evals = 0
            sp_collectives = 0
            _t_pipe = _time.perf_counter()
            try:
                with self._prep_lock:
                    _tk = _time.perf_counter()
                    self._prefetch_axes(st)
                    if full and not self.scalar_only and \
                            os.environ.get("GATEKEEPER_DEDUP", "on") != "off":
                        dedup_plan = self._audit_dedup_plan(st, target)
                    _prep_done("__axes_and_plan__", _tk)
                    _sweep_kinds = sorted(st.templates)
                    if full and trace is None and not self.scalar_only:
                        # Stage-8 consumer 3: dispatch order packs
                        # adjacent in-flight footprints under budget;
                        # parity-safe here because phase 2 re-sorts
                        # tagged results into a total order (pages/
                        # ledger kinds only occur when not full)
                        _sweep_kinds = self.memsurface_sweep_order(
                            st, _sweep_kinds)
                    for _kind_i, kind in enumerate(_sweep_kinds):
                        # fault injection: kill the backend mid-sweep
                        # (after the first kind when there are several)
                        # — the scalar_only property re-consults the
                        # supervisor below, so the remaining kinds
                        # route through the scalar oracle and the
                        # sweep completes with correct verdicts
                        if _kind_i > 0 or len(_sweep_kinds) == 1:
                            from gatekeeper_tpu.resilience import \
                                faults as _faults
                            if _faults.take("device_lost"):
                                self.supervisor.report_failure(
                                    "fault injection: device_lost "
                                    "mid-sweep")
                        _tk = _time.perf_counter()
                        compiled = st.templates[kind]
                        constraints = self._kind_constraints(st, kind)
                        if not constraints:
                            continue
                        if pg_on and not full and trace is None:
                            reason = self._pages_ineligible(st, kind,
                                                            compiled)
                            if reason is None:
                                # no device dispatch: the paged serve
                                # runs in phase 2 on the sweep thread
                                # (futures=None kinds format first, in
                                # sorted-kind order — deterministic
                                # ledger event order)
                                spec = ("pages", kind, compiled,
                                        constraints, None, None, None)
                                _prep_done(kind, _tk)
                                futures.append(None)
                                specs.append(spec)
                                pg_kinds.append(kind)
                                continue
                            pg_fallback[kind] = reason
                        if fp_enabled and not full and trace is None:
                            reuse = self._selective_reuse(
                                st, kind, compiled, constraints, limit)
                            if reuse is not None:
                                ent, bindings = reuse
                                spec = (ent["mode"], kind, compiled,
                                        constraints, ent["prog"], bindings,
                                        ent["mask"])
                                _prep_done(kind, _tk)
                                f = concurrent.futures.Future()
                                f.set_result(_ResolvedHandle(ent["payload"]))
                                futures.append(f)
                                specs.append(spec)
                                fp_skipped.append(kind)
                                fp_saved += len(ordered_rows) \
                                    * len(constraints)
                                continue
                        mask, mask_dirty, padded = self._kind_mask(
                            st, target, kind, constraints)
                        small = self.scalar_only or \
                            len(ordered_rows) * len(constraints) \
                            < SMALL_WORKLOAD_EVALS
                        if compiled.vectorized is not None and mask is not None \
                                and not small:
                            try:
                                bindings = self._kind_bindings(
                                    st, kind, compiled, constraints)
                            except ExternalDataError:
                                # failurePolicy Fail during this kind's
                                # table build: contained per kind — its
                                # violations are unknown this sweep, every
                                # other template is unaffected
                                self.metrics.counter(
                                    "external_data_kind_failures").inc()
                                _prep_done(kind, _tk)
                                continue
                            if bindings.f32_unsafe:
                                # some bound numeric value does not survive a
                                # float32 round-trip (|v| past 2^24): device
                                # ordering compares could silently mis-order,
                                # so this kind runs on the scalar oracle
                                # (ir/lower.py "known deviations" guard)
                                self.metrics.counter(
                                    "f32_unsafe_scalar_fallbacks").inc()
                                spec = ("scalar", kind, compiled, constraints,
                                        None, None, mask)
                                _prep_done(kind, _tk)
                                futures.append(None)
                                specs.append(spec)
                                continue
                            if self.executor.mesh is not None:
                                plan = st.shardplans.get(kind)
                                if sp_gate:
                                    self.executor.set_sharding_allowed(
                                        bindings,
                                        plan is not None and
                                        getattr(plan, "eligible", False))
                                if self.executor._sharded_for(bindings):
                                    sp_sharded.append(kind)
                                    _ms = self.executor.mesh.shape
                                    sp_evals += bindings.c_pad * \
                                        bindings.r_pad // \
                                        (_ms["c"] * _ms["r"])
                                    if plan is not None:
                                        sp_collectives += len(
                                            getattr(plan, "collectives",
                                                    ()))
                                else:
                                    sp_replicated.append(kind)
                            self._install_gates(st, kind, bindings, mask,
                                                mask_dirty, rank, padded)
                            prog = compiled.vectorized.program
                            if dedup_plan is not None and \
                                    kind in dedup_plan.rewritten:
                                _t_dd = _time.perf_counter()
                                prog2 = self._apply_dedup(
                                    st, dedup_plan, kind, bindings,
                                    dedup_shared_cols, dedup_applied)
                                if prog2 is not None:
                                    prog = prog2
                                _t_dd2 = _time.perf_counter()
                                _tracer.add_complete(
                                    "dedup.host_eval", cat="dedup",
                                    t0=_t_dd, t1=_t_dd2,
                                    parent=_sweep_ctx, kind=kind)
                                dedup_host_s += _t_dd2 - _t_dd
                            mode = "topk" if limit is not None else "mask"
                            spec = (mode, kind, compiled, constraints, prog,
                                    bindings, mask)
                            _prep_done(kind, _tk)
                            if _twin_src is not None:
                                tf = self._twin_future(
                                    _twin_src, mode, kind, prog, bindings,
                                    specs, futures)
                                if tf is not None:
                                    twin_shared.append(kind)
                                    futures.append(tf)
                                    specs.append(spec)
                                    continue
                            # serial_full: the no-overlap diagnostic
                            # baseline — dispatch inline and (because
                            # dispatch blocks on full sweeps) finish
                            # this kind end-to-end before the next
                            # kind's prep
                            if ordered_dispatch or serial_full:
                                f = concurrent.futures.Future()
                                try:
                                    f.set_result(dispatch(spec))
                                except Exception as e:  # noqa: BLE001
                                    f.set_exception(e)
                                futures.append(f)
                            else:
                                futures.append(pool.submit(dispatch, spec))
                        else:
                            # unlowerable template — or a workload too small
                            # to amortize a device dispatch round-trip
                            spec = ("scalar", kind, compiled, constraints, None,
                                    None, mask)
                            _prep_done(kind, _tk)
                            futures.append(None)
                        specs.append(spec)
                _phase("audit_prep_submit")

                # phase 2: resolve handles and host-format per kind.  The
                # tag key (row rank, kind, constraint name) is a total
                # order, so the tagged sort below restores output order no
                # matter which kind formats first — which lets a pipelined
                # sweep format each kind the moment its handle completes,
                # overlapping host formatting of finished kinds with
                # device compute of kinds still in flight.  Tracing is
                # append-order-sensitive, so it keeps sorted-kind order.
                # One (review, frozen) per violating row for the whole
                # sweep — rows recur across kinds/constraints, and
                # freeze() is a deep walk.
                rcache: dict[int, tuple] = {}
                tagged: list[tuple[tuple, Result]] = []
                fmt_s = 0.0

                def _format_kind(spec, handle):
                    nonlocal fmt_s
                    mode, kind, compiled, constraints, prog, bindings, \
                        mask = spec
                    _tf = _time.perf_counter()
                    # resolve the device payload once: the format path
                    # reads it through a pre-resolved handle, and a
                    # fresh (non-replayed) payload is captured for
                    # footprint-driven reuse on later churn sweeps
                    payload = None
                    fresh = not isinstance(handle, _ResolvedHandle)
                    if handle is not None and mode in ("topk", "mask"):
                        payload = handle.get()
                        handle = _ResolvedHandle(payload)
                    try:
                        if mode == "pages":
                            # the sweep formats pages-mode kinds outside
                            # _prep_lock; the devpages path fills the
                            # reader-side caches (_kind_bindings) the
                            # lock serializes against the reactor —
                            # take it here (react_kind's own _paged_kind
                            # call already holds it; plain Lock, so it
                            # must not be re-acquired deeper down)
                            with self._prep_lock:
                                self._paged_kind(st, target, handler,
                                                 compiled, constraints,
                                                 ordered_rows, row_order,
                                                 kind, limit, tagged,
                                                 rcache, pg_stats,
                                                 pg_dirty_pages, dv_stats)
                        elif mode == "topk":
                            self._format_topk(st, target, handler, compiled,
                                              constraints, prog, bindings,
                                              mask, rank, row_order, kind,
                                              limit, trace, tagged, handle,
                                              rcache)
                        elif mode == "mask":
                            self._format_pairs(st, target, handler, compiled,
                                               constraints, payload,
                                               row_order, kind, limit, trace,
                                               tagged, rcache)
                        else:
                            self._scalar_kind(st, target, handler, compiled,
                                              constraints, mask, ordered_rows,
                                              row_order, kind, limit, trace,
                                              tagged, rcache)
                    except ExternalDataError:
                        # scalar-oracle re-check hit a Fail-policy
                        # provider failure: same per-kind containment as
                        # the prep loop
                        m.counter("external_data_kind_failures").inc()
                    else:
                        if fp_enabled and fresh and payload is not None:
                            self._capture_sweep(st, kind, compiled, mode,
                                                spec, payload, limit)
                    _tf2 = _time.perf_counter()
                    _tracer.add_complete("kind.format", cat="format",
                                         t0=_tf, t1=_tf2,
                                         parent=_sweep_ctx, kind=kind,
                                         mode=mode)
                    fmt_s += _tf2 - _tf

                if trace is None:
                    fut_idx = {f: i for i, f in enumerate(futures)
                               if f is not None}
                    for i, f in enumerate(futures):
                        if f is None:   # scalar kinds: nothing to wait on
                            _format_kind(specs[i], None)
                    for f in concurrent.futures.as_completed(fut_idx):
                        _format_kind(specs[fut_idx[f]], f.result())
                else:
                    for sp, f in zip(specs, futures):
                        _format_kind(sp,
                                     f.result() if f is not None else None)
                # the resolve+format interleave is one wall region; split
                # the timers so dispatch-wait stays device-side only
                _now = _time.perf_counter()
                m.timer("audit_dispatch_wait").observe(
                    max(0.0, _now - _tphase - fmt_s))
                m.timer("audit_format").observe(fmt_s)
                _tphase = _now
                ph["format_s"] = fmt_s
                pipeline_wall = _time.perf_counter() - _t_pipe
            finally:
                pool.shutdown(wait=False)
            tagged.sort(key=lambda kv: kv[0])
            # warm the churn-delta executables in the background: the first
            # sweep after data churn otherwise pays one serialized XLA
            # compile per kind (multiple seconds) right on the sweep
            if limit is not None and not self.scalar_only \
                    and self.executor.mesh is None:
                warm = [(sp[4], sp[5]) for sp in specs if sp[0] == "topk"]
                if warm and not self._delta_warmed:
                    self._delta_warmed = True

                    def _warm(items=warm):
                        for prog, bindings in items:
                            if self.executor._shutdown.is_set():
                                return
                            try:
                                self.executor.prewarm_deltas(prog, bindings)
                            except Exception:
                                pass    # warmup is best-effort
                    # spawn_bg (not a bare daemon thread): a compile in
                    # flight at interpreter teardown aborts the process
                    self.executor.spawn_bg(_warm, "delta-warmup")
            m = self.metrics
            m.counter("audit_sweeps").inc()
            m.counter("audit_results").inc(len(tagged))
            m.timer("audit_sweep_wall").observe(_time.perf_counter() - _t0)
            m.gauge("audit_resources").set(len(ordered_rows))
            if full:
                # overlap_fraction: how much of the summed stage time
                # the pipeline hid — 0 means strictly serial stages,
                # (sum - wall)/sum > 0 means uploads/compute of some
                # kinds ran under other kinds' host prep.  Honest by
                # construction: every term is measured where the work
                # actually ran, and a serial run shows ~0.
                sum_ph = ph["host_prep_s"] + ph["h2d_s"] + \
                    ph["device_s"] + ph.get("format_s", 0.0)
                overlap = max(0.0, (sum_ph - pipeline_wall) / sum_ph) \
                    if sum_ph > 0 else 0.0
                self.last_sweep_phases = {
                    "full": True, "serial": serial_full,
                    "host_prep_s": round(ph["host_prep_s"], 6),
                    "h2d_s": round(ph["h2d_s"], 6),
                    "device_s": round(ph["device_s"], 6),
                    "format_s": round(ph.get("format_s", 0.0), 6),
                    "h2d_bytes": int(ph["h2d_bytes"]),
                    "pipeline_wall_s": round(pipeline_wall, 6),
                    "overlap_fraction": round(overlap, 4),
                }
                # per-template attribution of the measured device time
                # (obs/attribution.py): CostVector units apportion the
                # total, the per-kind timed dispatch blocks anchor the
                # drift report, and the samples recalibrate the cost
                # model's seconds-per-unit scale
                _dev_entries = [
                    (sp[1], sp[2].vectorized, len(sp[3]))
                    for sp in specs
                    if sp[0] in ("topk", "mask")
                    and sp[2].vectorized is not None]
                if _dev_entries and ph["device_s"] > 0:
                    from gatekeeper_tpu.obs.attribution import \
                        attribute_sweep
                    self.last_sweep_phases["attribution"] = \
                        attribute_sweep(_dev_entries, ph["device_s"],
                                        len(ordered_rows),
                                        measured=per_kind_dev, metrics=m)
                ext = self._external_sweep_stats(ext_fut)
                if ext is not None:
                    self.last_sweep_phases["external"] = ext
                if dedup_plan is not None:
                    n_res = len(ordered_rows)
                    saved = sum(max(0, c - 1) * n_res
                                for c in dedup_applied.values())
                    shared_n = sum(1 for c in dedup_applied.values()
                                   if c >= 2)
                    self.last_sweep_phases["dedup"] = {
                        "enabled": True,
                        "groups": len(dedup_plan.groups),
                        "subprograms_shared": shared_n,
                        "evaluations_saved": int(saved),
                        "host_eval_s": round(dedup_host_s, 6),
                    }
                    m.counter("dedup_shared_subprograms").inc(shared_n)
                    m.counter("dedup_evaluations_saved").inc(saved)
                else:
                    self.last_sweep_phases["dedup"] = {"enabled": False}
                if _twin_src is not None:
                    self.last_sweep_phases["whatif"] = {
                        "twin_shared_kinds": len(twin_shared),
                        "twin_dispatched_kinds": sum(
                            1 for s in specs
                            if s[0] in ("topk", "mask")) - len(twin_shared),
                    }
                m.counter("full_sweeps").inc()
                m.timer("full_sweep_host_prep").observe(ph["host_prep_s"])
                m.timer("full_sweep_h2d").observe(ph["h2d_s"])
                m.timer("full_sweep_device").observe(ph["device_s"])
                m.timer("full_sweep_format").observe(ph.get("format_s", 0.0))
                m.gauge("full_sweep_h2d_bytes").set(float(ph["h2d_bytes"]))
                m.gauge("full_sweep_overlap_fraction").set(overlap)
            else:
                self.last_sweep_phases = {"full": False}
                ext = self._external_sweep_stats(ext_fut)
                if ext is not None:
                    self.last_sweep_phases["external"] = ext
            # selective-invalidation stanza (both sweep shapes): how
            # many kinds replayed a cached payload vs ran, and the
            # (constraint x row) evaluations that skipping saved
            self.last_sweep_phases["footprint"] = {
                "enabled": fp_enabled,
                "kinds_skipped": len(fp_skipped),
                "kinds_evaluated": len(specs) - len(fp_skipped),
                "evaluations_saved": int(fp_saved),
            }
            if fp_saved:
                m.counter("footprint_evaluations_saved").inc(fp_saved)
            # plan-driven sharding stanza (both sweep shapes): mesh
            # size, which kinds ran sharded vs pinned replicated, the
            # per-shard evaluation slice and the collective count the
            # consumed plans declared
            _mesh = self.executor.mesh
            self.last_sweep_phases["shard"] = {
                "enabled": _mesh is not None,
                "shards": int(_mesh.devices.size) if _mesh is not None
                else 0,
                "plan_gated": sp_gate,
                "kinds_sharded": len(sp_sharded),
                "kinds_replicated": len(sp_replicated),
                "per_shard_evals": int(sp_evals),
                "collectives": int(sp_collectives),
            }
            # continuous-enforcement stanza (both sweep shapes): which
            # kinds served from the ledger vs fell back (with reasons),
            # page-level work accounting, and the delta events emitted
            _led = st.ledger if isinstance(st, JaxTargetState) else None
            self.last_sweep_phases["pages"] = {
                "enabled": pg_on,
                "page_rows": st.table.page_rows,
                "n_pages": st.table.n_pages,
                "kinds_paged": len(pg_kinds),
                "kinds_fallback": len(pg_fallback),
                "fallback_reasons": dict(pg_fallback),
                "pages_evaluated": int(pg_stats["pages_evaluated"]),
                "pages_skipped": int(pg_stats["pages_skipped"]),
                "rows_padded": int(pg_stats["rows_padded"]),
                "rows_reevaluated": int(pg_stats["rows_reevaluated"]),
                "evaluations_saved": int(pg_stats["evaluations_saved"]),
                "widen_fallbacks": int(pg_stats["widen_fallbacks"]),
                "ledger_full_builds": int(pg_stats["full_builds"]),
                "ledger_violations": _led.total_violations()
                if _led is not None else 0,
                "events": int(pg_stats["events"]),
            }
            self.last_sweep_phases["devpages"] = {"enabled": dv_on} \
                if dv_stats is None else {"enabled": True, **dv_stats}
            if dv_stats is not None:
                m.counter("store_h2d_bytes_total").inc(
                    int(dv_stats["h2d_bytes"]))
                m.gauge("devpages_scatter_rows").set(
                    float(dv_stats["scatter_rows"]))
                m.gauge("devpages_delta_events").set(
                    float(dv_stats["delta_events"]))
            m.gauge("store_pages_total").set(float(st.table.n_pages))
            if pg_kinds:
                m.gauge("store_pages_dirty").set(float(len(pg_dirty_pages)))
            if _led is not None:
                m.gauge("ledger_violations").set(
                    float(_led.total_violations()))
            _ov = st.table.dirtylog_overflows
            if _ov > st.dirtylog_overflows_seen:
                m.counter("store_dirtylog_overflow_total").inc(
                    _ov - st.dirtylog_overflows_seen)
                st.dirtylog_overflows_seen = _ov
            if _sweep_sp is not None:
                _sweep_sp.args["results"] = len(tagged)
            from gatekeeper_tpu.obs.flightrecorder import \
                record_event as _record_event
            _record_event("sweep", full=full, results=len(tagged),
                          wall_s=_time.perf_counter() - _t0,
                          device_s=(ph["device_s"] if full else None),
                          scalar_only=self.scalar_only)
            return [r for _, r in tagged], ("\n".join(trace) if trace is not None else None)
        finally:
            # ALWAYS cleared — a dispatch error leaving this set
            # would defer background upgrades forever
            self.executor.sweep_active.clear()
            _sweep_cm.__exit__(None, None, None)

    @locked_read
    def query_review_batch(self, target: str, reviews: list[dict],
                           opts: QueryOpts | None = None) -> list[tuple]:
        """Admission micro-batch as one [C, B] device pass per template
        kind (SURVEY §7 step 7).

        The B review objects become a throwaway mini resource table
        (own interner — admission strings must not grow the inventory
        interner); lowered programs and a ns-over-approximated match
        mask produce candidate (constraint, review) pairs on device, and
        only candidates are re-evaluated exactly on host (autoreject,
        namespaceSelector against the REAL cached namespaces, scalar
        oracle) — the same over-approximate-then-verify contract as the
        audit path, so results match per-review query_review exactly.

        Small batches (or tracing, which must observe evaluation) fall
        back to per-review scalar queries — below SMALL_WORKLOAD_EVALS
        pairs a device dispatch round-trip costs more than it saves."""
        st = self._state(target)
        handler = self.targets[target]
        tracing = opts.tracing if opts is not None else self.default_tracing
        shed = (opts.shed_actions if opts is not None else None) or None
        constraints_all = list(st.all_constraints())
        if shed:
            # brownout (overload.py): shed-action constraints excluded
            # before any evaluation — device mask, host verify, all of it
            constraints_all = [c for c in constraints_all
                               if enforcement_action_of(c) not in shed]
        B = len(reviews)
        if tracing or self.scalar_only or not isinstance(st, JaxTargetState) \
                or not B or \
                B * len(constraints_all) < REVIEW_BATCH_MIN_EVALS:
            return [self.query_review(target, r, opts) for r in reviews]

        import time as _time

        from gatekeeper_tpu.engine.match import MatchEngine
        from gatekeeper_tpu.store.table import ResourceMeta, ResourceTable
        _t_batch = _time.perf_counter()
        mt = ResourceTable()
        for i, rv in enumerate(reviews):
            k = rv.get("kind") or {}
            g, v = k.get("group", ""), k.get("version", "")
            api = f"{g}/{v}" if g else (v or "v1")
            obj = rv.get("object")
            mt.upsert(f"r{i:06d}", obj if isinstance(obj, dict) else {},
                      ResourceMeta(api_version=api, kind=k.get("kind", ""),
                                   name=str(rv.get("name", "")),
                                   namespace=rv.get("namespace")))
        mini = MatchEngine(mt)

        plans: list[tuple] = []
        for kind in sorted(st.templates):
            compiled = st.templates[kind]
            cons = self._kind_constraints(st, kind)
            if shed:
                cons = [c for c in cons
                        if enforcement_action_of(c) not in shed]
            if not cons:
                continue
            cmask = mini.mask(cons, overapprox_ns=True)
            lowered = compiled.vectorized
            # the audit review's operation is always CREATE, so $meta
            # operation columns would mis-encode UPDATE/DELETE reviews —
            # under-approximation risk; those kinds stay on the mask gate
            uses_op = lowered is not None and any(
                rc.path[:1] == ("$meta",) and rc.path[1:] == ("operation",)
                for rc in lowered.spec.r_cols)
            ops_ok = all(r.get("operation", "CREATE") == "CREATE"
                         for r in reviews) if uses_op else True
            # inventory-join columns built over the mini table would see
            # only the batch's reviews, not the real inventory — an
            # under-approximating gate (dropped violations).  Those
            # kinds keep the match-only gate + exact host evaluation.
            if lowered is not None and lowered.spec.inv_joins:
                lowered = None
            if lowered is not None and ops_ok:
                bindings = build_bindings(lowered.spec, mt, cons)
                if bindings.f32_unsafe:
                    # float32 round-trip-unsafe numerics: the device
                    # gate could under-approximate (mis-ordered compare
                    # drops a real violation) — keep the match-only gate
                    plans.append((kind, compiled, cons, cmask, None))
                    continue
                h = self.executor.run_async(lowered.program, bindings,
                                            match=cmask)
                plans.append((kind, compiled, cons, cmask, h))
            else:
                plans.append((kind, compiled, cons, cmask, None))
        gates = [(kind, compiled, cons, (h.get() if h is not None else cmask))
                 for kind, compiled, cons, cmask, h in plans]

        ns_sel_cons = [c for c in constraints_all
                       if ((c.get("spec") or {}).get("match") or {})
                       .get("namespaceSelector") is not None]
        out: list[tuple] = []
        for i, rv in enumerate(reviews):
            results: list[Result] = []
            if ns_sel_cons:
                for c, msg, details in handler.autoreject_review(
                        rv, ns_sel_cons, st.table):
                    results.append(Result(msg=msg,
                                          metadata={"details": details},
                                          constraint=c, review=rv,
                                          enforcement_action=
                                          enforcement_action_of(c)))
            frozen = freeze(rv)
            for kind, compiled, cons, gate in gates:
                for ci, c in enumerate(cons):
                    if not gate[ci, i]:
                        continue
                    # exact matching (incl. namespaceSelector against
                    # the real inventory) before the exact evaluation
                    if not any(True for _ in handler.matching_constraints(
                            rv, [c], st.table)):
                        continue
                    results.extend(self._eval_pair(st, target, compiled, rv,
                                                   frozen, c, None))
            out.append((results, None))
        m = self.metrics
        m.counter("review_batches_device").inc()
        m.counter("reviews_device").inc(B)
        from gatekeeper_tpu.obs.trace import get_tracer as _get_tracer
        _get_tracer().add_complete(
            "admission.device_batch", cat="device", t0=_t_batch,
            t1=_time.perf_counter(), n_reviews=B, kinds=len(gates))
        return out

    @locked_read
    def predict_review_batch_seconds(self, target: str,
                                     n_reviews: int) -> float | None:
        """Cost-model-predicted wall seconds for a review batch of size
        ``n_reviews`` against the installed constraint set — the PR-5
        static cost vector priced by the PR-9 calibrated seconds-per-unit
        scale, seeded with the static prior while uncalibrated
        (costmodel.effective_scale) so deadline-aware batch shrinking
        has an opinion from the very first batch.  None only when there
        is nothing to predict (no reviews, prior disabled and no
        samples) — callers must treat None as "no opinion", never as
        zero."""
        from gatekeeper_tpu.analysis import costmodel
        scale = costmodel.effective_scale()
        if scale <= 0.0 or n_reviews <= 0:
            return None
        st = self._state(target)
        key = (n_reviews, len(st.templates),
               sum(len(v) for v in st.constraints.values()))
        cached = self._predict_cache.get(key)
        units = cached
        if units is None:
            units = 0.0
            for kind in st.templates:
                compiled = st.templates[kind]
                lowered = compiled.vectorized
                if lowered is None:
                    continue
                n_cons = len(st.constraints.get(kind, {}))
                if not n_cons:
                    continue
                units += costmodel.estimate(
                    lowered, n_reviews, n_cons).units()
            if len(self._predict_cache) > 64:
                self._predict_cache.clear()
            self._predict_cache[key] = units
        return costmodel.predict_seconds(units, scale)

    @locked_read
    def explain_pair(self, target: str, kind: str, constraint_name: str,
                     resource_key: str) -> str:
        """Device-path mask dump for one (constraint, resource) pair:
        every IR node's value on that slice plus rule verdicts (the
        tracing equivalent for the vectorized engine, SURVEY §5), with
        the scalar oracle's verdict appended for cross-checking."""
        from gatekeeper_tpu.engine.veval import explain
        st = self._state(target)
        compiled = st.templates.get(kind)
        if compiled is None:
            return f"no template {kind!r}"
        row = st.table.lookup(resource_key)
        if row is None:
            return f"no resource {resource_key!r}"
        constraints = self._kind_constraints(st, kind)
        names = [(c.get("metadata") or {}).get("name") for c in constraints]
        if constraint_name not in names:
            return f"no constraint {constraint_name!r} of kind {kind!r}"
        ci = names.index(constraint_name)
        if compiled.vectorized is None:
            return f"template {kind!r} runs on the scalar engine (not lowered)"
        if self.scalar_only:
            return ("device backend unavailable (scalar-only engine); "
                    "use tracing on the scalar oracle instead")
        with self._prep_lock:
            bindings = self._kind_bindings(st, kind, compiled, constraints)
            mask, _, _ = self._kind_mask(st, target, kind, constraints)
        out = explain(compiled.vectorized.program, bindings, ci, row,
                      match=mask if mask is not TRIVIAL_MATCH else None)
        handler = self.targets[target]
        meta = st.table.meta_at(row)
        review = handler.make_review(meta, st.table.object_at(row))
        matched = any(True for _ in handler.matching_constraints(
            review, [constraints[ci]], st.table))
        oracle = list(self._eval_pair(st, target, compiled, review,
                                      freeze(review), constraints[ci], None)) \
            if matched else []
        return out + f"\n  oracle: {len(oracle)} violation(s)" + "".join(
            f"\n    msg={r.msg!r}" for r in oracle)

    def _pair_results(self, st, target, kind, compiled, c, row, review,
                      frozen, trace, shared=None) -> list:
        """Memoized per-pair formatting.  Steady-state sweeps re-visit
        the same capped (constraint, row) pairs against unchanged rows —
        the oracle re-evaluation is skipped when neither the row (its
        table version) nor the kind's constraint set changed.  Inventory
        -reading templates key on the whole table generation instead
        (their results can depend on any row); tracing bypasses the
        cache (the tracer must observe the evaluation)."""
        if trace is not None:
            return list(self._eval_pair(st, target, compiled, review, frozen,
                                        c, trace))
        con_ver = self.con_version_of(st, kind)
        hit = st.fmt_cache.get(kind)
        if hit is None or hit[0] != con_ver:
            hit = (con_ver, {})
            st.fmt_cache[kind] = hit
        entries = hit[1]
        ver = st.table.generation if compiled.uses_inventory \
            else st.table.version_at(row)
        cname = (c.get("metadata") or {}).get("name", "")
        key = (cname, row)
        ent = entries.get(key)
        if ent is None or ent[0] != ver:
            self.metrics.counter("format_memo_misses").inc()
            results = list(self._eval_pair(st, target, compiled, review,
                                           frozen, c, trace, shared))
            if len(entries) > 65536:     # bound growth across churn
                entries.clear()
            entries[key] = ent = (ver, results)
        else:
            self.metrics.counter("format_memo_hits").inc()
        # fresh copies (own metadata dict too): downstream sets
        # .resource and owns result.metadata — the cached canonical list
        # must stay pristine.  (metadata["details"] values are still
        # shared; they are produced once by thaw() and treated
        # read-only everywhere.)
        return [dataclasses.replace(r, metadata=dict(r.metadata))
                for r in ent[1]]

    @staticmethod
    def con_version_of(st, kind: str) -> int:
        return st.con_version.get(kind, 0)

    def _row_review(self, st, handler, row, rcache):
        """(review, frozen_review, shared_memo) for a table row, cached
        per sweep; None if the row is dead.  The third element is the
        per-review shared memo (rego/closures._memoize_review_pure):
        a violating row is formatted against every constraint that
        flagged it, and its review-pure comprehensions evaluate once per
        row instead of once per (row, constraint) — the memo entries are
        keyed by closure id and verify the frozen review's identity, so
        one dict is safe across kinds (the scalar driver's audit shares
        it the same way)."""
        hit = rcache.get(row)
        if hit is None:
            meta = st.table.meta_at(row)
            if meta is None:
                return None
            review = handler.make_review(meta, st.table.object_at(row))
            hit = (review, freeze(review), {})
            rcache[row] = hit
        return hit

    def _format_pairs(self, st, target, handler, compiled, constraints,
                      cand: np.ndarray, row_order, kind, limit, trace, tagged,
                      rcache):
        """Host-format violating (constraint, resource) pairs via the
        scalar oracle; over-approximated pairs yield no results."""
        for ci, c in enumerate(constraints):
            rows = np.nonzero(cand[ci])[0]
            # visit rows in the scalar driver's order for identical output
            rows = sorted((r for r in rows.tolist() if r in row_order),
                          key=row_order.__getitem__)
            emitted = 0
            for row in rows:
                if limit is not None and emitted >= limit:
                    break
                pair = self._row_review(st, handler, row, rcache)
                if pair is None:
                    continue
                review, frozen, shared = pair
                results = self._pair_results(st, target, kind, compiled, c,
                                             row, review, frozen, trace,
                                             shared)
                for r in results:
                    tagged.append(((row_order[row], kind,
                                    (c.get("metadata") or {}).get("name", "")), r))
                emitted += len(results)

    def _row_rank(self, st: JaxTargetState, row_order: dict) -> np.ndarray:
        """[n_rows] int32: row -> sorted-cache-key rank.  The device
        top-k scores by this rank so the capped subset matches the
        scalar driver's cap order (not raw table row order, which
        diverges after deletes/re-inserts).  Keyed on key_generation —
        pure updates reuse one array instance (device cache stays hot)."""
        kgen = st.table.key_generation
        if st.rank_cache is not None and st.rank_cache[0] == kgen:
            return st.rank_cache[1]
        n = st.table.n_rows
        rank = np.full((n,), n - 1, dtype=np.int32)
        if isinstance(row_order, _RowOrder):
            m = min(len(row_order._pos), n)
            pos = row_order._pos[:m]
            valid = pos >= 0
            rank[:m][valid] = pos[valid].astype(np.int32)
        elif row_order:
            rows = np.fromiter(row_order.keys(), dtype=np.int64,
                               count=len(row_order))
            rank[rows] = np.fromiter(row_order.values(), dtype=np.int32,
                                     count=len(row_order))
        st.rank_cache = (kgen, rank)
        return rank

    def _format_topk(self, st, target, handler, compiled, constraints,
                     prog, bindings, mask, rank, row_order, kind, limit,
                     trace, tagged, handle, rcache):

        """Capped audit: device finds the first-k candidate rows per
        constraint (in scalar cap order, via rank); the host formats
        only those.  If over-approximated pairs leave the cap
        under-filled while more candidates exist, fall back to the full
        mask for that constraint."""
        import time as _time
        if handle is None:
            handle = self.executor.run_topk_async(prog, bindings, limit)
        _tw = _time.perf_counter()
        counts, rows, valid = handle.get()
        self.metrics.timer("device_wait").observe(_time.perf_counter() - _tw)
        _tf = _time.perf_counter()
        full_cand = None
        for ci, c in enumerate(constraints):
            sel = [int(r) for r, v in zip(rows[ci], valid[ci]) if v]
            sel = sorted((r for r in sel if r in row_order),
                         key=row_order.__getitem__)
            emitted = self._emit_rows(st, target, handler, compiled, c, sel,
                                      row_order, kind, limit, trace, tagged,
                                      rcache)
            if emitted < limit and int(counts[ci]) > len(sel):
                if full_cand is None:
                    full_cand = self.executor.run(prog, bindings)
                sel_set = set(sel)
                rest = sorted((ri for ri in map(int, np.nonzero(full_cand[ci])[0])
                               if ri in row_order and ri not in sel_set),
                              key=row_order.__getitem__)
                self._emit_rows(st, target, handler, compiled, c, rest,
                                row_order, kind, limit - emitted, trace, tagged,
                                rcache)
        self.metrics.timer("host_format").observe(_time.perf_counter() - _tf)

    def _emit_rows(self, st, target, handler, compiled, c, rows, row_order,
                   kind, limit, trace, tagged, rcache) -> int:
        emitted = 0
        for row in rows:
            if limit is not None and emitted >= limit:
                break
            pair = self._row_review(st, handler, row, rcache)
            if pair is None:
                continue
            review, frozen, shared = pair
            results = self._pair_results(st, target, kind, compiled, c, row,
                                         review, frozen, trace, shared)
            for r in results:
                tagged.append(((row_order[row], kind,
                                (c.get("metadata") or {}).get("name", "")), r))
            emitted += len(results)
        return emitted

    def _scalar_kind(self, st, target, handler, compiled, constraints,
                     mask, ordered_rows, row_order, kind, limit, trace, tagged,
                     rcache):
        """Scalar fallback for unlowerable templates, restricted to
        match-mask candidates when a vector matcher exists."""
        emitted = {ci: 0 for ci in range(len(constraints))}
        for row in ordered_rows:
            if limit is not None and all(e >= limit for e in emitted.values()):
                break            # every constraint capped: stop scanning
            if st.table.meta_at(row) is None:
                continue
            pair = None
            for ci, c in enumerate(constraints):
                if limit is not None and emitted[ci] >= limit:
                    continue
                if mask is not None:
                    if not mask[ci, row]:
                        continue
                else:
                    if pair is None:
                        pair = self._row_review(st, handler, row, rcache)
                    if not any(True for _ in handler.matching_constraints(
                            pair[0], [c], st.table)):
                        continue
                if pair is None:
                    pair = self._row_review(st, handler, row, rcache)
                review, frozen, shared = pair
                results = self._pair_results(st, target, kind, compiled, c,
                                             row, review, frozen, trace,
                                             shared)
                for r in results:
                    tagged.append(((row_order[row], kind,
                                    (c.get("metadata") or {}).get("name", "")), r))
                emitted[ci] += len(results)
