"""Audit sweep manager.

Reference: pkg/audit/manager.go.  The loop that makes the engine a
product: every ``interval`` seconds run one sweep (:84-119) —

1. don't audit until the ConstraintTemplate CRD exists (:148-151);
2. ``client.audit()`` — the full cross-product evaluation (the
   north-star hot spot; here it runs on the jax driver's device path,
   with the per-constraint cap pushed down as a device top-k instead of
   the reference's format-everything-then-truncate);
3. group results per constraint selfLink capped at
   ``constraint_violations_limit`` (default 20, :35,161-199), truncating
   messages to 256 chars (:27-31,302-311);
4. discover all constraint kinds on constraints.gatekeeper.sh/v1alpha1
   (:153-159);
5. write ``status.violations`` + ``status.auditTimestamp`` on every
   constraint with exponential-backoff retry (:201-248,313-379);
   constraints with no violations get their stale ``status.violations``
   removed (:267-283).

Sweep observability (SURVEY §5 asks the build to beat the reference's
zero metrics): every sweep records device/host timings, result counts
and per-phase durations into ``last_sweep`` and the cumulative
``metrics`` registry.
"""

from __future__ import annotations

import threading
import time
from typing import Callable

from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.cluster.fake import FakeCluster
from gatekeeper_tpu.api.config import GVK
from gatekeeper_tpu.errors import ApiError, NotFoundError
from gatekeeper_tpu.utils.metrics import Metrics
from gatekeeper_tpu.utils.log import logger

_log = logger("audit")

CRD_NAME = "constrainttemplates.templates.gatekeeper.sh"
CRD_GVK = GVK("apiextensions.k8s.io", "v1beta1", "CustomResourceDefinition")
CONSTRAINTS_GV = "constraints.gatekeeper.sh/v1alpha1"
MSG_SIZE = 256

DEFAULT_AUDIT_INTERVAL = 60           # -auditInterval (manager.go:34)
DEFAULT_VIOLATIONS_LIMIT = 20         # -constraintViolationsLimit (:35)


def truncate_message(msg: str, size: int = MSG_SIZE) -> str:
    """manager.go:302-311 truncateString."""
    if len(msg) <= size:
        return msg
    if size > 3:
        size -= 3
    return msg[:size] + "..."


class AuditManager:
    def __init__(self, cluster: FakeCluster, client: Client,
                 interval: int = DEFAULT_AUDIT_INTERVAL,
                 violations_limit: int = DEFAULT_VIOLATIONS_LIMIT,
                 sleep: Callable[[float], None] = time.sleep,
                 now: Callable[[], float] = time.time,
                 metrics: Metrics | None = None):
        self.cluster = cluster
        self.client = client
        self.interval = interval
        self.violations_limit = violations_limit
        self._sleep = sleep
        self._now = now
        self.metrics = metrics if metrics is not None else Metrics()
        self.last_sweep: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # continuous enforcement (opt-in via attach_ledger): constraint
        # keys (kind, name) whose written status still reflects the
        # ledger's verdicts — a delta event dirties its key, and a
        # non-full sweep skips the status write for clean keys, so
        # status updates come from deltas instead of full resyncs
        self._ledger = None
        self._ledger_clean: set[tuple[str, str]] = set()
        self._ledger_lock = threading.Lock()
        self._reactor = None

    # ------------------------------------------------------------------
    # continuous enforcement subscription

    def attach_ledger(self, ledger) -> None:
        """Subscribe to a VerdictLedger's delta events (enforce/
        ledger.py).  Once attached, a non-full sweep skips the
        ``status.violations`` write for any ledger-maintained
        constraint whose verdicts did not change since its last write —
        the reference rewrites every constraint's status every
        ``--audit-interval`` regardless.  Default (unattached) behavior
        is byte-identical to before."""
        self._ledger = ledger
        with self._ledger_lock:
            self._ledger_clean.clear()
        ledger.subscribe(self._on_verdict_delta)

    def _on_verdict_delta(self, ev: dict) -> None:
        with self._ledger_lock:
            self._ledger_clean.discard((ev.get("kind", ""),
                                        ev.get("constraint", "")))

    def attach_reactor(self, reactor) -> None:
        """Let the event reactor (enforce/reactor.py) observe sweep
        completions: while its watch stream is degraded the periodic
        sweep is the enforcement freshness bound, and the reactor's
        health payload reports the age of the last one."""
        self._reactor = reactor

    # ------------------------------------------------------------------
    # one sweep

    def audit_once(self, full: bool = False) -> dict:
        """One audit() sweep (manager.go:84-119).  Returns the sweep
        report (also stored as ``last_sweep``).

        ``full=True`` forces a genuine full sweep: the driver's
        mask/bindings/format memoization is dropped for this sweep, and
        the report carries the driver's per-phase pipeline breakdown
        (``host_prep_s``, ``h2d_s``, ``device_s``, ``overlap_fraction``)
        so "full sweep" and "memoized steady" stay two separately
        metered numbers."""
        t0 = self._now()
        # audit.cycle parents the driver's audit.sweep span, so one
        # trace covers evaluate + status writes end to end
        from gatekeeper_tpu.obs.trace import get_tracer
        with get_tracer().span("audit.cycle", cat="audit", full=full):
            report = self._sweep(t0, full=full)
        if not report["skipped"]:
            report.setdefault("total_seconds", self._now() - t0)
            self.metrics.counter("audit_sweeps").inc()
            self.metrics.counter("audit_violations").inc(report["violations"])
            self.metrics.timer("audit_sweep_seconds").observe(
                report["total_seconds"])
            if self._reactor is not None:
                self._reactor.note_sweep()
        self.last_sweep = report
        if report["skipped"]:
            _log.debug("audit skipped: template CRD not deployed")
        else:
            _log.info("audit sweep complete",
                      violations=report["violations"],
                      constraints_updated=report["constraints_updated"],
                      seconds=round(report.get("total_seconds", 0.0), 3))
        return report

    def _sweep(self, t0: float, full: bool = False) -> dict:
        timestamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        report = {"timestamp": timestamp, "skipped": False,
                  "violations": 0, "constraints_updated": 0}

        # don't audit anything until the template CRD is deployed
        crd = self.cluster.try_get(CRD_GVK, CRD_NAME)
        if crd is None:
            # v1-first bootstrap stores the CRD under apiextensions v1
            crd = self.cluster.try_get(
                GVK("apiextensions.k8s.io", "v1",
                    "CustomResourceDefinition"), CRD_NAME)
        if crd is None:
            report["skipped"] = True
            return report

        t_eval = self._now()
        resp = self.client.audit(limit_per_constraint=self.violations_limit,
                                 full=full)
        results = resp.results()
        report["eval_seconds"] = self._now() - t_eval
        report["violations"] = len(results)
        report["full"] = full
        # surface the driver's pipeline phase breakdown (the jax driver
        # records host_prep_s / h2d_s / device_s / overlap_fraction per
        # sweep; the scalar oracle has no pipeline and reports nothing)
        phases = getattr(self.client.driver, "last_sweep_phases", None)
        if phases:
            for k in ("host_prep_s", "h2d_s", "device_s",
                      "overlap_fraction", "external", "dedup",
                      "attribution", "pages"):
                if k in phases:
                    report[k] = phases[k]

        # flight recorder: one structured event per sweep so a later
        # degradation dump shows the sweeps that led up to it
        from gatekeeper_tpu.obs.flightrecorder import record_event
        record_event("audit_sweep", full=full,
                     violations=report["violations"],
                     eval_seconds=report["eval_seconds"],
                     device_s=phases.get("device_s") if phases else None)

        # serving posture (resilience/supervisor): a sweep that ran —
        # partly or wholly — on the scalar/CPU fallback is correct but
        # must say so (maps to the reference's status.byPod[] operating
        # report; see BASELINE.md)
        from gatekeeper_tpu.resilience.supervisor import HEALTHY, \
            get_supervisor
        sup = get_supervisor()
        report["backend_state"] = sup.state
        if sup.state != HEALTHY:
            report["degraded"] = True
            report["degraded_reason"] = sup.reason
            self.metrics.counter("audit_sweeps_degraded").inc()

        update_lists = self._update_lists(results)

        # discovery: constraint kinds under constraints.gatekeeper.sh/v1alpha1
        try:
            kinds = self.cluster.server_resources_for_group_version(
                CONSTRAINTS_GV)
        except NotFoundError:
            # no constraint kind exists yet -> nothing to write (:111-115)
            return report

        t_write = self._now()
        # delta-skip is live only when the ledger actually served this
        # sweep (pages enabled, non-full) — a legacy sweep emits no
        # delta events, so skipping on its strength would go stale
        allow_skip = self._ledger is not None and not full and \
            bool((phases or {}).get("pages", {}).get("enabled"))
        updated, skipped = self._write_audit_results(
            kinds, update_lists, timestamp, allow_skip=allow_skip)
        report["write_seconds"] = self._now() - t_write
        report["constraints_updated"] = updated
        if self._ledger is not None:
            report["status_writes_skipped"] = skipped
            self.metrics.counter("status_writes_skipped").inc(skipped)
        self._maybe_snapshot_store()
        return report

    def _maybe_snapshot_store(self) -> None:
        """Warm-restart persistence: after a successful sweep, persist
        each target's columnar store so a restarted pod restores the
        inventory from disk instead of replaying it.  No-op unless
        GATEKEEPER_SNAPSHOT_DIR is set."""
        drv = getattr(self.client, "driver", None)
        if drv is None or not hasattr(drv, "save_store_snapshot"):
            return
        try:
            from gatekeeper_tpu.resilience import snapshot as _snap
            if not _snap.enabled():
                return
            for target in getattr(drv, "targets", {}):
                drv.save_store_snapshot(target)
        except Exception as e:   # noqa: BLE001 — persistence is
            _log.warning("store snapshot failed", error=e)   # best-effort

    def _update_lists(self, results) -> dict[str, list[dict]]:
        """Group results per constraint selfLink with cap + truncation
        (getUpdateListsFromAuditResponses, :161-199)."""
        out: dict[str, list[dict]] = {}
        for r in results:
            constraint = r.constraint or {}
            meta = constraint.get("metadata") or {}
            self_link = meta.get("selfLink") or \
                f"{constraint.get('kind', '')}/{meta.get('name', '')}"
            bucket = out.setdefault(self_link, [])
            if len(bucket) == self.violations_limit:
                continue
            resource = r.resource or {}
            rmeta = resource.get("metadata") or {}
            entry = {
                "kind": resource.get("kind", ""),
                "name": rmeta.get("name", ""),
                "message": truncate_message(r.msg),
                "enforcementAction": r.enforcement_action or "deny",
            }
            if rmeta.get("namespace"):
                entry["namespace"] = rmeta["namespace"]
            bucket.append(entry)
        return out

    def _write_audit_results(self, kinds: list[dict],
                             update_lists: dict[str, list[dict]],
                             timestamp: str,
                             allow_skip: bool = False) -> tuple[int, int]:
        """writeAuditResults + updateConstraintLoop (:201-248,313-379):
        list every constraint of every kind and write its status with
        exponential-backoff retry; constraints without violations get
        stale status.violations removed.  With ``allow_skip`` (ledger
        attached + paged sweep), ledger-maintained constraints whose
        verdicts didn't change since their last write are skipped."""
        pending: dict[str, dict] = {}
        for res in kinds:
            gvk = GVK("constraints.gatekeeper.sh", "v1alpha1", res["kind"])
            for item in self.cluster.list(gvk):
                link = (item.get("metadata") or {}).get("selfLink", "")
                pending[link] = item

        led_kinds = set(self._ledger.entries) if self._ledger is not None \
            else set()
        updated = 0
        skipped = 0
        delay = 1.0
        for _ in range(5):  # wait.Backoff{Duration:1s, Factor:2, Steps:5}
            for link, item in list(pending.items()):
                ckey = (item.get("kind", ""),
                        (item.get("metadata") or {}).get("name", ""))
                if allow_skip and ckey[0] in led_kinds:
                    with self._ledger_lock:
                        clean = ckey in self._ledger_clean
                    if clean:
                        del pending[link]
                        skipped += 1
                        continue
                try:
                    latest = self.cluster.get(
                        gvk_of_constraint(item),
                        (item.get("metadata") or {}).get("name", ""),
                        (item.get("metadata") or {}).get("namespace"))
                    self._update_constraint_status(
                        latest, update_lists.get(link, []), timestamp)
                except ApiError:
                    continue  # retried next backoff round
                del pending[link]
                updated += 1
                if self._ledger is not None and ckey[0] in led_kinds:
                    with self._ledger_lock:
                        self._ledger_clean.add(ckey)
            if not pending:
                break
            self._sleep(delay)
            delay *= 2
        return updated, skipped

    def _update_constraint_status(self, instance: dict,
                                  violations: list[dict],
                                  timestamp: str) -> None:
        """updateConstraintStatus (:250-300)."""
        status = instance.setdefault("status", {})
        status["auditTimestamp"] = timestamp
        if violations:
            status["violations"] = violations
        else:
            status.pop("violations", None)
        self.cluster.update(instance)

    # ------------------------------------------------------------------
    # loop (auditManagerLoop, :120-146)

    def start(self) -> None:
        if self._thread is not None:
            return
        # async warmup: compile (or load from the persistent cache) the
        # capped-audit executables before the first interval tick so a
        # restart or template churn doesn't stall the first sweep
        from gatekeeper_tpu.utils.compile_cache import warm_audit
        drv = getattr(self.client, "driver", None)
        if drv is not None and hasattr(drv, "executor"):
            for target in getattr(drv, "targets", {}):
                warm_audit(drv, target, cap=self.violations_limit)
            # backend recovery => the driver drops its executables; the
            # same warmup re-jits them onto the recovered backend in
            # the background so the next interval tick sweeps on-device
            from gatekeeper_tpu.resilience.supervisor import get_supervisor
            get_supervisor().add_recovery_listener(self, "_rewarm_on_recovery")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="audit-manager")
        self._thread.start()

    def _rewarm_on_recovery(self) -> None:
        """Recovery listener: re-warm the audit executables after the
        driver re-targeted the recovered backend."""
        from gatekeeper_tpu.utils.compile_cache import warm_audit
        drv = getattr(self.client, "driver", None)
        if drv is None or not hasattr(drv, "executor"):
            return
        for target in getattr(drv, "targets", {}):
            try:
                warm_audit(drv, target, cap=self.violations_limit)
            except Exception as e:   # noqa: BLE001 — next sweep warms
                _log.warning("post-recovery warmup failed",   # lazily
                             target=target, error=e)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._stop.wait(timeout=self.interval):
                return
            try:
                self.audit_once()
            except Exception as e:  # log-and-continue (:130-133)
                _log.error("audit sweep failed", error=e)
                self.metrics.counter("audit_errors").inc()


def gvk_of_constraint(obj: dict) -> GVK:
    return GVK.from_api_version(obj.get("apiVersion", ""),
                                obj.get("kind", ""))
