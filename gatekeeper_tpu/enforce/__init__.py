"""Continuous enforcement: incremental verdict maintenance.

The paged sweep (engine/jax_driver.py) re-evaluates only dirty pages ×
affected constraints and applies the per-page deltas to a
:class:`~gatekeeper_tpu.enforce.ledger.VerdictLedger`, which holds the
continuously-true violation set per kind and emits an ordered event
stream of violations appearing/clearing.
"""

from gatekeeper_tpu.enforce.ledger import (VerdictLedger, export_all,
                                           pages_mode)

__all__ = ["VerdictLedger", "export_all", "pages_mode"]
