"""Watch-driven continuous enforcement: the event reactor.

PR 14 made the verdict set delta-maintained (`enforce/ledger.py`), but
dirty bits were still folded in only when the next audit sweep ran, so
sweep cadence bounded detection latency.  The reactor couples the live
watch stream to the row-paged store: a single-object event becomes a
single-page re-eval (``Client.react`` -> ``JaxDriver.react_kind``) with
no sweep in between — wrapped in the robustness machinery that earns
``GATEKEEPER_PAGES`` its default-on:

* a **bounded per-kind event queue** with page-granular coalescing
  (repeat events for an object collapse to the latest; events landing
  in an already-pending row page cost nothing downstream — the store's
  per-page dirty bits are the unit of re-evaluation) and backpressure:
  a full queue is an ``overflow`` pathology that escalates to a relist,
  never an unbounded buffer or a silent drop;

* a **sequence / resourceVersion gap detector**: every event is stamped
  with a per-kind transport sequence at the ingest edge (the analogue
  of counting chunks on the HTTP watch stream).  Delivery classifies
  pathology — ``duplicate`` (seq already delivered; dropped, verdict
  application is idempotent anyway), ``out_of_order`` (late arrival
  below the high-water seq; *heals* a suspected gap, no resync),
  ``gap`` (a seq still missing after a grace window — something was
  dropped on the wire), ``stale_rv`` (an event older than the kind's
  resync watermark; dropped), and ``overflow`` (queue cap exceeded);

* a **three-rung resync ladder**: rung 1 re-evaluates pending dirty
  pages (``Client.react``); rung 2 relists the kind from the cluster
  (``Client.sync_kind``) and forces a whole-kind diff re-apply against
  the existing ledger entry (``Client.resync``) — missed appears
  surface, phantoms clear, and a *clean* resync is event-free; rung 3
  (a kind needing rung 2 twice inside the escalation window, or a
  reconnect after total stream loss) relists every attached kind and
  diff-rebuilds them all: the paged equivalent of upstream's
  fixed-interval full audit resync, but emitting exactly the true diff;

* **reconnect under exponential backoff + jitter** when the stream
  stalls, and graceful degradation to the existing sweep-cadence mode
  while unhealthy: ``live -> degraded(sweep-cadence) -> resyncing ->
  live``, every transition flight-recorded (``reactor_state`` events)
  and mirrored into ``probe --health`` and ``GET /debug/violations``.

Podracer (PAPERS.md) is the shape: event ingest stays decoupled from
device evaluation, so a sick stream degrades the *cadence*, never the
*verdicts* — while degraded, the audit sweep remains the source of
truth exactly as before this module existed.

Lock discipline: ``_rx_lock`` is a leaf.  Watch callbacks only enqueue
under it; ``pump()`` snapshots work under it, releases it, then calls
into the client (client RWLock -> driver locks).  No client or driver
call ever happens while ``_rx_lock`` is held, so the reactor adds no
edge into the engine's lock-order graph (``selflint --lockorder``).
"""

from __future__ import annotations

import collections
import os
import random
import threading
import time
import weakref
from typing import Any, Callable, Iterable

from gatekeeper_tpu.api.config import GVK
from gatekeeper_tpu.resilience import faults

# state machine: live -> degraded(sweep-cadence) -> resyncing -> live
LIVE = "live"
DEGRADED = "degraded"
RESYNCING = "resyncing"

PATHOLOGIES = ("gap", "duplicate", "out_of_order", "stale_rv", "overflow")

_STATE_GAUGE = {LIVE: 0, RESYNCING: 1, DEGRADED: 2}


def _env_f(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_i(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def queue_cap() -> int:
    """Per-kind pending-event bound (coalesced objects)."""
    return max(1, _env_i("GATEKEEPER_REACTOR_QUEUE", 256))


def gap_grace_s() -> float:
    """How long a missing transport seq may stay missing before it is
    confirmed as a ``gap`` (reordered frames arrive within this)."""
    return _env_f("GATEKEEPER_REACTOR_GAP_GRACE_S", 0.25)


def stall_timeout_s() -> float:
    """How long the stream may stall before the reactor declares the
    connection dead and degrades to sweep cadence."""
    return _env_f("GATEKEEPER_REACTOR_STALL_S", 0.5)


def backoff_base_s() -> float:
    return _env_f("GATEKEEPER_REACTOR_BACKOFF_S", 0.5)


def escalate_window_s() -> float:
    """Two rung-2 resyncs of the same kind inside this window take
    rung 3 instead."""
    return _env_f("GATEKEEPER_REACTOR_ESCALATE_S", 10.0)


def _rv_of(obj: Any) -> int | None:
    try:
        rv = (obj.get("metadata") or {}).get("resourceVersion")
    except AttributeError:
        return None
    if isinstance(rv, str) and rv.isdigit():
        return int(rv)
    if isinstance(rv, int):
        return rv
    return None


def _ident_of(obj: Any) -> tuple[str, str]:
    meta = (obj.get("metadata") or {}) if isinstance(obj, dict) else {}
    return (meta.get("namespace") or "", meta.get("name") or "")


class _KindStream:
    """Per-kind stream state: transport sequencing, gap suspicion, the
    coalesced pending queue, and the RV watermark guard."""

    def __init__(self, gvk: GVK, rv_floor: int = 0):
        self.gvk = gvk
        self.next_tseq = 1          # transport stamp counter (wire edge)
        self.hwm = 0                # highest tseq delivered
        self.delivered: set[int] = set()
        self.missing: dict[int, float] = {}    # tseq -> grace deadline
        # coalesced queue: object identity -> (event type, latest obj).
        # Insertion order is delivery order; re-delivery moves to end.
        self.pending: "collections.OrderedDict[tuple, tuple]" = \
            collections.OrderedDict()
        self.pending_pages: set = set()
        self.last_rv = 0
        self.rv_floor = rv_floor    # satellite-2 restart watermark
        self.rv_checked = rv_floor <= 0
        # recent object cache so watch_flood can replay a realistic
        # redundant-event storm (bounded by the kind's live set)
        self.recent: "collections.OrderedDict[tuple, dict]" = \
            collections.OrderedDict()
        self.resync_rung = 0        # highest rung requested, 0 = none
        self.resync_times: collections.deque = collections.deque(maxlen=4)
        self.reason = ""

    def reset_stream(self) -> None:
        """A reconnect starts a fresh transport stream: suspicion state
        is meaningless across it (seqs keep counting monotonically)."""
        self.hwm = self.next_tseq - 1
        self.delivered.clear()
        self.missing.clear()


class Reactor:
    """Couples cluster watch streams to the paged verdict ledger."""

    def __init__(self, client, cluster=None, target: str | None = None,
                 apply_objects: bool = False, seed: int = 0,
                 metrics=None, name: str = "reactor"):
        self._client = client
        self._cluster = cluster
        self._target = target or next(iter(client.targets))
        # apply_objects: the reactor itself upserts/removes event
        # objects into the store before reacting (chaos/bench/test
        # fixtures).  In the manager the sync controllers own store
        # writes and the reactor only schedules re-evaluation.
        self._apply_objects = apply_objects
        self._rng = random.Random(seed)
        self.metrics = metrics if metrics is not None \
            else getattr(client.driver, "metrics", None)
        self.name = name

        # _rx_lock is a LEAF: never held across client/driver calls.
        self._rx_lock = threading.RLock()
        self._streams: dict[str, _KindStream] = {}
        self._subs: dict[str, tuple[GVK, Callable[[], None]]] = {}
        self.state = LIVE
        self.state_since = time.monotonic()
        self.transitions: collections.deque = collections.deque(maxlen=64)
        self.counters: collections.Counter = collections.Counter()
        # stall / reconnect machinery
        self._stall_buf: list[tuple[str, Any]] = []
        self._stall_since: float | None = None
        self._reconnect_at: float | None = None
        self._backoff_n = 0
        self._last_sweep: float | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        _registry.add(self)

    # ------------------------------------------------------------------
    # subscriptions

    def attach(self, gvk: GVK) -> None:
        """Subscribe to one GVK's watch stream.  The kind's RV floor is
        seeded from the adopted ledger watermark (satellite 2): if the
        first event observed does not extend the watermark the snapshot
        was built at, the pg tier adopted stale state and the kind gets
        one forced resync."""
        kind = gvk.kind
        floor = 0
        fn = getattr(self._client.driver, "ledger_rv", None)
        if fn is not None:
            try:
                floor = int(fn(self._target, kind) or 0)
            except Exception:
                floor = 0
        with self._rx_lock:
            if kind in self._subs:
                return
            self._streams.setdefault(kind, _KindStream(gvk, rv_floor=floor))
        unsub = None
        if self._cluster is not None:
            unsub = self._cluster.watch(
                gvk, lambda ev, _k=kind: self.ingest(_k, ev))
        with self._rx_lock:
            self._subs[kind] = (gvk, unsub or (lambda: None))

    def detach(self, kind: str) -> None:
        with self._rx_lock:
            sub = self._subs.pop(kind, None)
            self._streams.pop(kind, None)
        if sub is not None:
            sub[1]()

    def sync_subscriptions(self, gvks: Iterable[GVK]) -> None:
        """Reconcile attached streams against the watch manager's
        active roster (called from the manager's poll loop)."""
        want = {g.kind: g for g in gvks}
        with self._rx_lock:
            have = set(self._subs)
        for kind in have - set(want):
            self.detach(kind)
        for kind, gvk in want.items():
            if kind not in have:
                self.attach(gvk)

    # ------------------------------------------------------------------
    # ingest: the wire edge

    def ingest(self, kind: str, event: Any) -> None:
        """Watch callback.  Stamps the transport sequence and delivers,
        with the watch-class fault seams applied in wire order: a stall
        buffers *before* stamping (bytes stuck in the socket), gap /
        duplicate / reorder act on stamped frames (the chunk made it
        onto the wire and was then lost / repeated / swapped), a flood
        replays recent frames after the real one."""
        with self._rx_lock:
            st = self._streams.get(kind)
            if st is None:
                return
            if faults.active("watch_stall"):
                if self._stall_since is None:
                    self._stall_since = time.monotonic()
                self._stall_buf.append((kind, event))
                self.counters["stalled_events"] += 1
                return
            self._flush_stall_locked()
            tseq = st.next_tseq
            st.next_tseq += 1
            if faults.take("watch_gap"):
                # frame lost on the wire: seq consumed, never delivered
                self.counters["faults_watch_gap"] += 1
                st.missing[tseq] = time.monotonic() + gap_grace_s()
                return
            if faults.take("watch_reorder"):
                # frame swapped with its successor: deliver seq+1's
                # payload slot first by holding this one until the next
                # frame is stamped — modelled by marking it missing now
                # and delivering late below the high-water mark
                self.counters["faults_watch_reorder"] += 1
                st.missing[tseq] = time.monotonic() + gap_grace_s()
                st.hwm = max(st.hwm, tseq)
                self._deliver_locked(st, tseq, event, late=True)
                return
            self._deliver_locked(st, tseq, event)
            if faults.take("watch_duplicate"):
                self.counters["faults_watch_duplicate"] += 1
                self._deliver_locked(st, tseq, event)
            if faults.active("watch_flood"):
                self.counters["faults_watch_flood"] += 1
                for obj in list(st.recent.values()):
                    fseq = st.next_tseq
                    st.next_tseq += 1
                    self._deliver_locked(
                        st, fseq, _Replay("MODIFIED", obj))

    def _flush_stall_locked(self) -> None:
        """Short stall (cleared before the timeout): the socket drained
        — stamp and deliver the buffered frames in order."""
        if not self._stall_buf:
            self._stall_since = None
            return
        buf, self._stall_buf = self._stall_buf, []
        self._stall_since = None
        for kind, ev in buf:
            st = self._streams.get(kind)
            if st is None:
                continue
            tseq = st.next_tseq
            st.next_tseq += 1
            self._deliver_locked(st, tseq, ev)

    def _deliver_locked(self, st: _KindStream, tseq: int, event: Any,
                        late: bool = False) -> None:
        """Classify one stamped frame and enqueue its work."""
        self.counters["events"] += 1
        if tseq in st.delivered:
            self._pathology_locked(st, "duplicate")
            return
        if tseq <= st.hwm:
            # late arrival below the high-water mark: heals a suspected
            # gap — the frame was reordered, not lost
            self._pathology_locked(st, "out_of_order")
            if st.missing.pop(tseq, None) is None and not late:
                # below hwm yet never suspected: stream restarted its
                # counter — treat as a gap-class break
                st.resync_rung = max(st.resync_rung, 2)
                st.reason = st.reason or "seq_regression"
        elif tseq == st.hwm + 1:
            st.hwm = tseq
            # contiguous advance may close the window over older seqs
            while st.hwm + 1 in st.delivered:
                st.delivered.discard(st.hwm + 1)
                st.hwm += 1
        else:
            # jumped ahead: everything between is a suspected gap with
            # a grace deadline (reordering heals it; expiry confirms)
            deadline = time.monotonic() + gap_grace_s()
            for s in range(st.hwm + 1, tseq):
                st.missing.setdefault(s, deadline)
            st.hwm = tseq
        st.delivered.add(tseq)
        if len(st.delivered) > 4096:
            st.delivered = {s for s in st.delivered if s > st.hwm - 1024}

        obj = getattr(event, "obj", None)
        etype = getattr(event, "type", "MODIFIED")
        rv = _rv_of(obj)
        if rv is not None:
            if not st.rv_checked:
                st.rv_checked = True
                if rv <= st.rv_floor:
                    # satellite 2: first observed RV does not extend the
                    # adopted snapshot watermark — the pg tier may hold
                    # verdicts for state this stream never saw
                    self._pathology_locked(st, "stale_rv")
                    st.resync_rung = max(st.resync_rung, 2)
                    st.reason = st.reason or "stale_rv_watermark"
                    return
            elif rv <= st.rv_floor:
                # pre-relist leftover: already incorporated by a resync
                self._pathology_locked(st, "stale_rv")
                return
            st.last_rv = max(st.last_rv, rv)

        ident = _ident_of(obj) if isinstance(obj, dict) else ("", "")
        if etype == "DELETED":
            st.recent.pop(ident, None)
        elif isinstance(obj, dict):
            st.recent[ident] = obj
            while len(st.recent) > 4 * queue_cap():
                st.recent.popitem(last=False)

        page = self._page_hint(obj)
        if page is not None and page in st.pending_pages \
                and ident in st.pending:
            self.counters["coalesced_pages"] += 1
        st.pending.pop(ident, None)     # re-delivery moves to end
        st.pending[ident] = (etype, obj)
        if page is not None:
            st.pending_pages.add(page)
        if len(st.pending) > queue_cap():
            # backpressure: drop the queue, escalate — the relist
            # supersedes every queued frame
            st.pending.clear()
            st.pending_pages.clear()
            self._pathology_locked(st, "overflow")
            st.resync_rung = max(st.resync_rung, 2)
            st.reason = st.reason or "queue_overflow"

    def _page_hint(self, obj: Any) -> int | None:
        """Row page of an event object, for coalescing accounting.
        Driver call, but read-only and internally locked; returns None
        for objects not (yet) resident."""
        fn = getattr(self._client.driver, "page_of_object", None)
        if fn is None or not isinstance(obj, dict):
            return None
        try:
            return fn(self._target, obj)
        except Exception:
            return None

    def _pathology_locked(self, st: _KindStream, cls: str) -> None:
        self.counters[f"pathology_{cls}"] += 1
        if self.metrics is not None:
            self.metrics.counter(f"reactor_pathology_{cls}_total").inc()

    # ------------------------------------------------------------------
    # pump: drain queues, confirm gaps, run the ladder

    def pump(self, budget: int | None = None) -> dict:
        """Process pending work.  Never called with ``_rx_lock`` held
        across client/driver calls: work is snapshotted under the lock,
        the lock released, then applied."""
        now = time.monotonic()
        summary = {"reacted": [], "resynced": [], "rung3": False}

        with self._rx_lock:
            # stall watchdog: a buffered stream older than the timeout
            # is a dead connection
            if self._stall_since is not None \
                    and now - self._stall_since > stall_timeout_s() \
                    and self.state != DEGRADED:
                self._stall_buf.clear()
                self._backoff_n = 0
                self._reconnect_at = now + self._next_backoff()
                self._set_state_locked(DEGRADED, "watch stream stalled")
            elif self._stall_since is None and self._stall_buf:
                self._flush_stall_locked()
            # confirm expired gap suspicions
            for st in self._streams.values():
                expired = [s for s, dl in st.missing.items() if dl <= now]
                if expired:
                    for s in expired:
                        st.missing.pop(s, None)
                        st.delivered.add(s)     # stop re-suspecting it
                    self._pathology_locked(st, "gap")
                    st.resync_rung = max(st.resync_rung, 2)
                    st.reason = st.reason or "gap_confirmed"

            degraded = self.state == DEGRADED
            reconnect_due = degraded and self._reconnect_at is not None \
                and now >= self._reconnect_at

        if degraded:
            if reconnect_due:
                self._try_reconnect()
            return summary

        # snapshot per-kind work under the lock, apply outside it
        with self._rx_lock:
            work: list[tuple[str, int, list, str]] = []
            for kind, st in self._streams.items():
                if st.resync_rung or st.pending:
                    batch = list(st.pending.values())
                    work.append((kind, st.resync_rung, batch, st.reason))
                    st.pending.clear()
                    st.pending_pages.clear()
                    st.resync_rung = 0
                    st.reason = ""
                    if budget is not None:
                        budget -= 1
                        if budget <= 0:
                            break

        rung3 = False
        for kind, rung, batch, reason in work:
            if rung >= 2:
                with self._rx_lock:
                    st = self._streams.get(kind)
                    if st is not None:
                        recent = [t for t in st.resync_times
                                  if now - t < escalate_window_s()]
                        st.resync_times.append(now)
                        if recent:
                            rung3 = True
                if rung3:
                    break
                self._resync_kind(kind, reason)
                summary["resynced"].append(kind)
            else:
                self._apply_batch(kind, batch)
                summary["reacted"].append(kind)
        if rung3:
            self._full_resync("escalated: repeated kind resync")
            summary["rung3"] = True

        with self._rx_lock:
            if self.state == RESYNCING and not any(
                    st.resync_rung for st in self._streams.values()):
                self._set_state_locked(LIVE, "resync complete")
        return summary

    # -- ladder rungs (no _rx_lock held) --------------------------------

    def _apply_batch(self, kind: str, batch: list) -> None:
        """Rung 1: fold the kind's dirty pages into the ledger."""
        if self._apply_objects:
            for etype, obj in batch:
                if not isinstance(obj, dict):
                    continue
                try:
                    if etype == "DELETED":
                        self._client.remove_data(obj)
                    else:
                        self._client.add_data(obj)
                except Exception:
                    self.counters["apply_errors"] += 1
        try:
            self._client.react(kind)
            self.counters["rung1"] += 1
            if self.metrics is not None:
                self.metrics.counter("reactor_react_total").inc()
        except Exception:
            self.counters["react_errors"] += 1

    def _resync_kind(self, kind: str, reason: str) -> None:
        """Rung 2: relist the kind, then force a whole-kind diff
        re-apply against the existing ledger entry."""
        with self._rx_lock:
            if self.state == LIVE:
                self._set_state_locked(RESYNCING, f"{kind}: {reason}")
            st = self._streams.get(kind)
            gvk = st.gvk if st is not None else None
        listed_rv = 0
        try:
            if gvk is not None and self._cluster is not None \
                    and self._apply_objects:
                objs = self._cluster.list(gvk)
                self._client.sync_kind(gvk.group_version, kind, objs)
                listed_rv = max(
                    [r for r in (_rv_of(o) for o in objs)
                     if r is not None], default=0)
            self._client.resync(kind)
            self.counters["rung2"] += 1
            if self.metrics is not None:
                self.metrics.counter("reactor_resync_total").inc()
        except Exception:
            self.counters["resync_errors"] += 1
        with self._rx_lock:
            st = self._streams.get(kind)
            if st is not None:
                st.reset_stream()
                if listed_rv:
                    st.rv_floor = max(st.rv_floor, listed_rv)
                    st.last_rv = max(st.last_rv, listed_rv)
                st.rv_checked = True

    def _full_resync(self, reason: str) -> None:
        """Rung 3: relist + diff-rebuild every attached kind — the
        paged analogue of upstream's full audit resync."""
        with self._rx_lock:
            if self.state != RESYNCING:
                self._set_state_locked(RESYNCING, reason)
            kinds = list(self._streams)
            for st in self._streams.values():
                st.pending.clear()
                st.pending_pages.clear()
                st.resync_rung = 0
                st.reason = ""
        for kind in kinds:
            self._resync_kind(kind, reason)
        self.counters["rung3"] += 1

    # -- reconnect ------------------------------------------------------

    def _next_backoff(self) -> float:
        base = backoff_base_s() * (2 ** self._backoff_n)
        self._backoff_n = min(self._backoff_n + 1, 8)
        delay = min(base, 30.0)
        return delay * (1.0 + 0.25 * self._rng.random())

    def _try_reconnect(self) -> None:
        """One reconnect attempt.  The stall fault models the server
        still refusing the stream: attempts while it is active fail and
        re-arm the (exponential, jittered) backoff."""
        self.counters["reconnect_attempts"] += 1
        if faults.active("watch_stall"):
            with self._rx_lock:
                self._stall_buf.clear()
                self._reconnect_at = time.monotonic() + self._next_backoff()
            return
        with self._rx_lock:
            self._stall_buf.clear()
            self._stall_since = None
            self._reconnect_at = None
            self._backoff_n = 0
            self._set_state_locked(RESYNCING, "reconnected; resyncing")
            kinds = list(self._streams)
            for st in self._streams.values():
                st.reset_stream()
                st.pending.clear()
                st.pending_pages.clear()
        self.counters["reconnects"] += 1
        for kind in kinds:
            self._resync_kind(kind, "post-reconnect relist")
        with self._rx_lock:
            self._set_state_locked(LIVE, "post-reconnect resync complete")

    # ------------------------------------------------------------------
    # state + introspection

    def _set_state_locked(self, state: str, reason: str) -> None:
        if state == self.state:
            return
        prev, self.state = self.state, state
        self.state_since = time.monotonic()
        self.transitions.append(
            {"from": prev, "to": state, "reason": reason,
             "t": time.time()})
        self.counters[f"state_{state}"] += 1
        if self.metrics is not None:
            self.metrics.gauge("reactor_state").set(_STATE_GAUGE[state])
        try:
            from gatekeeper_tpu.obs.flightrecorder import record_event
            record_event("reactor_state", reactor=self.name,
                         prev=prev, state=state, reason=reason)
        except Exception:
            pass

    def note_sweep(self) -> None:
        """Audit-manager hook: a full sweep just completed.  While
        degraded this is the sweep-cadence fallback actually doing the
        enforcement; record it so health output can show the cadence."""
        with self._rx_lock:
            self._last_sweep = time.monotonic()
            self.counters["sweeps_observed"] += 1

    def state_payload(self) -> dict:
        with self._rx_lock:
            now = time.monotonic()
            return {
                "name": self.name,
                "state": self.state,
                "state_age_s": round(now - self.state_since, 3),
                "kinds": {
                    k: {"pending": len(st.pending),
                        "pending_pages": len(st.pending_pages),
                        "hwm": st.hwm,
                        "suspected_gaps": len(st.missing),
                        "last_rv": st.last_rv,
                        "rv_floor": st.rv_floor}
                    for k, st in self._streams.items()},
                "counters": dict(self.counters),
                "transitions": list(self.transitions)[-8:],
                "last_sweep_age_s": (
                    round(now - self._last_sweep, 3)
                    if self._last_sweep is not None else None),
            }

    def healthy(self) -> bool:
        return self.state == LIVE

    # ------------------------------------------------------------------
    # pump thread

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.pump()
                except Exception:
                    self.counters["pump_errors"] += 1
                self._stop.wait(interval)

        self._thread = threading.Thread(
            target=loop, name=f"{self.name}-pump", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        for kind in list(self._subs):
            self.detach(kind)


class _Replay:
    """A flood-replayed frame (shaped like cluster.fake.Event)."""

    __slots__ = ("type", "obj")

    def __init__(self, etype: str, obj: dict):
        self.type = etype
        self.obj = obj


# ----------------------------------------------------------------------
# module registry: /debug/violations and probe --health enumerate live
# reactors the same way ledger.export_all() enumerates ledgers

_registry: "weakref.WeakSet[Reactor]" = weakref.WeakSet()


def export_state() -> list[dict]:
    return [r.state_payload() for r in list(_registry)]
