"""Device-resident paged store (GATEKEEPER_DEVPAGES).

PR 14/15 made continuous enforcement O(dirty) *on the host*: dirty
bits live in the Python path log, the paged sweep re-evaluates dirty
pages through the scalar oracle, and the VerdictLedger re-diffs rows in
Python.  This module is the device half (ROADMAP item 4, the Ragged
Paged Attention pattern): each eligible kind's column buffers stay
resident on device as fixed-geometry page arrays behind an on-device
page table (row -> slot indirection, free-list slots reused in place),
churn arrives as host-staged *row-sized* update records applied by a
jitted scatter (veval._scatter_rows), and the paged sweep computes the
violation mask AND its delta against the previous resident mask inside
one jitted call (veval.ProgramExecutor.eval_mask_delta) — a compact
(constraint, row, ±) stream the ledger consumes directly.

Soundness rests on the established over-approximation contract: a mask
bit 0 means *definitely no violation* (so 1→0 transitions are direct
ledger clears with no host eval), a mask bit 1 is a candidate the host
scalar oracle confirms (exact messages).  The device mask deliberately
excludes the ``__match__`` gate: every match input is row-local (own
labels/name/ns/kind; namespaceSelector churn forces a rebuild
upstream), so a match flip always dirties its own row and the dirty-row
confirm covers it — and the [C, R] match matrix never rides H2D.

``GATEKEEPER_DEVPAGES=off`` (the default) keeps the bit-identical
host-paged oracle — the same graduation pattern ``GATEKEEPER_PAGES``
followed: the device path must hold the randomized-churn and chaos-soak
event-stream parity gates before it defaults on.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any

import numpy as np


def residency_budget_bytes() -> int | None:
    """GATEKEEPER_DEVPAGES_BUDGET_BYTES: HBM the resident verdict
    masks may claim per kind.  None (default) = unbounded — every page
    stays device-resident, exactly the pre-Stage-8 behavior."""
    raw = os.environ.get("GATEKEEPER_DEVPAGES_BUDGET_BYTES")
    if not raw:
        return None
    try:
        return int(raw)
    except ValueError:
        return None

DELTA_K_MIN = 256
"""Smallest compiled width of the compact delta stream."""

DELTA_K_LADDER = 4
"""Overflow growth factor: a sweep whose changed-bit count exceeds the
compiled width k re-runs at bucket(count)*LADDER — one recompile per
bucket ever, never per sweep."""


def devpages_mode() -> bool:
    """GATEKEEPER_DEVPAGES: device-resident page table + in-jit verdict
    deltas.  Default OFF — ``=off`` is the host-paged oracle every
    parity gate diffs against (exactly how GATEKEEPER_PAGES graduated:
    soak first, default-on later)."""
    return os.environ.get("GATEKEEPER_DEVPAGES", "off").lower() in (
        "on", "1", "true")


def delta_bucket(n: int) -> int:
    """Power-of-two width for the compact delta stream."""
    k = DELTA_K_MIN
    while k < n:
        k <<= 1
    return k


@dataclasses.dataclass
class KindPages:
    """One kind's device-resident paged state.

    ``mask`` is the resident [c_pad, r_pad] violation mask the next
    sweep deltas against; ``page_table`` the on-device row->slot
    indirection ([slots] int32, identity while remap_generation is
    stable — rebuilt, not mutated, on remap); ``free`` mirrors the
    table's free slot list at last build (reused slots keep their
    device storage; the delta stream reports the clear+appear pair when
    a different identity lands in a freed slot).  All device handles
    here are REBOUND on update, never mutated in place — the only
    mutation seam is the jitted scatter inside veval (selflint
    --rebind enforces this for engine/ and enforce/)."""

    kind: str
    mask: Any = None              # device [c_pad, r_pad] bool
    page_table: Any = None        # device [slots] int32
    c_pad: int = 0
    slots: int = 0                # r_pad: fixed page-array capacity
    page_rows: int = 0
    n_pages: int = 0
    free: tuple = ()              # free-slot mirror at last build
    gen: int = -1                 # table generation the mask reflects
    remap: int = -1
    conver: int = -1
    k: int = DELTA_K_MIN          # compiled delta-stream width
    ij_dev: dict = dataclasses.field(default_factory=dict)
    #   inv-join input records: name -> device array (r:ij.<join>.*)
    ij_host: dict = dataclasses.field(default_factory=dict)
    #   the numpy twins the scatter staged from (identity-compared)
    geometry_adopted: bool = False
    resident: Any = None          # ResidencyPlanner under a budget

    def geometry(self) -> dict:
        """Plain-data device-pagemap geometry for the pg snapshot tier:
        enough for a warm restart to adopt the paged layout (slot
        capacity, page shape, free list) with zero rebuilds — the mask
        itself is NOT persisted (it is re-derived on the first delta
        sweep from the adopted ledger's baseline)."""
        return {"slots": int(self.slots), "c_pad": int(self.c_pad),
                "page_rows": int(self.page_rows),
                "n_pages": int(self.n_pages),
                "free": [int(f) for f in self.free]}

    def adopt_geometry(self, geom: dict) -> bool:
        """Seed the paged layout from a snapshot geometry payload; the
        first device sweep then builds its mask into the adopted shape
        instead of deriving geometry cold."""
        try:
            self.slots = int(geom["slots"])
            self.c_pad = int(geom["c_pad"])
            self.page_rows = int(geom["page_rows"])
            self.n_pages = int(geom["n_pages"])
            self.free = tuple(int(f) for f in geom.get("free", ()))
            self.geometry_adopted = True
            return True
        except (KeyError, TypeError, ValueError):
            return False


def fresh_stats() -> dict:
    """Per-sweep devpages accounting (the ``devpages`` stanza)."""
    return {"kinds_device": 0, "kinds_fallback": 0,
            "fallback_reasons": {}, "scatter_rows": 0,
            "h2d_bytes": 0, "h2d_scatter_bytes": 0,
            "delta_events": 0, "delta_overflows": 0,
            "rows_confirmed": 0, "direct_clears": 0,
            "inv_joins_device": 0, "geometry_adopted": 0,
            "mask_builds": 0,
            "resident_spills": 0, "resident_restores": 0,
            "resident_pages_device": 0}


class ResidencyPlanner:
    """Certificate-driven resident-set planner for one kind's verdict
    mask (the ROADMAP item-4 spill ladder).

    When the Stage-8 certificate's devpages claim at the ACTUAL sweep
    geometry exceeds ``GATEKEEPER_DEVPAGES_BUDGET_BYTES``, the full
    [c_pad, r_pad] resident mask no longer lives on device between
    sweeps.  Instead the planner keeps an LRU-chosen *hot* subset of
    pages in a compact device buffer of ``n_slots`` page-sized slots
    (the largest power-of-two slot count whose bytes fit the budget —
    the slot ladder), spills cold pages' bits to a pinned host mirror,
    and reconstructs the exact full mask on demand before the next
    delta sweep: hot pages scatter back from the slot buffer, spilled
    pages restore through the existing row-scatter path
    (``veval.ProgramExecutor._scatter_rows``), and never-violating
    pages are zeros by the over-approximation contract.  Freed slots
    are reused in place when the working set shifts.  ``expand`` after
    ``store`` is bit-identical to the always-resident mask by
    construction — the parity tests force a tiny budget and diff
    against the unbudgeted oracle.

    Inactive (``active`` False) whenever the claim fits the budget:
    zero overhead, ``kp.mask`` holds the full mask exactly as before.
    """

    def __init__(self, budget: int, c_pad: int, r_pad: int,
                 page_rows: int, cert=None):
        self.budget = int(budget)
        self.c_pad = int(c_pad)
        self.r_pad = int(r_pad)
        self.page_rows = max(int(page_rows), 1)
        self.n_pages = -(-self.r_pad // self.page_rows)
        dims = {"c": self.c_pad, "r": self.r_pad}
        if cert is not None and getattr(cert, "has_r", False):
            claim = cert.devpages_bytes(dims, delta_k=0)
        else:
            claim = 2 * self.c_pad * self.r_pad + 4 * self.r_pad
        self.active = claim > self.budget
        page_bytes = self.c_pad * self.page_rows
        n_slots = 1
        while (n_slots * 2 * page_bytes <= self.budget
               and n_slots * 2 < self.n_pages):
            n_slots *= 2
        self.n_slots = n_slots
        self.slot_of: dict[int, int] = {}     # page -> slot
        self.free: list[int] = list(range(n_slots - 1, -1, -1))
        self.lru: list[int] = []              # pages, most-recent last
        self.dev_buf = None                   # [c_pad, n_slots*page_rows]
        self.host_mask = np.zeros((self.c_pad, self.r_pad), dtype=bool)
        self.spilled: set[int] = set()        # pages living host-side
        self.spilled_any: set[int] = set()    # spilled pages with a bit
        self.has_mask = False
        self.spills = 0                       # pages spilled to host
        self.restores = 0                     # pages restored to device

    def compatible(self, c_pad: int, r_pad: int, page_rows: int) -> bool:
        return (self.c_pad == c_pad and self.r_pad == r_pad
                and self.page_rows == max(int(page_rows), 1))

    def holds(self, c_pad: int, r_pad: int) -> bool:
        """True when expand() can reproduce a stored full mask at this
        geometry."""
        return (self.active and self.has_mask
                and self.c_pad == c_pad and self.r_pad == r_pad)

    def _page_rows_abs(self, page: int) -> np.ndarray:
        lo = page * self.page_rows
        rows = np.arange(lo, lo + self.page_rows, dtype=np.int64)
        # the tail page pads by repeating the last real row: gather
        # duplicates read one bit twice, scatter duplicates write the
        # same bit twice — bit-identity holds either way
        return np.minimum(rows, self.r_pad - 1)

    def touch(self, pages) -> None:
        """LRU bump: these pages were involved in the current sweep."""
        for p in sorted(pages):
            if 0 <= p < self.n_pages:
                if p in self.lru:
                    self.lru.remove(p)
                self.lru.append(p)

    def store(self, new_mask) -> None:
        """Adopt a freshly computed full mask: keep the ``n_slots``
        most-recently-touched pages in the device slot buffer, spill
        the rest to the host mirror, release the full-size device
        array."""
        import jax.numpy as jnp
        hot = self._hot_pages()
        # free slots of pages leaving the hot set (reused below)
        for p in [p for p in self.slot_of if p not in hot]:
            self.free.append(self.slot_of.pop(p))
        for p in hot:
            if p not in self.slot_of:
                self.slot_of[p] = self.free.pop()
        gather = np.empty((self.n_slots * self.page_rows,),
                          dtype=np.int64)
        # slots without a page gather row 0 (never expanded back)
        gather[:] = 0
        for p, s in self.slot_of.items():
            gather[s * self.page_rows:(s + 1) * self.page_rows] = \
                self._page_rows_abs(p)
        self.dev_buf = jnp.take(new_mask, jnp.asarray(gather), axis=1)
        cold = [p for p in range(self.n_pages) if p not in hot]
        newly_spilled = [p for p in cold if p not in self.spilled]
        self.spills += len(newly_spilled)
        if cold:
            rows = np.concatenate([self._page_rows_abs(p) for p in cold])
            bits = np.asarray(jnp.take(new_mask,
                                       jnp.asarray(rows), axis=1))
            self.host_mask[:, rows] = bits
            for j, p in enumerate(cold):
                seg = bits[:, j * self.page_rows:(j + 1) * self.page_rows]
                if seg.any():
                    self.spilled_any.add(p)
                else:
                    self.spilled_any.discard(p)
        self.spilled = set(cold)
        self.has_mask = True

    def expand(self, ex):
        """Reconstruct the exact full [c_pad, r_pad] mask: hot pages
        scatter back from the slot buffer, spilled non-zero pages
        restore host->device through the executor's existing
        row-scatter path, all-zero pages stay zeros."""
        import jax.numpy as jnp
        full = jnp.zeros((self.c_pad, self.r_pad), dtype=bool)
        if self.slot_of:
            rows = np.concatenate(
                [self._page_rows_abs(p)
                 for p in sorted(self.slot_of)])
            idx = np.concatenate(
                [np.arange(self.slot_of[p] * self.page_rows,
                           (self.slot_of[p] + 1) * self.page_rows)
                 for p in sorted(self.slot_of)])
            full = full.at[:, rows].set(
                jnp.take(self.dev_buf, jnp.asarray(idx), axis=1))
        restore = sorted(self.spilled & self.spilled_any)
        if restore:
            rows = np.concatenate(
                [self._page_rows_abs(p) for p in restore])
            full = ex._scatter_rows("__resident__", full,
                                    self.host_mask, rows, False, axis=1)
            self.restores += len(restore)
        return full

    def _hot_pages(self) -> set[int]:
        """The ``n_slots`` most-recently-touched pages (LRU order,
        seeded with the lowest page indices before any touch)."""
        hot: list[int] = []
        for p in reversed(self.lru):
            if len(hot) >= self.n_slots:
                break
            hot.append(p)
        for p in range(self.n_pages):
            if len(hot) >= self.n_slots:
                break
            if p not in hot:
                hot.append(p)
        return set(hot)


def inv_join_binding_names(join_name: str) -> tuple[str, str, str, str]:
    """The four device input records backing one in-jit inventory join
    (src ids, inventory ids, inventory-side row filter, name ids).
    The ``r:`` prefix keys them into ir/prep.binding_axes as
    row-axis arrays so the scatter seam and R-chunking see them."""
    return (f"r:ij.{join_name}.src", f"r:ij.{join_name}.inv",
            f"r:ij.{join_name}.sel", f"r:ij.{join_name}.names")


def build_inv_join_inputs(req, table, r_pad: int) -> dict[str, np.ndarray]:
    """Host twins of one join's device input records, padded to the
    slot capacity.  Column extraction is table-cached (O(dirty) after
    the first build); padding fills MISSING / False so padded slots can
    never join."""
    from gatekeeper_tpu.store.columns import ColSpec
    from gatekeeper_tpu.store.interner import MISSING
    n = table.n_rows
    ident = table.identity()
    kid = table.interner.lookup(req.kind)

    def _pad(a: np.ndarray, fill) -> np.ndarray:
        out = np.full((r_pad,), fill, dtype=a.dtype)
        out[:n] = a[:n]
        return out

    src = table.column(ColSpec(req.src_path, "val")).ids
    inv = table.column(ColSpec(req.inv_path, "val")).ids
    sel = ident.alive & (ident.kind_ids == kid)
    if req.namespaced_only:
        sel = sel & (ident.ns_ids != MISSING)
    if kid == MISSING:
        sel = np.zeros_like(sel)
    names = ident.name_ids
    s, i, f, m = inv_join_binding_names(req.name)
    return {s: _pad(src.astype(np.int32), MISSING),
            i: _pad(inv.astype(np.int32), MISSING),
            f: _pad(sel.astype(bool), False),
            m: _pad(names.astype(np.int32), MISSING)}
