"""VerdictLedger: the current violation set, maintained by deltas.

The reference's audit rebuilds every constraint's violation list from
scratch each ``--audit-interval`` (pkg/audit/manager.go) — the cluster
state between sweeps is a mystery and the work is O(cluster) per tick.
Here the paged sweep applies per-page deltas in place, so the ledger is
*continuously true*: for every eligible kind it holds exactly the
confirmed violating rows, and every change to that set is emitted as an
ordered event (flight-recorded, served at ``GET /debug/violations``,
and offered to the audit manager so ``status.byPod[]`` updates come
from deltas instead of full resyncs).

Correctness contract (oracle-driven, like every engine change): with
``GATEKEEPER_PAGES=off`` the legacy full path runs, and the ledger's
event stream under pages=on must equal the diff of consecutive full
sweeps for the same churn sequence — ordered, no duplicates, no silent
drops.  Events are canonically ordered per sweep: kinds sorted, then
constraints sorted, then rows in audit rank order, clears before
appears within a row (msgs sorted).
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import threading
import weakref
from typing import Any, Callable

from gatekeeper_tpu.obs.flightrecorder import record_event

EVENT_RING = 4096
"""Delta events retained for /debug/violations; older ones age out
(the flight recorder keeps its own ring, subscribers see every event
at emit time — the ring is a debugging window, not the stream)."""


def pages_mode() -> bool:
    """GATEKEEPER_PAGES: the paged O(dirty) sweep + VerdictLedger.
    Default ON as of the reactor PR — the path has soaked under the
    chaos harness with watch-class faults injected (gap, duplicate,
    reorder, stall, flood) with the ledger stream bit-identical to the
    full-sweep diff throughout (ROADMAP item 2 graduation).  ``off``
    selects the legacy full-kind path (with PR-10 footprint selective
    invalidation) — still maintained as the shipping oracle every
    parity gate diffs against."""
    import os
    return os.environ.get("GATEKEEPER_PAGES", "on").lower() in (
        "on", "1", "true")


def constraints_digest(constraints: list[dict]) -> str:
    """Content digest of a kind's constraint set — revalidation key for
    ledger entries adopted from a snapshot (the in-process
    ``con_version`` counter restarts with the process)."""
    blob = json.dumps(sorted(
        json.dumps(c, sort_keys=True, default=str) for c in constraints))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclasses.dataclass
class LedgerEntry:
    """One kind's confirmed violation set + the guards under which it
    was computed.  ``rows`` maps row -> (identity, {constraint name ->
    [Result, ...]}) and is UNCAPPED: the sweep's per-constraint result
    cap is applied at serve time by walking rows in rank order, which
    reproduces the full path's top-k + refill emission exactly."""
    gen: int = -1                 # table generation the entry reflects
    kgen: int = -1                # key_generation at last apply
    remap: int = -1               # remap_generation (row-id validity)
    n_rows: int = -1
    conver: int = -1              # driver constraint-set version
    condigest: str = ""           # content digest (snapshot adoption)
    rows: dict[int, tuple[tuple, dict[str, list]]] = \
        dataclasses.field(default_factory=dict)
    full_builds: int = 0          # cold/fallback rebuilds of this entry
    rv: int = 0                   # watch resourceVersion watermark the
    #                               entry was built/adopted at (stamped
    #                               at snapshot save; guards the pg
    #                               tier against stale watch state)

    def size(self) -> int:
        return sum(len(rs) for _ident, by_c in self.rows.values()
                   for rs in by_c.values())


_registry: "weakref.WeakSet[VerdictLedger]" = weakref.WeakSet()
_registry_lock = threading.Lock()


class VerdictLedger:
    """Per-target ledger of confirmed violations, delta-maintained."""

    def __init__(self, target: str):
        self.target = target
        self.entries: dict[str, LedgerEntry] = {}
        self.events: collections.deque = collections.deque(
            maxlen=EVENT_RING)
        self.seq = 0
        self._subscribers: list[Callable[[dict], None]] = []
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.add(self)

    # -- maintenance -----------------------------------------------------

    def entry(self, kind: str) -> LedgerEntry:
        ent = self.entries.get(kind)
        if ent is None:
            ent = self.entries[kind] = LedgerEntry()
        return ent

    def drop(self, kind: str) -> None:
        self.entries.pop(kind, None)

    def set_row(self, kind: str, row: int, ident: tuple,
                by_constraint: dict[str, list]) -> list[dict]:
        """Replace one row's verdicts, emitting the delta events.
        ``by_constraint`` maps constraint name -> confirmed Results; an
        empty mapping (or a dead row) clears the row.  Events follow
        the canonical within-row order: per constraint (caller iterates
        constraints sorted), clears before appears, msgs sorted."""
        ent = self.entry(kind)
        old = ent.rows.get(row)
        old_by_c = old[1] if old is not None else {}
        old_ident = old[0] if old is not None else None
        # a freed row reused by a DIFFERENT resource between sweeps is
        # a clear+appear pair even when the msgs coincide (the full
        # sweep diff keys on the resource ref, so nothing cancels);
        # only a same-identity replace gets the multiset cancellation
        same = (old is None or not by_constraint
                or old_ident == ident)
        old_ref = self._resource_ref(
            old_ident if old_ident is not None else ident)
        new_ref = self._resource_ref(ident) if by_constraint else old_ref
        out: list[dict] = []
        for cname in sorted(set(old_by_c) | set(by_constraint)):
            old_msgs = collections.Counter(
                r.msg for r in old_by_c.get(cname, ()))
            new_msgs = collections.Counter(
                r.msg for r in by_constraint.get(cname, ()))
            if same:
                if old_msgs == new_msgs:
                    continue
                to_clear = old_msgs - new_msgs
                to_appear = new_msgs - old_msgs
            else:
                to_clear, to_appear = old_msgs, new_msgs
            for msg in sorted(to_clear.elements()):
                out.append(self._emit(kind, cname, old_ref, msg, "clear"))
            for msg in sorted(to_appear.elements()):
                out.append(self._emit(kind, cname, new_ref, msg, "appear"))
        if by_constraint:
            ent.rows[row] = (ident, by_constraint)
        else:
            ent.rows.pop(row, None)
        return out

    def _resource_ref(self, ident: tuple) -> str:
        ns, name = (ident + (None, None))[:2] if ident else (None, None)
        return f"{ns}/{name}" if ns else str(name)

    def _emit(self, kind: str, cname: str, resource: str, msg: str,
              op: str) -> dict:
        with self._lock:
            self.seq += 1
            ev = {"seq": self.seq, "target": self.target, "kind": kind,
                  "constraint": cname, "resource": resource, "msg": msg,
                  "op": op}
            self.events.append(ev)
        record_event("verdict_delta", **ev)
        for cb in list(self._subscribers):
            try:
                cb(ev)
            except Exception:   # noqa: BLE001 — a bad subscriber must
                pass            # not poison the sweep
        return ev

    def subscribe(self, cb: Callable[[dict], None]) -> None:
        """Register a delta consumer (e.g. the audit manager's
        status.byPod[] updater).  Called synchronously at emit time,
        exceptions swallowed."""
        self._subscribers.append(cb)

    # -- introspection ---------------------------------------------------

    def total_violations(self) -> int:
        return sum(e.size() for e in self.entries.values())

    def export(self, events: int = 256) -> dict:
        """JSON-safe view for /debug/violations and probe --pages."""
        kinds = {}
        for kind in sorted(self.entries):
            ent = self.entries[kind]
            kinds[kind] = {
                "rows": len(ent.rows), "violations": ent.size(),
                "gen": ent.gen, "n_rows": ent.n_rows,
                "full_builds": ent.full_builds,
            }
        with self._lock:
            tail = list(self.events)[-events:]
        return {"target": self.target, "seq": self.seq, "kinds": kinds,
                "violations_total": self.total_violations(),
                "events": tail}

    # -- snapshot (the "pg" warm-restart tier) ---------------------------

    def snapshot_payload(self) -> dict:
        """Plain-data payload for resilience/snapshot.save_pagemap —
        per kind the confirmed rows plus the constraint-set digest and
        row-space shape that gate adoption.  Row ids are valid against
        a table restored from the companion store snapshot (restore
        bulk-upserts in saved row order)."""
        out = {}
        for kind, ent in self.entries.items():
            out[kind] = {
                "condigest": ent.condigest, "n_rows": ent.n_rows,
                "rv": ent.rv,
                "rows": {row: (ident, {c: list(rs)
                               for c, rs in by_c.items()})
                         for row, (ident, by_c) in ent.rows.items()},
            }
        return out

    def adopt(self, kind: str, payload: dict, condigest: str,
              table, conver: int) -> bool:
        """Adopt one kind's snapshot payload as the live entry — only
        when the constraint set (by content) and row space still match
        the restored table.  Guards are stamped from the restored
        table's counters: the snapshot pair (store + pagemap) was taken
        atomically, so the just-restored rows ARE the state the
        verdicts were computed over."""
        if payload.get("condigest") != condigest:
            return False
        if payload.get("n_rows") != table.n_rows:
            return False
        ent = LedgerEntry(
            gen=table.generation, kgen=table.key_generation,
            remap=table.remap_generation, n_rows=table.n_rows,
            conver=conver, condigest=condigest,
            rv=int(payload.get("rv", 0) or 0),
            rows={row: (tuple(ident), dict(by_c))
                  for row, (ident, by_c) in payload["rows"].items()})
        self.entries[kind] = ent
        return True


def export_all(events: int = 256) -> dict:
    """All live ledgers, for GET /debug/violations."""
    with _registry_lock:
        ledgers = list(_registry)
    return {"ledgers": [led.export(events)
                        for led in sorted(ledgers,
                                          key=lambda x: x.target)]}
