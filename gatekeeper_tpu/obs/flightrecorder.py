"""Degradation flight recorder — the last N events, dumped on failure.

A bounded ring (``GATEKEEPER_FLIGHT_RING``, default 2048) of small
structured events: sweep phase summaries, admission batch sizes, probe
results, supervisor transitions, circuit-breaker flips, fault trips.
Recording is cheap (one dict + deque append under a lock) and never
raises, so it is safe to call from any seam including failure paths.

``dump(reason)`` serializes the ring plus the tracer's current span
export (so the in-flight sweep's span tree survives) to a JSON
artifact under ``GATEKEEPER_FLIGHT_DIR`` (default
``$TMPDIR/gatekeeper-flight``), pruning to the newest
``GATEKEEPER_FLIGHT_KEEP`` (default 20) files.  It is invoked
automatically on supervisor degradation, ``GATEKEEPER_FAULT=*`` trips,
and bench rc-3 exits — PR-7's "fail loudly" with evidence attached.

Admission corpus (whatif/replay.py, rollout/): with
``GATEKEEPER_FLIGHT_ADMISSION=1`` the webhook also persists each
AdmissionReview — payload capped at
``GATEKEEPER_FLIGHT_PAYLOAD_BYTES`` (default 8192) and redacted
(``metadata.managedFields`` stripped, secret-shaped values replaced)
BEFORE anything touches disk — into the durable capture log
(rollout/capture.py): segmented, CRC-framed ``capture-*.seg`` files
under ``<flight dir>/capture``, fed through a bounded queue so the
admission path never blocks on disk (drops are counted, committed
records survive crashes).  ``load_admission_corpus`` reads the capture
segments back (plus any legacy ``admission-*.jsonl`` files from older
recordings) for replay.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Any, Optional

from gatekeeper_tpu.utils.log import logger

log = logger("obs.flight")


def _flight_dir() -> str:
    return os.environ.get(
        "GATEKEEPER_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "gatekeeper-flight"))


# ---------------------------------------------------------------------------
# admission corpus hygiene: redact, then cap, then persist

REDACTED = "[REDACTED]"

_SECRET_KEY_HINTS = ("password", "passwd", "token", "secret", "credential",
                     "apikey", "api_key", "authorization", "private_key",
                     "privatekey", "client_key")


def _secret_shaped_key(key: str) -> bool:
    k = key.lower()
    return any(h in k for h in _SECRET_KEY_HINTS)


def redact_payload(obj: Any, _secretish: bool = False) -> Any:
    """Deep-copying redaction for a to-be-persisted k8s object:
    ``metadata.managedFields`` is dropped outright, string values under
    secret-shaped keys (and every string of a Secret's ``data`` /
    ``stringData`` maps) are replaced with a marker.  Only strings are
    secret material — booleans/numbers under a matching key (e.g. the
    ``automountServiceAccountToken`` flag) pass through, so replaying a
    redacted corpus still evaluates them faithfully.  The input is
    never mutated — the webhook still evaluates the original."""
    if isinstance(obj, dict):
        is_secret = obj.get("kind") == "Secret"
        out = {}
        for k, v in obj.items():
            if not isinstance(k, str):
                out[k] = redact_payload(v, _secretish)
                continue
            if k == "metadata" and isinstance(v, dict):
                out[k] = {mk: redact_payload(mv, _secretish)
                          for mk, mv in v.items() if mk != "managedFields"}
                continue
            blob = (_secretish or _secret_shaped_key(k)
                    or (is_secret and k in ("data", "stringData")))
            out[k] = redact_payload(v, blob)
        return out
    if isinstance(obj, list):
        return [redact_payload(v, _secretish) for v in obj]
    if _secretish and isinstance(obj, str):
        return REDACTED
    return obj


def payload_byte_cap() -> int:
    try:
        return int(os.environ.get("GATEKEEPER_FLIGHT_PAYLOAD_BYTES", "8192"))
    except ValueError:
        return 8192


def cap_payload(obj: Any, cap: Optional[int] = None) -> Any:
    """Bound one persisted object to ``cap`` serialized bytes.  An
    oversize object is deterministically reduced to its identifying
    envelope (apiVersion/kind/name/namespace/labels) plus a truncation
    marker carrying the original size — replay treats truncated events
    as unreplayable rather than silently evaluating a partial object."""
    if cap is None:
        cap = payload_byte_cap()
    try:
        size = len(json.dumps(obj, sort_keys=True, default=str))
    except Exception:
        return {"__truncated__": True, "__bytes__": -1}
    if size <= cap or not isinstance(obj, dict):
        return obj
    meta = obj.get("metadata") or {}
    return {
        "apiVersion": obj.get("apiVersion"),
        "kind": obj.get("kind"),
        "metadata": {k: meta.get(k) for k in ("name", "namespace", "labels")
                     if k in meta},
        "__truncated__": True,
        "__bytes__": size,
    }


def admission_corpus_enabled() -> bool:
    return os.environ.get("GATEKEEPER_FLIGHT_ADMISSION", "") not in ("", "0")


class FlightRecorder:
    def __init__(self, ring: Optional[int] = None):
        if ring is None:
            ring = int(os.environ.get("GATEKEEPER_FLIGHT_RING", "2048"))
        self._lock = threading.Lock()
        self._events: collections.deque[dict] = collections.deque(maxlen=ring)
        self._dump_seq = 0
        self._capture = None           # lazy rollout.capture.CaptureLog
        self._capture_dir: Optional[str] = None

    def _capture_log(self):
        """The durable capture log under the CURRENT flight dir,
        re-opened if GATEKEEPER_FLIGHT_DIR moved (tests point each case
        at a fresh tmpdir).  Called under self._lock."""
        from gatekeeper_tpu.rollout.capture import CaptureLog
        d = os.path.join(_flight_dir(), "capture")
        if self._capture is None or self._capture_dir != d:
            if self._capture is not None:
                try:
                    self._capture.close()
                except Exception:
                    pass
            self._capture = CaptureLog(d)
            self._capture_dir = d
        return self._capture

    def capture_stats(self) -> Optional[dict]:
        """Capture-log health (segments, drops, queue depth); None when
        nothing was ever captured by this recorder."""
        with self._lock:
            return self._capture.stats() if self._capture else None

    def record(self, etype: str, **fields: Any) -> None:
        """Append one event; never raises."""
        try:
            ev = {"ts": round(time.time(), 6), "type": etype}
            try:
                from gatekeeper_tpu.obs.trace import get_tracer
                tid = get_tracer().current_trace_id()
                if tid:
                    ev["trace"] = tid
            except Exception:
                pass
            for k, v in fields.items():
                if isinstance(v, float):
                    v = round(v, 6)
                ev[k] = v
            with self._lock:
                self._events.append(ev)
        except Exception:
            pass

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump(self, reason: str, extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring + current span export to a JSON artifact.
        Returns the path, or None on any failure (dumping evidence
        must never become its own failure mode)."""
        try:
            d = _flight_dir()
            os.makedirs(d, exist_ok=True)
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            try:
                from gatekeeper_tpu.obs.trace import get_tracer
                trace = get_tracer().export()
            except Exception:
                trace = {"traceEvents": []}
            payload = {
                "reason": reason,
                "dumped_at": round(time.time(), 6),
                "pid": os.getpid(),
                "events": self.snapshot(),
                "trace": trace,
            }
            if extra:
                payload["extra"] = extra
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            path = os.path.join(
                d, f"flight-{stamp}-{os.getpid()}-{seq:03d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True)
            os.replace(tmp, path)
            self._prune(d)
            log.info("flight recorder dumped", reason=reason, path=path,
                     events=len(payload["events"]))
            return path
        except Exception as exc:  # pragma: no cover - best effort
            try:
                log.warning("flight recorder dump failed", error=exc)
            except Exception:
                pass
            return None

    @staticmethod
    def _prune(d: str, prefix: str = "flight-",
               suffix: str = ".json") -> None:
        keep = int(os.environ.get("GATEKEEPER_FLIGHT_KEEP", "20"))
        try:
            files = sorted(
                f for f in os.listdir(d)
                if f.startswith(prefix) and f.endswith(suffix))
            for stale in files[:-keep] if keep > 0 else files:
                try:
                    os.unlink(os.path.join(d, stale))
                except OSError:
                    pass
        except OSError:
            pass

    def record_admission(self, request: dict, allowed: bool,
                         verdicts: Optional[list] = None,
                         warnings: Optional[list] = None) -> None:
        """Record one AdmissionReview as a replayable corpus event.

        The ring always gets a small summary event.  When the corpus is
        enabled (GATEKEEPER_FLIGHT_ADMISSION=1) the full — redacted,
        byte-capped — request is enqueued onto this recorder's durable
        capture log (rollout/capture.py): the admission path only pays
        a queue put, the background writer owns the disk.  Never
        raises: recording must not become an admission failure mode."""
        try:
            obj = (request.get("object") or {})
            self.record("admission",
                        operation=request.get("operation"),
                        kind=((request.get("kind") or {}).get("kind")),
                        name=(obj.get("metadata") or {}).get("name"),
                        allowed=allowed, verdicts=len(verdicts or ()))
            if not admission_corpus_enabled():
                return
            cap = payload_byte_cap()
            req = dict(request)
            for f in ("object", "oldObject"):
                if isinstance(req.get(f), dict):
                    req[f] = cap_payload(redact_payload(req[f]), cap)
            event = {
                "ts": round(time.time(), 6),
                "request": req,
                "allowed": bool(allowed),
                "warnings": list(warnings or ()),
                "verdicts": [
                    {"kind": (v.constraint or {}).get("kind"),
                     "name": ((v.constraint or {}).get("metadata") or {})
                     .get("name"),
                     "action": v.enforcement_action,
                     "msg": v.msg}
                    for v in (verdicts or ())],
            }
            with self._lock:
                self._capture_log().append(event)
        except Exception:  # pragma: no cover - best effort
            pass


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record_event(etype: str, **fields: Any) -> None:
    """Module-level convenience for instrumentation seams."""
    get_flight_recorder().record(etype, **fields)


def load_admission_corpus(directory: Optional[str] = None) -> list[dict]:
    """Read the recorded admission corpus back into replayable events.

    Primary source is the durable capture log's segments under
    ``<directory>/capture`` (committed records, in segment order across
    process restarts — open in-process writers are flushed first so a
    same-process record-then-replay flow sees everything it enqueued).
    Legacy ``admission-*.jsonl`` files from older recordings are still
    read, torn/unparsable lines skipped."""
    d = directory or _flight_dir()
    events: list[dict] = []
    try:
        from gatekeeper_tpu.rollout import capture as _capture
        cap_dir = os.path.join(d, "capture")
        _capture.flush_all()
        recs, _report = _capture.scan(cap_dir)
        events.extend(ev for ev in recs
                      if isinstance(ev, dict) and "request" in ev)
    except Exception:   # noqa: BLE001 — capture dir may not exist yet
        pass
    try:
        names = sorted(f for f in os.listdir(d)
                       if f.startswith("admission-") and f.endswith(".jsonl"))
    except OSError:
        return events
    for name in names:
        try:
            with open(os.path.join(d, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(ev, dict) and "request" in ev:
                        events.append(ev)
        except OSError:
            continue
    return events
