"""Degradation flight recorder — the last N events, dumped on failure.

A bounded ring (``GATEKEEPER_FLIGHT_RING``, default 2048) of small
structured events: sweep phase summaries, admission batch sizes, probe
results, supervisor transitions, circuit-breaker flips, fault trips.
Recording is cheap (one dict + deque append under a lock) and never
raises, so it is safe to call from any seam including failure paths.

``dump(reason)`` serializes the ring plus the tracer's current span
export (so the in-flight sweep's span tree survives) to a JSON
artifact under ``GATEKEEPER_FLIGHT_DIR`` (default
``$TMPDIR/gatekeeper-flight``), pruning to the newest
``GATEKEEPER_FLIGHT_KEEP`` (default 20) files.  It is invoked
automatically on supervisor degradation, ``GATEKEEPER_FAULT=*`` trips,
and bench rc-3 exits — PR-7's "fail loudly" with evidence attached.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Any, Optional

from gatekeeper_tpu.utils.log import logger

log = logger("obs.flight")


def _flight_dir() -> str:
    return os.environ.get(
        "GATEKEEPER_FLIGHT_DIR",
        os.path.join(tempfile.gettempdir(), "gatekeeper-flight"))


class FlightRecorder:
    def __init__(self, ring: Optional[int] = None):
        if ring is None:
            ring = int(os.environ.get("GATEKEEPER_FLIGHT_RING", "2048"))
        self._lock = threading.Lock()
        self._events: collections.deque[dict] = collections.deque(maxlen=ring)
        self._dump_seq = 0

    def record(self, etype: str, **fields: Any) -> None:
        """Append one event; never raises."""
        try:
            ev = {"ts": round(time.time(), 6), "type": etype}
            try:
                from gatekeeper_tpu.obs.trace import get_tracer
                tid = get_tracer().current_trace_id()
                if tid:
                    ev["trace"] = tid
            except Exception:
                pass
            for k, v in fields.items():
                if isinstance(v, float):
                    v = round(v, 6)
                ev[k] = v
            with self._lock:
                self._events.append(ev)
        except Exception:
            pass

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._events]

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def dump(self, reason: str, extra: Optional[dict] = None) -> Optional[str]:
        """Write the ring + current span export to a JSON artifact.
        Returns the path, or None on any failure (dumping evidence
        must never become its own failure mode)."""
        try:
            d = _flight_dir()
            os.makedirs(d, exist_ok=True)
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            try:
                from gatekeeper_tpu.obs.trace import get_tracer
                trace = get_tracer().export()
            except Exception:
                trace = {"traceEvents": []}
            payload = {
                "reason": reason,
                "dumped_at": round(time.time(), 6),
                "pid": os.getpid(),
                "events": self.snapshot(),
                "trace": trace,
            }
            if extra:
                payload["extra"] = extra
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            path = os.path.join(
                d, f"flight-{stamp}-{os.getpid()}-{seq:03d}.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, sort_keys=True)
            os.replace(tmp, path)
            self._prune(d)
            log.info("flight recorder dumped", reason=reason, path=path,
                     events=len(payload["events"]))
            return path
        except Exception as exc:  # pragma: no cover - best effort
            try:
                log.warning("flight recorder dump failed", error=exc)
            except Exception:
                pass
            return None

    @staticmethod
    def _prune(d: str) -> None:
        keep = int(os.environ.get("GATEKEEPER_FLIGHT_KEEP", "20"))
        try:
            files = sorted(
                f for f in os.listdir(d)
                if f.startswith("flight-") and f.endswith(".json"))
            for stale in files[:-keep] if keep > 0 else files:
                try:
                    os.unlink(os.path.join(d, stale))
                except OSError:
                    pass
        except OSError:
            pass


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder


def record_event(etype: str, **fields: Any) -> None:
    """Module-level convenience for instrumentation seams."""
    get_flight_recorder().record(etype, **fields)
