"""Observability: span tracer, flight recorder, device-time attribution.

The reference ships zero tracing — its only instrumentation is the
never-served OPA metrics registry (SURVEY §5).  This package is the
window into the pipeline that registry was supposed to be: spans with
context propagation across the webhook → batcher → device dispatch and
audit → per-stage sweep paths (Chrome trace-event export, Perfetto-
loadable), a bounded flight recorder dumped on degradation, and
per-template attribution of measured device time via the PR-5 static
cost model.
"""

from gatekeeper_tpu.obs.flightrecorder import (FlightRecorder,
                                               get_flight_recorder,
                                               record_event)
from gatekeeper_tpu.obs.trace import Span, Tracer, get_tracer

__all__ = [
    "Span", "Tracer", "get_tracer",
    "FlightRecorder", "get_flight_recorder", "record_event",
]
