"""Span-based tracer with context propagation and Chrome trace export.

Spans nest through a ``contextvars`` context: a span opened while
another is active becomes its child and inherits the trace id; a span
opened with no active context starts a fresh trace.  Cross-thread
hand-offs (the sweep's dispatch pool, the micro-batcher worker) pass
the parent explicitly via ``parent=tracer.current()`` since context
vars do not flow into pre-existing pool threads.

Finished spans land in a bounded ring (``GATEKEEPER_TRACE_RING``,
default 4096) so memory is flat no matter how long the process runs;
open spans are tracked separately so a crash dump can include the
in-flight sweep.  ``export()`` renders the ring as Chrome trace-event
JSON (``ph:"X"`` complete events, microsecond timestamps) which
Perfetto and chrome://tracing load directly.

Tracing is on by default — the bench's ``trace_overhead`` row holds it
under 2% on the memoized steady sweep — and ``GATEKEEPER_TRACE=off``
kills it, making ``span()`` a no-op yielding ``None``.

Importing this module registers a context provider with
``utils.log`` so every structured log line emitted under a span
carries ``trace=<id> span=<id>``.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import itertools
import json
import os
import threading
import time
from typing import Any, Iterator, Optional, Tuple

from gatekeeper_tpu.utils import log as _log

# (trace_id, span_id) of the innermost active span on this context
_CTX: contextvars.ContextVar[Optional[Tuple[str, int]]] = \
    contextvars.ContextVar("gatekeeper_span", default=None)

_span_ids = itertools.count(1)
_trace_ids = itertools.count(1)


class Span:
    """One timed region. ``args`` may be mutated while the span is
    open to attach results (e.g. allowed/denied) post-hoc."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "t0_us", "dur_us", "tid", "args")

    def __init__(self, name: str, cat: str, trace_id: str, span_id: int,
                 parent_id: int, t0_us: float, tid: int, args: dict):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0_us = t0_us
        self.dur_us: Optional[float] = None  # None while open
        self.tid = tid
        self.args = args

    def event(self, now_us: Optional[float] = None) -> dict:
        """Chrome trace-event dict (ph "X" complete event)."""
        dur = self.dur_us
        args = dict(self.args)
        args["trace_id"] = self.trace_id
        args["span_id"] = self.span_id
        if self.parent_id:
            args["parent_span_id"] = self.parent_id
        if dur is None:  # still open: clamp to "now", flag it
            dur = max(0.0, (now_us or self.t0_us) - self.t0_us)
            args["incomplete"] = True
        return {
            "name": self.name, "cat": self.cat, "ph": "X",
            "ts": round(self.t0_us, 3), "dur": round(dur, 3),
            "pid": os.getpid(), "tid": self.tid, "args": args,
        }


class Tracer:
    """Process-wide span collector.  Thread-safe; near-zero cost when
    ``enabled`` is False (one attribute check per span site)."""

    def __init__(self, ring: Optional[int] = None):
        if ring is None:
            ring = int(os.environ.get("GATEKEEPER_TRACE_RING", "4096"))
        self._lock = threading.Lock()
        self._done: collections.deque[Span] = collections.deque(maxlen=ring)
        self._open: dict[int, Span] = {}
        self._epoch = time.perf_counter()
        self.enabled = os.environ.get("GATEKEEPER_TRACE", "on") != "off"

    # -- clock -------------------------------------------------------
    def _now_us(self) -> float:
        return (time.perf_counter() - self._epoch) * 1e6

    # -- context -----------------------------------------------------
    def current(self) -> Optional[Tuple[str, int]]:
        """(trace_id, span_id) of the active span, for explicit
        cross-thread parenting."""
        return _CTX.get()

    def current_trace_id(self) -> Optional[str]:
        ctx = _CTX.get()
        return ctx[0] if ctx else None

    # -- span lifecycle ----------------------------------------------
    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host",
             parent: Optional[Tuple[str, int]] = None,
             **args: Any) -> Iterator[Optional[Span]]:
        if not self.enabled:
            yield None
            return
        ctx = parent if parent is not None else _CTX.get()
        if ctx is None:
            trace_id = f"t{next(_trace_ids):06d}.{os.getpid()}"
            parent_id = 0
        else:
            trace_id, parent_id = ctx
        sid = next(_span_ids)
        sp = Span(name, cat, trace_id, sid, parent_id, self._now_us(),
                  threading.get_ident() & 0xFFFFFFFF, dict(args))
        with self._lock:
            self._open[sid] = sp
        token = _CTX.set((trace_id, sid))
        try:
            yield sp
        finally:
            _CTX.reset(token)
            sp.dur_us = max(0.0, self._now_us() - sp.t0_us)
            with self._lock:
                self._open.pop(sid, None)
                self._done.append(sp)

    def add_complete(self, name: str, cat: str, t0: float, t1: float,
                     parent: Optional[Tuple[str, int]] = None,
                     **args: Any) -> None:
        """Record an already-measured region (``t0``/``t1`` are
        ``time.perf_counter()`` values) as a complete span — for hot
        loops that already meter themselves and multi-exit blocks
        where a context manager would be intrusive."""
        if not self.enabled:
            return
        ctx = parent if parent is not None else _CTX.get()
        if ctx is None:
            trace_id = f"t{next(_trace_ids):06d}.{os.getpid()}"
            parent_id = 0
        else:
            trace_id, parent_id = ctx
        sp = Span(name, cat, trace_id, next(_span_ids), parent_id,
                  (t0 - self._epoch) * 1e6,
                  threading.get_ident() & 0xFFFFFFFF, dict(args))
        sp.dur_us = max(0.0, (t1 - t0) * 1e6)
        with self._lock:
            self._done.append(sp)

    # -- export ------------------------------------------------------
    def export(self, trace_id: Optional[str] = None) -> dict:
        """Chrome trace-event JSON object.  Open spans are included as
        clamped-to-now complete events flagged ``incomplete`` so a
        mid-sweep dump still shows the sweep's span tree."""
        now = self._now_us()
        with self._lock:
            spans = list(self._done) + list(self._open.values())
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        return {
            "traceEvents": [s.event(now) for s in spans],
            "displayTimeUnit": "ms",
        }

    def export_json(self, trace_id: Optional[str] = None) -> str:
        return json.dumps(self.export(trace_id), sort_keys=True)

    def reset(self) -> None:
        """Drop all recorded spans (tests)."""
        with self._lock:
            self._done.clear()
            self._open.clear()


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def get_tracer() -> Tracer:
    global _tracer
    if _tracer is None:
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


def _log_context() -> Optional[dict]:
    ctx = _CTX.get()
    if ctx is None:
        return None
    return {"trace": ctx[0], "span": ctx[1]}


_log.set_context_provider(_log_context)
