"""Per-template device-time attribution via the static cost model.

A full sweep measures ``device_s`` as one number (and, per kind, the
individual dispatch block times).  This module apportions the sweep's
total device time across member templates using their PR-5
:class:`CostVector` units as weights — the attributed shares sum to
the measured total by construction — and reports predicted-vs-measured
drift per template against the running calibration, feeding each
template's measured seconds back into ``costmodel.record_sample`` so
the seconds-per-unit scale tracks reality.

Exposed surfaces: ``last_sweep_phases["attribution"]`` on full sweeps,
labelled gauges ``template_device_seconds{template=...}`` /
``template_cost_drift{template=...}`` in the Prometheus exposition,
and the ``probe --trace`` artifact.
"""

from __future__ import annotations

from typing import Optional

from gatekeeper_tpu.analysis import costmodel
from gatekeeper_tpu.utils.log import logger

log = logger("obs.attribution")


def attribute_sweep(entries: list, device_s: float, n_rows: int,
                    measured: Optional[dict] = None,
                    metrics=None) -> dict:
    """Apportion one sweep's measured device seconds across templates.

    ``entries`` is ``[(kind, lowered, n_constraints), ...]`` for every
    device-dispatched kind in the sweep; ``measured`` optionally maps
    kind -> that kind's individually measured device block seconds
    (full sweeps time each dispatch).  Returns the attribution stanza
    stored in ``last_sweep_phases``.  Never raises — a template whose
    estimate fails gets unit weight.
    """
    units: dict[str, float] = {}
    for kind, lowered, n_cons in entries:
        try:
            units[kind] = max(
                1.0, costmodel.estimate(lowered, n_rows, n_cons).units())
        except Exception as exc:
            log.warning("cost estimate failed", template=kind, error=exc)
            units[kind] = 1.0
    total_units = sum(units.values())
    scale = costmodel.current_scale()

    rows = []
    for kind in sorted(units):
        u = units[kind]
        share = u / total_units if total_units else 0.0
        attributed = share * device_s
        meas = (measured or {}).get(kind)
        predicted = u * scale if scale > 0 else None
        drift = None
        ref = meas if meas else attributed
        if predicted is not None and ref > 0:
            drift = (predicted - ref) / ref
        rows.append({
            "template": kind,
            "units": round(u, 1),
            "share": round(share, 6),
            "device_seconds": round(attributed, 9),
            "measured_seconds": round(meas, 9) if meas is not None else None,
            "predicted_seconds": (round(predicted, 9)
                                  if predicted is not None else None),
            "drift": round(drift, 4) if drift is not None else None,
        })
        # feed the calibration loop with the best per-kind truth we
        # have: the individually timed dispatch block when available,
        # else the apportioned share
        costmodel.record_sample(u, meas if meas else attributed)
        if metrics is not None:
            try:
                metrics.gauge("template_device_seconds",
                              template=kind).set(round(attributed, 9))
                if drift is not None:
                    metrics.gauge("template_cost_drift",
                                  template=kind).set(round(drift, 4))
            except Exception:
                pass

    return {
        "device_s": round(device_s, 9),
        "scale_seconds_per_unit": scale,
        "templates": rows,
    }
