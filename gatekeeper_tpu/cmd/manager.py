"""Process entry point — driver → backend → client → controllers →
webhook → audit wiring.

Reference: cmd/manager/main.go:35-103.  Same wiring order: construct the
engine driver (tracing on, main.go:68), the Backend + Client with the
K8s target (main.go:69-74), add controllers (controller.AddToManager,
main.go:81), webhook (main.go:87) and audit manager (main.go:93), then
start everything and block (main.go:100).

Flags mirror the reference's flag set (audit/manager.go:34-35,
webhook/policy.go:47-49).  The cluster is this build's in-memory
apiserver (a real deployment would swap in an adapter with the same
surface); ``--demo`` seeds the demo/basic scenario (1k namespaces +
required-labels template) and runs one audit sweep so the whole stack
is observable end-to-end.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from gatekeeper_tpu.utils.log import logger
from gatekeeper_tpu.api.config import GVK, empty_config_object
from gatekeeper_tpu.api.externaldata import PROVIDER_GVK
from gatekeeper_tpu.audit.manager import (CRD_NAME, AuditManager,
                                          DEFAULT_AUDIT_INTERVAL,
                                          DEFAULT_VIOLATIONS_LIMIT)
from gatekeeper_tpu.client.client import Backend
from gatekeeper_tpu.cluster.fake import FakeCluster
from gatekeeper_tpu.controllers.config import CONFIG_GVK
from gatekeeper_tpu.controllers.constrainttemplate import TEMPLATE_GVK
from gatekeeper_tpu.controllers.registry import ControlPlane, add_to_manager
from gatekeeper_tpu.engine.jax_driver import JaxDriver
from gatekeeper_tpu.target.k8s import K8sValidationTarget
from gatekeeper_tpu.utils.metrics import Metrics
from gatekeeper_tpu.webhook.batcher import MicroBatcher
from gatekeeper_tpu.webhook.policy import ValidationHandler
from gatekeeper_tpu.webhook.server import DEFAULT_PORT, WebhookServer

_log = logger("manager")

NS_GVK = GVK("", "v1", "Namespace")


def bootstrap_cluster(cluster) -> None:
    """Install what deploy/gatekeeper.yaml installs: the base CRDs /
    served kinds the controllers and audit manager expect.  A real
    apiserver (cluster.kube.KubeCluster) serves core kinds already and
    gets only the ConstraintTemplate CRD applied; the FakeCluster also
    needs its discovery seeded."""
    if hasattr(cluster, "register_kind"):
        cluster.register_kind(TEMPLATE_GVK, "constrainttemplates")
        cluster.register_kind(CONFIG_GVK, "configs")
        cluster.register_kind(PROVIDER_GVK, "providers")
        # core kinds every conformant apiserver serves (sync configs
        # routinely watch these; the fake's discovery must agree)
        for kind, plural in (("Namespace", "namespaces"), ("Pod", "pods"),
                             ("Service", "services"),
                             ("ConfigMap", "configmaps"),
                             ("Secret", "secrets"),
                             ("ServiceAccount", "serviceaccounts")):
            cluster.register_kind(GVK("", "v1", kind), plural)
        cluster.register_kind(GVK("apps", "v1", "Deployment"), "deployments")
        cluster.register_kind(GVK("networking.k8s.io", "v1", "Ingress"),
                              "ingresses")
    from gatekeeper_tpu.webhook.bootstrap import apply_crd
    apply_crd(cluster, CRD_NAME, "templates.gatekeeper.sh", "v1alpha1",
              "ConstraintTemplate", "constrainttemplates")
    apply_crd(cluster, "configs.config.gatekeeper.sh", "config.gatekeeper.sh",
              "v1alpha1", "Config", "configs")
    apply_crd(cluster, "providers.externaldata.gatekeeper.sh",
              "externaldata.gatekeeper.sh", "v1beta1", "Provider",
              "providers", namespaced=False)


class Manager:
    """Everything main() builds, held together for tests and the demo."""

    def __init__(self, args: argparse.Namespace,
                 cluster=None):
        self.metrics = Metrics()
        if cluster is not None:
            self.cluster = cluster
        elif getattr(args, "kubeconfig", None):
            # real apiserver: the whole control plane binds to it through
            # the cluster protocol (reference main.go:43-51)
            from gatekeeper_tpu.cluster.kube import KubeCluster
            self.cluster = KubeCluster.from_kubeconfig(args.kubeconfig)
        else:
            self.cluster = FakeCluster()
        # async clusters deliver watch events on stream threads; the
        # deterministic pump must settle instead of assuming inline events
        self.async_cluster = not isinstance(self.cluster, FakeCluster)
        bootstrap_cluster(self.cluster)
        if getattr(args, "engine_worker_url", None):
            # engine-process split: the evaluation engine (and the TPU)
            # live in a worker process behind the Driver seam
            # (reference drivers/remote analogue, remote.go:49)
            from gatekeeper_tpu.client.remote_driver import RemoteDriver
            driver = RemoteDriver(args.engine_worker_url)
        else:
            driver = JaxDriver(tracing=False)
        self.client = Backend(driver).new_client([K8sValidationTarget()])
        # external-data runtime: installed process-globally (the
        # `external_data` builtin resolves it there) and instrumented
        # through the manager's metrics registry
        from gatekeeper_tpu.externaldata.runtime import (ExternalDataRuntime,
                                                         set_runtime)
        self.external_data = ExternalDataRuntime(metrics=self.metrics)
        set_runtime(self.external_data)
        self.plane: ControlPlane = add_to_manager(
            self.cluster, self.client, external_data=self.external_data)
        from gatekeeper_tpu.webhook.overload import OverloadController
        from gatekeeper_tpu.webhook.server import REQUEST_TIMEOUT_S
        self.batcher = MicroBatcher(
            # shed_actions is consulted at evaluation time (not submit
            # time): a batch formed while healthy but evaluated under
            # brownout still sheds dryrun/warn work
            lambda reqs: self.client.review_batch(
                reqs, shed_actions=self.overload.shed_actions() or None),
            max_batch=args.max_batch, max_wait=args.batch_window_ms / 1000.0,
            metrics=self.metrics,
            # a submit must give up before the server's own request
            # deadline so the caller still gets a clean 500, not a
            # severed connection
            submit_timeout=REQUEST_TIMEOUT_S * 0.9,
            prefetch=self.client.prefetch_external,
            predict_seconds=self.client.predict_review_seconds,
            # Stage-7: deadline shrinks step along the certified
            # compile-surface rungs instead of halving blindly
            certified_rungs=lambda: self.client.certified_review_rungs(
                args.max_batch))
        self.overload = OverloadController(self.batcher.depth,
                                           self.batcher.capacity,
                                           metrics=self.metrics)
        self.handler = ValidationHandler(self.client, cluster=self.cluster,
                                         batcher=self.batcher,
                                         metrics=self.metrics,
                                         overload=self.overload,
                                         log=lambda m: _log.info("admission trace", dump=m))
        # TLS engages when the cert dir exists (reference /certs,
        # policy.go:76-79); otherwise plain HTTP (tests/demo)
        import os as _os
        cert_dir = getattr(args, "cert_dir", None)
        cert_dir = cert_dir if cert_dir and _os.path.isdir(cert_dir) else None
        self.webhook = WebhookServer(self.handler, port=args.port,
                                     cert_dir=cert_dir) \
            if args.port >= 0 else None
        self._manual_deploy = getattr(args, "enable_manual_deploy", False)
        self._cert_dir = cert_dir
        self.audit = AuditManager(self.cluster, self.client,
                                  interval=args.audit_interval,
                                  violations_limit=args.constraint_violations_limit,
                                  metrics=self.metrics)
        # continuous enforcement (pages on): couple the watch stream to
        # the paged store so a single-object event becomes a single-page
        # re-eval, with the periodic sweep as degraded-mode fallback.
        # The sync controllers own store writes; the reactor only
        # schedules re-evaluation (apply_objects stays False).
        from gatekeeper_tpu.enforce.ledger import pages_mode
        self.reactor = None
        if pages_mode():
            from gatekeeper_tpu.enforce.reactor import Reactor
            self.reactor = Reactor(self.client, cluster=self.cluster,
                                   metrics=self.metrics)
            self.audit.attach_reactor(self.reactor)
        self.watch_poll_interval = getattr(args, "watch_poll_interval", 5.0)
        self._poll_stop = None
        self._poll_thread = None

    def start(self) -> None:
        self.plane.mgr.start()
        self.batcher.start()
        if self.webhook is not None:
            self.webhook.start()
            if self.webhook.tls and not self._manual_deploy:
                # self-register the ValidatingWebhookConfiguration +
                # cert secret + service (policy.go:81-100)
                from gatekeeper_tpu.webhook.bootstrap import bootstrap_webhook
                try:
                    bootstrap_webhook(self.cluster, self._cert_dir,
                                      self.webhook.port)
                except Exception as e:
                    _log.error("webhook bootstrap failed", error=e)
        self.audit.start()
        if self.reactor is not None:
            self.reactor.sync_subscriptions(
                self.plane.watch_manager.watched_gvks())
            self.reactor.start()
        # roster poll loop (reference updateManagerLoop, 5 s —
        # watch/manager.go:165-178): a GVK whose CRD becomes served
        # AFTER registration is picked up without any roster mutation
        self._poll_stop = threading.Event()

        def poll_loop():
            while not self._poll_stop.wait(self.watch_poll_interval):
                try:
                    self.plane.watch_manager.poll_once()
                    if self.reactor is not None:
                        # the reactor's subscriptions track the watch
                        # roster: a kind gaining/losing sync intent
                        # attaches/detaches its stream
                        self.reactor.sync_subscriptions(
                            self.plane.watch_manager.watched_gvks())
                except Exception as e:   # log-and-continue like the loop
                    _log.warning("watch poll error", error=e)
        self._poll_thread = threading.Thread(
            target=poll_loop, daemon=True, name="watch-roster-poll")
        self._poll_thread.start()

    def stop(self) -> None:
        if getattr(self, "_poll_stop", None) is not None:
            self._poll_stop.set()
            self._poll_thread.join(timeout=10)
            self._poll_stop = None
        if self.reactor is not None:
            self.reactor.stop()
        self.audit.stop()
        if self.webhook is not None:
            self.webhook.stop()
        self.batcher.stop()
        self.plane.mgr.stop()


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(prog="gatekeeper-tpu-manager")
    p.add_argument("--audit-interval", type=int,
                   default=DEFAULT_AUDIT_INTERVAL,
                   help="interval to run audit in seconds (manager.go:34)")
    p.add_argument("--constraint-violations-limit", type=int,
                   default=DEFAULT_VIOLATIONS_LIMIT,
                   help="violations reported per constraint (manager.go:35)")
    p.add_argument("--port", type=int, default=DEFAULT_PORT,
                   help="webhook port; -1 disables (policy.go:48)")
    p.add_argument("--batch-window-ms", type=float, default=2.0,
                   help="admission micro-batch window")
    p.add_argument("--max-batch", type=int, default=64,
                   help="admission micro-batch size cap")
    p.add_argument("--engine-worker-url", default=None,
                   help="run evaluation in a separate engine worker "
                        "process at this URL (see cmd/worker)")
    p.add_argument("--watch-poll-interval", type=float, default=5.0,
                   help="watch roster poll period in seconds "
                        "(watch/manager.go:172)")
    p.add_argument("--kubeconfig", default=None,
                   help="kubeconfig path for a real apiserver; absent -> "
                        "in-memory cluster (tests/demo) unless running "
                        "in-cluster")
    p.add_argument("--cert-dir", default="/certs",
                   help="TLS cert dir for the webhook server "
                        "(tls.crt/tls.key, policy.go:76-79)")
    p.add_argument("--enable-manual-deploy", action="store_true",
                   help="skip self-registering the "
                        "ValidatingWebhookConfiguration (policy.go:81-100)")
    p.add_argument("--demo", action="store_true",
                   help="seed demo/basic (1k namespaces + required-labels) "
                        "and run one audit sweep")
    return p.parse_args(argv)


def run_demo(mgr: Manager, n_namespaces: int = 1000) -> dict:
    """The demo/basic flow (reference demo/basic/demo.sh): sync config →
    template → constraint → resources → one audit sweep → statuses."""
    cluster = mgr.cluster
    cfg = empty_config_object()
    cfg["spec"] = {"sync": {"syncOnly": [
        {"group": "", "version": "v1", "kind": "Namespace"}]}}
    cluster.create(cfg)
    for i in range(n_namespaces):
        obj = {"apiVersion": "v1", "kind": "Namespace",
               "metadata": {"name": f"ns-{i:04d}"}}
        if i % 2:
            obj["metadata"]["labels"] = {"gatekeeper": "true"}
        cluster.create(obj)
    cluster.create({
        "apiVersion": "templates.gatekeeper.sh/v1alpha1",
        "kind": "ConstraintTemplate",
        "metadata": {"name": "k8srequiredlabels"},
        "spec": {
            "crd": {"spec": {"names": {"kind": "K8sRequiredLabels"},
                             "validation": {"openAPIV3Schema": {"properties": {
                                 "labels": {"type": "array",
                                            "items": {"type": "string"}}}}}}},
            "targets": [{
                "target": "admission.k8s.gatekeeper.sh",
                "rego": 'package k8srequiredlabels\n'
                        'violation[{"msg": msg, "details": '
                        '{"missing_labels": missing}}] {\n'
                        '  provided := {label | '
                        'input.review.object.metadata.labels[label]}\n'
                        '  required := {label | label := '
                        'input.constraint.spec.parameters.labels[_]}\n'
                        '  missing := required - provided\n'
                        '  count(missing) > 0\n'
                        '  msg := sprintf("you must provide labels: %v", '
                        '[missing])\n}\n'}]},
    })
    mgr.plane.run_until_idle(settle=2.0 if mgr.async_cluster else 0.0)
    cluster.create({
        "apiVersion": "constraints.gatekeeper.sh/v1alpha1",
        "kind": "K8sRequiredLabels",
        "metadata": {"name": "ns-must-have-gk"},
        "spec": {"match": {"kinds": [{"apiGroups": [""],
                                      "kinds": ["Namespace"]}]},
                 "parameters": {"labels": ["gatekeeper"]}},
    })
    mgr.plane.run_until_idle(settle=2.0 if mgr.async_cluster else 0.0)
    report = mgr.audit.audit_once()
    con = cluster.get(GVK("constraints.gatekeeper.sh", "v1alpha1",
                          "K8sRequiredLabels"), "ns-must-have-gk")
    return {"sweep": report,
            "status_violations": len((con.get("status") or {})
                                     .get("violations") or []),
            "audit_timestamp": (con.get("status") or {}).get("auditTimestamp")}


def main(argv=None) -> int:
    args = parse_args(argv)
    # warm-restart persistence defaults ON for the managed entry point:
    # snapshots (lowered IR / dedup plan / store) live next to the XLA
    # executable cache.  GATEKEEPER_SNAPSHOT_DIR="" disables; tests
    # constructing Manager directly stay hermetic (no default there).
    import os as _os
    if "GATEKEEPER_SNAPSHOT_DIR" not in _os.environ:
        from gatekeeper_tpu.utils.compile_cache import cache_root
        _os.environ["GATEKEEPER_SNAPSHOT_DIR"] = \
            _os.path.join(cache_root(), "snapshots")
    mgr = Manager(args)
    if args.demo:
        out = run_demo(mgr)
        print(json.dumps(out, indent=2, default=str))
        return 0
    mgr.start()
    _log.info(f"gatekeeper-tpu manager up "
          f"(webhook :{mgr.webhook.port if mgr.webhook else 'off'}, "
          f"audit every {args.audit_interval}s)", file=sys.stderr)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    mgr.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
