"""Engine worker process: a JaxDriver (owning the accelerator) served
over the Driver seam (reference drivers/remote analogue, remote.go:49).

Run ``python -m gatekeeper_tpu.cmd.worker --port 8686`` next to a
manager started with ``--engine-worker-url http://127.0.0.1:8686`` —
the control plane stays responsive while evaluation (and XLA
compilation) happens out of process.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from gatekeeper_tpu.client.remote_driver import EngineWorker
from gatekeeper_tpu.engine.jax_driver import JaxDriver


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="gatekeeper-tpu-worker")
    p.add_argument("--port", type=int, default=8686)
    p.add_argument("--host", default="127.0.0.1")
    args = p.parse_args(argv)
    worker = EngineWorker(JaxDriver, host=args.host, port=args.port)
    worker.start()
    print(f"engine worker up at {worker.url}", file=sys.stderr)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    worker.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
