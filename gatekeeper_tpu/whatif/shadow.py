"""Shadow policy-set installs: candidate and live in ONE device sweep.

A candidate set's template/constraint docs are staged into the live
client under version-tagged kinds (analysis/policyset.shadow_kind) —
the constraint kind is only the registry key, never a match criterion,
so the shadow constraints select exactly the resources their live
twins do.  One full audit then covers live ∪ shadow kinds: the jax
driver's per-sweep dedup plan is built over the union, and because
canonical conjunct digests hash program structure + folded params (not
kind names), every conjunct the candidate shares with the live version
is evaluated once and fanned out to both — cross-version sharing is
the cross-template mechanism verbatim, which is what keeps the
combined sweep under 1.5x a single-set sweep instead of 2x.

The report carries the would-be-denied diff (``added`` violations the
candidate would newly reject, ``cleared`` ones it would stop
rejecting) and a parity digest over the candidate's normalized
verdicts, bit-identical to installing the candidate standalone
(`standalone_candidate_verdicts` is that oracle).
"""

from __future__ import annotations

import copy
import dataclasses

from gatekeeper_tpu.analysis.policyset import (cross_version_groups,
                                               is_shadow_kind, shadow_kind,
                                               split_shadow_kind)


def shadow_template_doc(doc: dict, tag: str) -> dict:
    """Deep-copied template doc re-keyed under the shadow version tag
    (crd names.kind + metadata.name; the rego body is untouched, so
    its lowering — and its conjunct digests — match the live twin)."""
    d = copy.deepcopy(doc)
    names = d["spec"]["crd"]["spec"]["names"]
    sk = shadow_kind(names["kind"], tag)
    names["kind"] = sk
    d.setdefault("metadata", {})["name"] = sk.lower()
    return d


def shadow_constraint_doc(doc: dict, tag: str) -> dict:
    """Deep-copied constraint doc re-pointed at the shadow template
    kind.  metadata.name is unchanged — constraint names are already
    namespaced per kind, and keeping them stable is what makes the
    live-vs-shadow diff line up per constraint."""
    d = copy.deepcopy(doc)
    d["kind"] = shadow_kind(d["kind"], tag)
    return d


@dataclasses.dataclass
class ShadowReport:
    tag: str
    live: list[tuple]            # normalized verdicts, live set
    shadow: list[tuple]          # normalized verdicts, candidate set
    added: list[tuple]           # would-be-denied: candidate only
    cleared: list[tuple]         # would-be-cleared: live only
    live_digest: str
    shadow_digest: str
    dedup: dict                  # cross-version sharing accounting
    by_constraint: dict          # cname -> {"added": n, "cleared": n}


def _diff_key(v: tuple) -> tuple:
    # drop the msg (v[-1]): a param tweak that only rewords the message
    # is not a verdict change
    return v[:-1]


class ShadowSession:
    """Stage -> sweep -> diff -> unstage, usable as a context manager
    (the candidate set never outlives the session unless promoted)."""

    def __init__(self, client, tag: str = "candidate"):
        if not tag:
            raise ValueError("shadow tag must be non-empty")
        self.client = client
        self.tag = tag
        self._templates: list[dict] = []
        self._constraints: list[dict] = []

    # -- staging --------------------------------------------------------

    def stage(self, templates: list[dict], constraints: list[dict]) -> None:
        """Install the candidate docs under the version tag.  Any
        install failure unwinds the partial stage before re-raising —
        a half-staged candidate must never linger beside the live set."""
        try:
            for doc in templates:
                sd = shadow_template_doc(doc, self.tag)
                self.client.add_template(sd)
                self._templates.append(sd)
            for doc in constraints:
                sd = shadow_constraint_doc(doc, self.tag)
                self.client.add_constraint(sd)
                self._constraints.append(sd)
        except Exception:
            self.unstage()
            raise

    def unstage(self) -> None:
        for doc in self._constraints:
            try:
                self.client.remove_constraint(doc)
            except Exception:
                pass
        for doc in self._templates:
            try:
                self.client.remove_template(doc)
            except Exception:
                pass
        self._templates = []
        self._constraints = []

    def __enter__(self) -> "ShadowSession":
        return self

    def __exit__(self, *exc) -> None:
        self.unstage()

    # -- the combined sweep --------------------------------------------

    def sweep(self, limit_per_constraint: int = 20,
              full: bool = True) -> ShadowReport:
        """One audit over live ∪ shadow kinds, partitioned back into
        the two policy-set versions.  With a per-constraint cap the
        diff is over the capped verdict sets (same cap both sides)."""
        from gatekeeper_tpu.whatif import normalize_result, verdict_digest
        resp = self.client.audit(limit_per_constraint=limit_per_constraint,
                                 full=full)
        live: list[tuple] = []
        shadow: list[tuple] = []
        for r in resp.results():
            con_kind = (r.constraint or {}).get("kind", "")
            _base, tag = split_shadow_kind(con_kind)
            v = normalize_result(r)
            if tag == self.tag:
                shadow.append(v)
            elif tag is None:
                live.append(v)
        live.sort()
        shadow.sort()
        live_keys = {_diff_key(v) for v in live}
        shadow_keys = {_diff_key(v) for v in shadow}
        added = sorted(v for v in shadow if _diff_key(v) not in live_keys)
        cleared = sorted(v for v in live if _diff_key(v) not in shadow_keys)
        by_con: dict = {}
        for v in added:
            by_con.setdefault(v[1], {"added": 0, "cleared": 0})["added"] += 1
        for v in cleared:
            by_con.setdefault(v[1], {"added": 0, "cleared": 0})["cleared"] += 1
        return ShadowReport(
            tag=self.tag, live=live, shadow=shadow,
            added=added, cleared=cleared,
            live_digest=verdict_digest(live),
            shadow_digest=verdict_digest(shadow),
            dedup=self._dedup_stats(),
            by_constraint=by_con)

    def _dedup_stats(self) -> dict:
        """Cross-version sharing accounting from the sweep's dedup plan
        (memoized per policy-set digest on the driver).  Best-effort:
        scalar drivers and GATEKEEPER_DEDUP=off report zeros."""
        try:
            memo = getattr(self.client.driver, "_dedup_plan_memo", None)
            if memo:
                for _target, (_digest, plan) in memo.items():
                    if plan is not None and any(
                            is_shadow_kind(k) for k in plan.kind_digests):
                        return cross_version_groups(plan)
        except Exception:
            pass
        return {"groups_cross_version": 0, "groups_within_version": 0,
                "sites_cross_version": 0}


def standalone_candidate_verdicts(templates: list[dict],
                                  constraints: list[dict],
                                  store_state: dict,
                                  limit_per_constraint: int = 20,
                                  ) -> list[tuple]:
    """The shadow parity oracle: a fresh driver + client with ONLY the
    candidate set (unmangled kinds) over the same store contents; the
    normalized verdicts must be bit-identical to a ShadowSession
    sweep's candidate half."""
    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    from gatekeeper_tpu.whatif import normalize_results
    driver = JaxDriver()
    handler = K8sValidationTarget()
    client = Backend(driver).new_client([handler])
    for doc in templates:
        client.add_template(doc)
    for doc in constraints:
        client.add_constraint(doc)
    driver.adopt_store(handler.name, store_state)
    resp = client.audit(limit_per_constraint=limit_per_constraint, full=True)
    return normalize_results(resp.results())
