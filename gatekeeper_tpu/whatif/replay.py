"""Historical replay: audit a past store, re-review a recorded stream.

Two time machines over the same engine path:

- `replay_snapshot` loads a versioned columnar-store snapshot
  (resilience/snapshot store tier — optionally from an explicit
  historical snapshot root, independent of the live
  GATEKEEPER_SNAPSHOT_DIR) as a *secondary* store under a fresh driver
  and audits it with whatever policy set you hand it: the live set for
  "what was violating last week", a candidate set for "what would this
  change have rejected last week".
- `replay_admissions` feeds a recorded AdmissionReview corpus
  (obs/flightrecorder, GATEKEEPER_FLIGHT_ADMISSION=1) back through a
  client's review path and compares verdicts against what was
  recorded.  Under the same policy set the reproduction must be exact;
  under a candidate set the mismatch list IS the what-if answer.
"""

from __future__ import annotations

import dataclasses
import time


def load_historical_store(target: str, root: str | None = None) -> dict | None:
    """The store-tier snapshot payload for ``target``, from the live
    snapshot dir or an explicit historical ``root``; None on miss."""
    from gatekeeper_tpu.resilience import snapshot as _snap
    hit = _snap.load_store(target, root=root)
    return hit[0] if hit is not None else None


@dataclasses.dataclass
class ReplayReport:
    verdicts: list[tuple]        # normalized (whatif.normalize_results)
    digest: str
    n_resources: int
    wall_s: float


def replay_snapshot(templates: list[dict], constraints: list[dict],
                    store_state: dict,
                    limit_per_constraint: int = 20) -> ReplayReport:
    """Audit a historical store state under the given policy docs, in
    a fresh driver (the live client and its caches are untouched)."""
    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    from gatekeeper_tpu.whatif import normalize_results, verdict_digest
    t0 = time.perf_counter()
    driver = JaxDriver()
    handler = K8sValidationTarget()
    client = Backend(driver).new_client([handler])
    for doc in templates:
        client.add_template(doc)
    for doc in constraints:
        client.add_constraint(doc)
    driver.adopt_store(handler.name, store_state)
    resp = client.audit(limit_per_constraint=limit_per_constraint, full=True)
    verdicts = normalize_results(resp.results())
    return ReplayReport(
        verdicts=verdicts, digest=verdict_digest(verdicts),
        n_resources=len(store_state.get("entries", ())),
        wall_s=time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# recorded admission streams


@dataclasses.dataclass
class StreamReplayReport:
    replayed: int
    skipped: int                 # truncated/unreplayable corpus events
    matched: int
    mismatches: list[dict]       # per-event recorded-vs-replayed delta
    wall_s: float

    @property
    def exact(self) -> bool:
        return self.replayed > 0 and not self.mismatches


def _verdict_rows(results) -> list[tuple]:
    from gatekeeper_tpu.analysis.policyset import split_shadow_kind
    rows = []
    for r in results:
        con = r.constraint or {}
        rows.append((split_shadow_kind(con.get("kind", ""))[0],
                     (con.get("metadata") or {}).get("name", ""),
                     r.enforcement_action, r.msg))
    return sorted(rows)


def _recorded_rows(event: dict) -> list[tuple]:
    from gatekeeper_tpu.analysis.policyset import split_shadow_kind
    rows = []
    for v in event.get("verdicts", ()):
        rows.append((split_shadow_kind(v.get("kind") or "")[0],
                     v.get("name") or "", v.get("action") or "deny",
                     v.get("msg") or ""))
    return sorted(rows)


def _truncated(request: dict) -> bool:
    for f in ("object", "oldObject"):
        o = request.get(f)
        if isinstance(o, dict) and o.get("__truncated__"):
            return True
    return False


def replay_admissions(events: list[dict], client,
                      compare: bool = True) -> StreamReplayReport:
    """Re-review each corpus event through ``client`` and (optionally)
    compare against the recorded outcome.  Allowed/denied is recomputed
    with the webhook's enforcementAction partition (deny blocks, warn/
    dryrun admit), so a corpus recorded by the webhook reproduces
    exactly under the same policy set.  Events whose payload was
    byte-capped at record time are skipped, not guessed at."""
    t0 = time.perf_counter()
    replayed = skipped = matched = 0
    mismatches: list[dict] = []
    for event in events:
        request = event.get("request") or {}
        if _truncated(request):
            skipped += 1
            continue
        try:
            resp = client.review(request)
        except Exception as e:  # noqa: BLE001 — count, keep replaying
            skipped += 1
            mismatches.append({"request": request.get("name"),
                               "error": str(e)})
            continue
        results = resp.results()
        allowed = not any(r.enforcement_action not in ("warn", "dryrun")
                          for r in results)
        replayed += 1
        if not compare:
            continue
        got = _verdict_rows(results)
        want = _recorded_rows(event)
        if allowed == bool(event.get("allowed")) and got == want:
            matched += 1
        else:
            obj = (request.get("object") or {})
            mismatches.append({
                "name": (obj.get("metadata") or {}).get("name"),
                "recorded_allowed": bool(event.get("allowed")),
                "replayed_allowed": allowed,
                "recorded": want, "replayed": got})
    return StreamReplayReport(
        replayed=replayed, skipped=skipped, matched=matched,
        mismatches=mismatches, wall_s=time.perf_counter() - t0)
