"""Historical replay: audit a past store, re-review a recorded stream.

Two time machines over the same engine path:

- `replay_snapshot` loads a versioned columnar-store snapshot
  (resilience/snapshot store tier — optionally from an explicit
  historical snapshot root, independent of the live
  GATEKEEPER_SNAPSHOT_DIR) as a *secondary* store under a fresh driver
  and audits it with whatever policy set you hand it: the live set for
  "what was violating last week", a candidate set for "what would this
  change have rejected last week".
- `replay_admissions` feeds a recorded AdmissionReview corpus
  (obs/flightrecorder, GATEKEEPER_FLIGHT_ADMISSION=1) back through a
  client's review path and compares verdicts against what was
  recorded.  Under the same policy set the reproduction must be exact;
  under a candidate set the mismatch list IS the what-if answer.
"""

from __future__ import annotations

import dataclasses
import time


def load_historical_store(target: str, root: str | None = None) -> dict | None:
    """The store-tier snapshot payload for ``target``, from the live
    snapshot dir or an explicit historical ``root``; None on miss."""
    from gatekeeper_tpu.resilience import snapshot as _snap
    hit = _snap.load_store(target, root=root)
    return hit[0] if hit is not None else None


@dataclasses.dataclass
class ReplayReport:
    verdicts: list[tuple]        # normalized (whatif.normalize_results)
    digest: str
    n_resources: int
    wall_s: float


def replay_snapshot(templates: list[dict], constraints: list[dict],
                    store_state: dict,
                    limit_per_constraint: int = 20) -> ReplayReport:
    """Audit a historical store state under the given policy docs, in
    a fresh driver (the live client and its caches are untouched)."""
    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    from gatekeeper_tpu.whatif import normalize_results, verdict_digest
    t0 = time.perf_counter()
    driver = JaxDriver()
    handler = K8sValidationTarget()
    client = Backend(driver).new_client([handler])
    for doc in templates:
        client.add_template(doc)
    for doc in constraints:
        client.add_constraint(doc)
    driver.adopt_store(handler.name, store_state)
    resp = client.audit(limit_per_constraint=limit_per_constraint, full=True)
    verdicts = normalize_results(resp.results())
    return ReplayReport(
        verdicts=verdicts, digest=verdict_digest(verdicts),
        n_resources=len(store_state.get("entries", ())),
        wall_s=time.perf_counter() - t0)


# ---------------------------------------------------------------------------
# recorded admission streams


@dataclasses.dataclass
class StreamReplayReport:
    replayed: int
    skipped: int                 # unreplayable corpus events (errors)
    matched: int
    mismatches: list[dict]       # per-event recorded-vs-replayed delta
    wall_s: float
    skipped_oversize: int = 0    # byte-capped to the identifying envelope
    digest: str = ""             # sha256[:16] over per-event verdict rows
    batched: bool = False        # went through the device micro-batcher

    @property
    def exact(self) -> bool:
        return self.replayed > 0 and not self.mismatches


def _verdict_rows(results) -> list[tuple]:
    from gatekeeper_tpu.analysis.policyset import split_shadow_kind
    rows = []
    for r in results:
        con = r.constraint or {}
        rows.append((split_shadow_kind(con.get("kind", ""))[0],
                     (con.get("metadata") or {}).get("name", ""),
                     r.enforcement_action, r.msg))
    return sorted(rows)


def _recorded_rows(event: dict) -> list[tuple]:
    from gatekeeper_tpu.analysis.policyset import split_shadow_kind
    rows = []
    for v in event.get("verdicts", ()):
        rows.append((split_shadow_kind(v.get("kind") or "")[0],
                     v.get("name") or "", v.get("action") or "deny",
                     v.get("msg") or ""))
    return sorted(rows)


def _truncated(request: dict) -> bool:
    for f in ("object", "oldObject"):
        o = request.get(f)
        if isinstance(o, dict) and o.get("__truncated__"):
            return True
    return False


def _stream_digest(rows_per_event: list[list[tuple]]) -> str:
    """The replay parity currency: one digest over the ordered
    per-event verdict rows.  The scalar and batched paths must agree
    bit-for-bit, so this is computed from the same normalized rows on
    both."""
    import hashlib
    import json
    blob = json.dumps(rows_per_event, sort_keys=True,
                      separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def _compare_event(event: dict, request: dict, results, allowed: bool,
                   mismatches: list[dict]) -> bool:
    got = _verdict_rows(results)
    want = _recorded_rows(event)
    if allowed == bool(event.get("allowed")) and got == want:
        return True
    obj = (request.get("object") or {})
    mismatches.append({
        "name": (obj.get("metadata") or {}).get("name"),
        "recorded_allowed": bool(event.get("allowed")),
        "replayed_allowed": allowed,
        "recorded": want, "replayed": got})
    return False


def replay_admissions(events: list[dict], client,
                      compare: bool = True) -> StreamReplayReport:
    """Re-review each corpus event through ``client`` and (optionally)
    compare against the recorded outcome.  Allowed/denied is recomputed
    with the webhook's enforcementAction partition (deny blocks, warn/
    dryrun admit), so a corpus recorded by the webhook reproduces
    exactly under the same policy set.  Events whose payload was
    byte-capped to the identifying envelope at record time are counted
    in ``skipped_oversize``, not guessed at."""
    t0 = time.perf_counter()
    replayed = skipped = oversize = matched = 0
    mismatches: list[dict] = []
    rows_per_event: list[list[tuple]] = []
    for event in events:
        request = event.get("request") or {}
        if _truncated(request):
            oversize += 1
            continue
        try:
            resp = client.review(request)
        except Exception as e:  # noqa: BLE001 — count, keep replaying
            skipped += 1
            mismatches.append({"request": request.get("name"),
                               "error": str(e)})
            continue
        results = resp.results()
        allowed = not any(r.enforcement_action not in ("warn", "dryrun")
                          for r in results)
        replayed += 1
        rows_per_event.append(_verdict_rows(results))
        if compare and _compare_event(event, request, results, allowed,
                                      mismatches):
            matched += 1
    return StreamReplayReport(
        replayed=replayed, skipped=skipped, matched=matched,
        mismatches=mismatches, wall_s=time.perf_counter() - t0,
        skipped_oversize=oversize,
        digest=_stream_digest(rows_per_event))


def replay_admissions_batched(events: list[dict], client,
                              compare: bool = True,
                              batch_size: int = 256
                              ) -> StreamReplayReport:
    """Batched twin of :func:`replay_admissions`: replayable events go
    through ``client.review_batch`` — the webhook's device micro-batch
    seam, one [B, C] matrix pass per chunk when the driver is eligible
    (see jax_driver REVIEW_BATCH_MIN_EVALS) — instead of one scalar
    ``review`` per event.  Verdict comparison, accounting, and the
    stream ``digest`` are computed from the same normalized rows, so
    the report must be bit-identical to the scalar oracle's; a chunk
    that fails wholesale falls back to per-event scalar replay so one
    poisoned request cannot sink its neighbours' accounting."""
    t0 = time.perf_counter()
    replayed = skipped = oversize = matched = 0
    mismatches: list[dict] = []
    rows_per_event: list[list[tuple]] = []
    pending: list[dict] = []                 # events with replayable payloads
    for event in events:
        request = event.get("request") or {}
        if _truncated(request):
            oversize += 1
            continue
        pending.append(event)
    for lo in range(0, len(pending), max(1, batch_size)):
        chunk = pending[lo:lo + max(1, batch_size)]
        requests = [ev.get("request") or {} for ev in chunk]
        try:
            resps = client.review_batch(requests)
        except Exception:  # noqa: BLE001 — fall back to scalar replay
            resps = None
        for i, event in enumerate(chunk):
            request = requests[i]
            try:
                resp = (resps[i] if resps is not None
                        else client.review(request))
            except Exception as e:  # noqa: BLE001
                skipped += 1
                mismatches.append({"request": request.get("name"),
                                   "error": str(e)})
                continue
            results = resp.results()
            allowed = not any(
                r.enforcement_action not in ("warn", "dryrun")
                for r in results)
            replayed += 1
            rows_per_event.append(_verdict_rows(results))
            if compare and _compare_event(event, request, results,
                                          allowed, mismatches):
                matched += 1
    return StreamReplayReport(
        replayed=replayed, skipped=skipped, matched=matched,
        mismatches=mismatches, wall_s=time.perf_counter() - t0,
        skipped_oversize=oversize,
        digest=_stream_digest(rows_per_event), batched=True)
