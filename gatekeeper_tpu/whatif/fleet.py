"""Multi-cluster batched audit: N stores, one vmapped mega-sweep.

DrJAX-style broadcast/map-reduce (PAPERS.md): every cluster runs the
SAME compiled policy programs, so the fleet sweep pads each cluster's
bound arrays to a common shape, stacks them along a leading cluster
axis, and evaluates one ``jax.vmap`` of the existing chunked top-k
kernel (engine/veval._eval_topk) per kind — one device dispatch for
the whole fleet, with the per-cluster capped top-k falling out of the
vmap.  Host formatting then runs per cluster through the same scalar
oracle the single-cluster sweep uses, so `fleet_loop_oracle` (a plain
per-cluster audit loop) is bit-identical by construction.

Eligibility reuses the install-time certification ladder: a kind is
stacked only when its Stage-5 footprint certifies row-locality with no
external providers AND its Stage-6 partition plan (when present) is
shard-eligible — the same gates the sharded sweep trusts.  Everything
else (scalar templates, cross-row inventory joins) runs the per-cluster
replicated path inside the same call.

Padding safety mirrors the sharded path's argument: padded rows are
dead (``__alive__`` False) and every gather in the evaluator is
clipped/sentinel-guarded, so zero-fill is sound — EXCEPT the
per-constraint ``.any``/``.all``/``.bitmap`` tables, whose u-axis pad
must replicate the sentinel column (an ``.all`` row for an
empty-param constraint is vacuously True everywhere, and out-of-range
value ids land on the LAST column after stacking).
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

_EDGE_PAD_SUFFIXES = (".any", ".all", ".bitmap")

# jitted vmapped evaluators, keyed by (program cache key, limit).  A
# fresh jax.jit wrapper would re-trace on every fleet_audit call; the
# memo makes repeat sweeps hit XLA's executable cache exactly like the
# single-cluster path does (shape changes still re-specialize inside
# the cached wrapper).
_TOPK_JIT: dict = {}
_MASK_JIT: dict = {}


def _topk_fn(program, limit: int):
    import jax

    from gatekeeper_tpu.engine.veval import _eval_topk
    key = (program.cache_key(), limit)
    fn = _TOPK_JIT.get(key)
    if fn is None:
        fn = jax.jit(lambda d, p=program, k=limit: jax.vmap(
            lambda a: _eval_topk(p, a, k))(d))
        _TOPK_JIT[key] = fn
    return fn


def _mask_fn(program):
    import jax

    from gatekeeper_tpu.engine.veval import _eval_mask
    key = program.cache_key()
    fn = _MASK_JIT.get(key)
    if fn is None:
        fn = jax.jit(lambda d, p=program: jax.vmap(
            lambda a: _eval_mask(p, a))(d))
        _MASK_JIT[key] = fn
    return fn


@dataclasses.dataclass
class FleetCluster:
    name: str
    client: object
    driver: object
    handler: object


def make_cluster(name: str, templates: list[dict], constraints: list[dict],
                 objs: list | None = None,
                 store_state: dict | None = None) -> FleetCluster:
    """One simulated cluster: fresh driver + client with the shared
    policy set and either an object batch or a store snapshot."""
    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    driver = JaxDriver()
    handler = K8sValidationTarget()
    client = Backend(driver).new_client([handler])
    for doc in templates:
        client.add_template(doc)
    for doc in constraints:
        client.add_constraint(doc)
    if store_state is not None:
        driver.adopt_store(handler.name, store_state)
    if objs:
        client.add_data_batch(objs)
    return FleetCluster(name=name, client=client, driver=driver,
                        handler=handler)


@dataclasses.dataclass
class FleetReport:
    n_clusters: int
    verdicts: list[list[tuple]]      # per cluster, normalized
    digests: list[str]               # per cluster
    kinds_stacked: list[str]
    kinds_replicated: dict           # kind -> reason
    device_dispatches: int           # stacked dispatches (1 per kind)
    wall_s: float


def _pad_to(arr: np.ndarray, shape: tuple, edge: bool) -> np.ndarray:
    if arr.shape == shape:
        return arr
    widths = [(0, t - s) for s, t in zip(arr.shape, shape)]
    return np.pad(arr, widths, mode="edge" if edge else "constant")


def _stack_reason(driver, st, kind, compiled) -> str | None:
    """Why a kind can NOT ride the stacked path (None: eligible)."""
    if compiled.vectorized is None:
        return "scalar_template"
    if driver.scalar_only:
        return "backend_degraded"
    fp = st.footprints.get(kind)
    if fp is None:
        return "no_footprint"
    if not fp.row_local:
        return "not_row_local"
    if fp.providers:
        return "external_providers"
    sp = st.shardplans.get(kind)
    if sp is not None and not getattr(sp, "eligible", False):
        return "partition_plan_ineligible"
    return None


def fleet_audit(clusters: list[FleetCluster],
                limit_per_constraint: int = 20) -> FleetReport:
    """The stacked mega-sweep.  Single-threaded entry point (bench,
    probe, centralized fleet audit) — per-cluster driver internals are
    driven directly under each driver's prep lock."""
    from gatekeeper_tpu.engine.jax_driver import TRIVIAL_MATCH
    from gatekeeper_tpu.engine.veval import pad_rank
    from gatekeeper_tpu.whatif import normalize_results, verdict_digest

    if not clusters:
        raise ValueError("fleet_audit needs at least one cluster")
    t0 = time.perf_counter()
    limit = limit_per_constraint
    target = clusters[0].handler.name
    drivers = [c.driver for c in clusters]
    sts = [d._state(target) for d in drivers]
    orders = [d._ensure_order(st) for d, st in zip(drivers, sts)]
    ranks = [d._row_rank(st, ro) for d, st, (_o, ro)
             in zip(drivers, sts, orders)]

    kinds = sorted(sts[0].templates)
    for st in sts[1:]:
        if sorted(st.templates) != kinds:
            raise ValueError("fleet clusters must share one policy set")

    tagged = [[] for _ in clusters]
    rcaches: list[dict] = [{} for _ in clusters]
    kinds_stacked: list[str] = []
    kinds_replicated: dict = {}
    dispatches = 0

    def _replicated(kind, reason, cons_by_cluster, masks):
        kinds_replicated[kind] = reason
        for i, (d, st) in enumerate(zip(drivers, sts)):
            cons = cons_by_cluster[i]
            if not cons:
                continue
            mask = masks[i] if masks is not None else None
            if mask is None or mask is TRIVIAL_MATCH:
                mask = None
            ordered_rows, row_order = orders[i]
            d._scalar_kind(st, target, clusters[i].handler,
                           st.templates[kind], cons, mask, ordered_rows,
                           row_order, kind, limit, None, tagged[i],
                           rcaches[i])

    for kind in kinds:
        cons_by_cluster = [d._kind_constraints(st, kind)
                           for d, st in zip(drivers, sts)]
        if not any(cons_by_cluster):
            continue
        if any(c != cons_by_cluster[0] for c in cons_by_cluster[1:]):
            raise ValueError(
                f"fleet clusters disagree on constraints for {kind}")
        compiled = sts[0].templates[kind]
        reason = None
        for d, st in zip(drivers, sts):
            reason = _stack_reason(d, st, kind, st.templates[kind])
            if reason is not None:
                break
        if reason is not None:
            _replicated(kind, reason, cons_by_cluster, None)
            continue

        # per-cluster host prep through the same seams the single
        # cluster sweep uses: exact match mask, bindings, rank gate
        per_arrays: list[dict] = []
        masks = []
        try:
            for i, (d, st) in enumerate(zip(drivers, sts)):
                with d._prep_lock:
                    cons = cons_by_cluster[i]
                    mask, _dirty, padded = d._kind_mask(st, target, kind,
                                                        cons)
                    masks.append(mask)
                    if mask is None:
                        raise LookupError("no vector matcher")
                    b = d._kind_bindings(st, kind, st.templates[kind], cons)
                    if b.f32_unsafe:
                        raise LookupError("f32_unsafe")
                    arrays = dict(b.arrays)
                    arrays.pop("__match__", None)
                    if mask is not TRIVIAL_MATCH:
                        pm = padded
                        if pm is None or pm.shape != (b.c_pad, b.r_pad):
                            pm = np.zeros((b.c_pad, b.r_pad), dtype=bool)
                            pm[:mask.shape[0], :mask.shape[1]] = mask
                        arrays["__match__"] = pm
                    arrays["__rank__"] = pad_rank(ranks[i], b.r_pad)
                    per_arrays.append(arrays)
        except LookupError as e:
            masks += [None] * (len(clusters) - len(masks))
            _replicated(kind, str(e), cons_by_cluster, masks)
            continue
        if any(m is TRIVIAL_MATCH for m in masks) and \
                any(m is not TRIVIAL_MATCH for m in masks):
            # mixed trivial/real masks would need per-instance input
            # sets; constraints are identical so this cannot happen,
            # but fail safe to the oracle path if it ever does
            _replicated(kind, "mixed_match_gates", cons_by_cluster, masks)
            continue

        names = sorted(per_arrays[0])
        if any(sorted(a) != names for a in per_arrays[1:]):
            _replicated(kind, "binding_name_mismatch", cons_by_cluster,
                        masks)
            continue
        ckey = compiled.vectorized.program.cache_key()
        if any(st.templates[kind].vectorized.program.cache_key() != ckey
               for st in sts[1:]):
            _replicated(kind, "program_mismatch", cons_by_cluster, masks)
            continue
        stacked = {}
        for nm in names:
            arrs = [a[nm] for a in per_arrays]
            shape = tuple(max(s) for s in zip(*[x.shape for x in arrs]))
            edge = nm.endswith(_EDGE_PAD_SUFFIXES)
            stacked[nm] = np.stack([_pad_to(x, shape, edge) for x in arrs])

        program = compiled.vectorized.program
        counts, rows, scores = _topk_fn(program, limit)(stacked)
        dispatches += 1
        counts = np.asarray(counts)
        rows = np.asarray(rows)
        scores = np.asarray(scores)
        kinds_stacked.append(kind)

        full_cand = None

        def _full_mask(i, stacked=stacked, program=program):
            nonlocal full_cand
            if full_cand is None:
                full_cand = np.asarray(_mask_fn(program)(stacked))
            return full_cand[i]

        for i, (d, st) in enumerate(zip(drivers, sts)):
            cons = cons_by_cluster[i]
            _ordered, row_order = orders[i]
            handler = clusters[i].handler
            cl_compiled = st.templates[kind]
            for ci, c in enumerate(cons):
                sel = [int(r) for r, s in zip(rows[i, ci], scores[i, ci])
                       if s > 0]
                sel = sorted((r for r in sel if r in row_order),
                             key=row_order.__getitem__)
                emitted = d._emit_rows(st, target, handler, cl_compiled, c,
                                       sel, row_order, kind, limit, None,
                                       tagged[i], rcaches[i])
                if emitted < limit and int(counts[i, ci]) > len(sel):
                    # over-approximated pairs left the cap under-filled:
                    # widen to this cluster's slice of the (lazily
                    # computed, still stacked) full mask
                    sel_set = set(sel)
                    rest = sorted(
                        (ri for ri in map(int,
                                          np.nonzero(_full_mask(i)[ci])[0])
                         if ri in row_order and ri not in sel_set),
                        key=row_order.__getitem__)
                    d._emit_rows(st, target, handler, cl_compiled, c, rest,
                                 row_order, kind, limit - emitted, None,
                                 tagged[i], rcaches[i])

    verdicts: list[list[tuple]] = []
    digests: list[str] = []
    for i, cl in enumerate(clusters):
        tagged[i].sort(key=lambda kv: kv[0])
        results = [r for _key, r in tagged[i]]
        for r in results:
            cl.handler.handle_violation(r)
        v = normalize_results(results)
        verdicts.append(v)
        digests.append(verdict_digest(v))
    return FleetReport(
        n_clusters=len(clusters), verdicts=verdicts, digests=digests,
        kinds_stacked=kinds_stacked, kinds_replicated=kinds_replicated,
        device_dispatches=dispatches, wall_s=time.perf_counter() - t0)


def fleet_loop_oracle(clusters: list[FleetCluster],
                      limit_per_constraint: int = 20):
    """The bit-identical baseline: one full single-cluster audit per
    cluster.  Returns (per-cluster normalized verdicts, digests,
    wall_s)."""
    from gatekeeper_tpu.whatif import normalize_results, verdict_digest
    t0 = time.perf_counter()
    verdicts = []
    for cl in clusters:
        resp = cl.client.audit(limit_per_constraint=limit_per_constraint,
                               full=True)
        verdicts.append(normalize_results(resp.results()))
    return (verdicts, [verdict_digest(v) for v in verdicts],
            time.perf_counter() - t0)
