"""What-if engine: policy simulation on the audit kernels.

Three entry points over one engine path (ROADMAP item 5):

- ``shadow`` — stage a candidate policy set *beside* the live one under
  a version tag and evaluate both in a single device sweep; the PR-5
  dedup plan shares canonical conjuncts across the versions, and the
  report is a would-be-denied diff (``added`` / ``cleared`` violations
  per constraint) plus a parity digest bit-identical to installing the
  candidate standalone.
- ``replay`` — re-audit a historical versioned store snapshot, or
  re-review a recorded admission-stream corpus (obs/flightrecorder),
  against either policy set: "what would this change have rejected
  last week?".
- ``fleet`` — stack N clusters' columnar stores along a leading
  cluster axis and evaluate the whole fleet as one vmapped mega-sweep
  with per-cluster capped top-k, reusing the Stage-6 partition-plan /
  footprint eligibility gates; a per-cluster loop is the bit-identical
  oracle.

All three report verdicts in one normalized form (`normalize_results`)
whose sha256 digest (`verdict_digest`) is the parity currency across
this package, the bench rows, and the tests.
"""

from __future__ import annotations

import hashlib

from gatekeeper_tpu.analysis.policyset import split_shadow_kind


def normalize_result(r) -> tuple:
    """One Result -> a driver-independent verdict tuple.  Shadow kinds
    collapse to their logical kind, so a shadow sweep's candidate
    verdicts compare bit-identically against a standalone install of
    the candidate set."""
    con = r.constraint or {}
    kind, _tag = split_shadow_kind(con.get("kind", ""))
    cname = (con.get("metadata") or {}).get("name", "")
    review = r.review if isinstance(r.review, dict) else {}
    rk = review.get("kind") or {}
    return (kind, cname,
            rk.get("group", ""), rk.get("version", ""), rk.get("kind", ""),
            review.get("namespace") or "", review.get("name", ""),
            r.msg)


def normalize_results(results) -> list[tuple]:
    return sorted(normalize_result(r) for r in results)


def verdict_digest(verdicts: list[tuple]) -> str:
    """Order-independent sha256 over normalized verdicts — 16 hex
    chars, same idiom as the bench parity digests."""
    return hashlib.sha256(
        repr(sorted(verdicts)).encode()).hexdigest()[:16]


from gatekeeper_tpu.whatif.shadow import (ShadowReport, ShadowSession,  # noqa: E402
                                          standalone_candidate_verdicts)
from gatekeeper_tpu.whatif.replay import (ReplayReport, StreamReplayReport,  # noqa: E402
                                          load_historical_store,
                                          replay_admissions,
                                          replay_admissions_batched,
                                          replay_snapshot)
from gatekeeper_tpu.whatif.fleet import (FleetReport, fleet_audit,  # noqa: E402
                                         fleet_loop_oracle, make_cluster)

__all__ = [
    "normalize_result", "normalize_results", "verdict_digest",
    "ShadowSession", "ShadowReport", "standalone_candidate_verdicts",
    "ReplayReport", "StreamReplayReport", "load_historical_store",
    "replay_snapshot", "replay_admissions", "replay_admissions_batched",
    "FleetReport", "fleet_audit", "fleet_loop_oracle", "make_cluster",
]
