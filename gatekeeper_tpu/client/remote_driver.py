"""The ``remote`` driver: out-of-process engine worker over HTTP.

The reference ships two drivers behind its seam: in-process OPA
(drivers/local) and an HTTP client speaking to a remote OPA
(vendor/.../drivers/remote/remote.go:49-100, one URL per API:
PutModule -> PUT /v1/policies/<name>, Query -> POST /v1/data/...).
This is that second kind for the TPU engine: the control plane
(controllers, webhook, audit manager) runs in one process while the
evaluation engine — typically a JaxDriver owning the TPU — runs in a
worker process.  The wire protocol is one POST per Driver-seam method
with JSON bodies; templates travel as Rego source and are re-compiled
worker-side (exactly how the reference's remote OPA receives modules).

``EngineWorker`` hosts any Driver implementation; ``RemoteDriver`` is
the client half, implementing the same seam so ``Backend``/``Client``
cannot tell the difference (the conformance suite runs against it).
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from gatekeeper_tpu.api.templates import CompiledTemplate, compile_target_rego
from gatekeeper_tpu.client.interface import Driver, QueryOpts
from gatekeeper_tpu.client.targets import TargetHandler
from gatekeeper_tpu.client.types import Result
from gatekeeper_tpu.errors import ClientError
from gatekeeper_tpu.store.table import ResourceMeta

# worker-side registry: target name -> handler factory (handlers are
# code, not data — the worker constructs its own, like a remote OPA
# owning its own regolib)
TARGET_REGISTRY: dict[str, Callable[[], TargetHandler]] = {}


def register_target(name: str, factory: Callable[[], TargetHandler]) -> None:
    TARGET_REGISTRY[name] = factory


def _default_registry() -> None:
    from gatekeeper_tpu.target.k8s import TARGET_NAME, K8sValidationTarget
    TARGET_REGISTRY.setdefault(TARGET_NAME, K8sValidationTarget)


def _result_to_wire(r: Result) -> dict:
    return {"msg": r.msg, "metadata": r.metadata, "constraint": r.constraint,
            "review": r.review, "resource": r.resource,
            "enforcement_action": r.enforcement_action}


def _result_from_wire(d: dict) -> Result:
    return Result(msg=d.get("msg", ""), metadata=d.get("metadata") or {},
                  constraint=d.get("constraint"), review=d.get("review"),
                  resource=d.get("resource"),
                  enforcement_action=d.get("enforcement_action", "deny"))


def _opts_to_wire(opts: QueryOpts | None) -> dict | None:
    if opts is None:
        return None
    return {"tracing": opts.tracing,
            "limit_per_constraint": opts.limit_per_constraint,
            "shed_actions": sorted(opts.shed_actions)
            if opts.shed_actions else None}


def _opts_from_wire(d: dict | None) -> QueryOpts | None:
    if d is None:
        return None
    shed = d.get("shed_actions")
    return QueryOpts(tracing=bool(d.get("tracing")),
                     limit_per_constraint=d.get("limit_per_constraint"),
                     shed_actions=frozenset(shed) if shed else None)


class WorkerUnreachableError(ClientError):
    """Transport-level failure talking to an engine worker (connect,
    timeout, torn connection) — retriable on another replica, unlike a
    semantic 4xx the worker actually answered with."""


class EngineWorker:
    """HTTP server hosting a Driver (usually a JaxDriver owning the
    accelerator).  One POST endpoint per seam method.  ``driver`` may be
    an instance or a zero-arg factory; with a factory, each ``init``
    from a (re)connecting control plane gets a FRESH driver — a
    restarted manager must not inherit templates/constraints/data a
    previous manager synced (they would never be garbage-collected)."""

    def __init__(self, driver: Driver | Callable[[], Driver],
                 host: str = "127.0.0.1", port: int = 0):
        _default_registry()
        if callable(driver) and not isinstance(driver, Driver):
            self._factory: Callable[[], Driver] | None = driver
            self.driver = driver()
        else:
            self._factory = None
            self.driver = driver
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            # keep-alive: the client reuses one connection per thread
            # instead of a TCP handshake per Driver call (admission is
            # call-per-review); Nagle off, or the header/body write
            # pair interacts with delayed ACK for ~40ms per call
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                    body = json.loads(self.rfile.read(length) or b"{}")
                    method = self.path.strip("/").split("/")[-1]
                    out = outer._dispatch(method, body)
                    payload = json.dumps(out).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except ClientError as e:
                    self.send_error(400, str(e))
                except Exception as e:  # worker must not die on a bad call
                    self.send_error(500, f"{type(e).__name__}: {e}")

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------

    def _dispatch(self, method: str, b: dict) -> Any:
        d = self.driver
        if method == "init":
            targets = {}
            for name in b["targets"]:
                factory = TARGET_REGISTRY.get(name)
                if factory is None:
                    raise ClientError(f"worker has no target {name!r}")
                targets[name] = factory()
            if self._factory is not None:
                self.driver = d = self._factory()   # fresh state per client
            d.init(targets)
            return {"ok": True}
        if method == "put_template":
            compiled = compile_target_rego(b["kind"], b["target"], b["source"])
            d.put_template(b["target"], b["kind"], compiled)
            return {"ok": True}
        if method == "delete_template":
            d.delete_template(b["target"], b["kind"])
            return {"ok": True}
        if method == "put_constraint":
            d.put_constraint(b["target"], b["kind"], b["name"], b["constraint"])
            return {"ok": True}
        if method == "delete_constraint":
            d.delete_constraint(b["target"], b["kind"], b["name"])
            return {"ok": True}
        if method == "put_data":
            m = b["meta"]
            meta = ResourceMeta(m["api_version"], m["kind"], m["name"],
                                m.get("namespace"))
            d.put_data(b["target"], b["key"], meta, b["obj"])
            return {"ok": True}
        if method == "put_data_batch":
            entries = []
            for e in b["entries"]:
                m = e["meta"]
                entries.append((e["key"],
                                ResourceMeta(m["api_version"], m["kind"],
                                             m["name"], m.get("namespace")),
                                e["obj"]))
            d.put_data_batch(b["target"], entries)
            return {"ok": True}
        if method == "delete_data":
            return {"removed": d.delete_data(b["target"], b["key"])}
        if method == "wipe_data":
            d.wipe_data(b["target"])
            return {"ok": True}
        if method == "query_review":
            results, trace = d.query_review(b["target"], b["review"],
                                            _opts_from_wire(b.get("opts")))
            return {"results": [_result_to_wire(r) for r in results],
                    "trace": trace}
        if method == "query_review_batch":
            opts = _opts_from_wire(b.get("opts"))
            pairs = d.query_review_batch(b["target"], b["reviews"], opts)
            return {"batch": [{"results": [_result_to_wire(r) for r in rs],
                               "trace": tr} for rs, tr in pairs]}
        if method == "query_audit":
            results, trace = d.query_audit(b["target"],
                                           _opts_from_wire(b.get("opts")))
            return {"results": [_result_to_wire(r) for r in results],
                    "trace": trace}
        if method == "dump":
            return {"dump": d.dump()}
        raise ClientError(f"unknown method {method!r}")

    # ------------------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._server.serve_forever,
                                            daemon=True, name="engine-worker")
            self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            # BaseServer.shutdown blocks on an event only serve_forever
            # sets — calling it without a running thread hangs forever
            self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class RemoteDriver(Driver):
    """Driver-seam client forwarding every call to an EngineWorker."""

    def __init__(self, url: str, timeout: float = 60.0):
        self.url = url.rstrip("/")
        self.timeout = timeout
        p = urllib.parse.urlparse(self.url)
        self._host = p.hostname or "127.0.0.1"
        self._port = p.port or 80
        self._local = threading.local()   # per-thread keep-alive conn

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(self._host, self._port,
                                              timeout=self.timeout)
            self._local.conn = conn
        return conn

    def _call(self, method: str, body: dict,
              no_retry: bool = False) -> dict:
        """One POST per Driver-seam call over a per-thread persistent
        connection (a fresh TCP handshake per admission review costs
        more than the evaluation itself).  A failure on a REUSED
        connection is retried once — the server closing an idle
        keep-alive between requests is routine — but never a timeout
        (the call may still be executing) and never when `no_retry`
        (non-idempotent answers, e.g. delete_data's removed flag)."""
        payload = json.dumps(body).encode()
        for attempt in (0, 1):
            conn = self._conn()
            was_reused = conn.sock is not None
            try:
                if conn.sock is None:
                    conn.connect()
                    # Nagle off: request = two small writes (headers,
                    # body); coalescing against delayed ACK can cost
                    # ~40ms per call
                    conn.sock.setsockopt(socket.IPPROTO_TCP,
                                         socket.TCP_NODELAY, 1)
                conn.request("POST", f"/v1/{method}", body=payload,
                             headers={"Content-Type": "application/json"})
                resp = conn.getresponse()
                data = resp.read()
            except socket.timeout:
                conn.close()
                self._local.conn = None
                raise WorkerUnreachableError(
                    f"worker {method} timed out after {self.timeout}s")
            except (ConnectionError, OSError,
                    http.client.HTTPException) as e:
                conn.close()
                self._local.conn = None
                if attempt == 0 and was_reused and not no_retry:
                    continue    # stale keep-alive: reconnect once
                raise WorkerUnreachableError(
                    f"worker unreachable at {self.url}: {e}")
            if resp.status != 200:
                detail = data.decode(errors="replace")[:500]
                raise ClientError(
                    f"worker {method} failed: {resp.status} {detail}")
            return json.loads(data)
        raise AssertionError("unreachable")

    # ------------------------------------------------------------------

    def init(self, targets: dict[str, TargetHandler]) -> None:
        self._call("init", {"targets": sorted(targets)})

    def put_template(self, target: str, kind: str,
                     compiled: CompiledTemplate) -> None:
        self._call("put_template", {"target": target, "kind": kind,
                                    "source": compiled.source})

    def delete_template(self, target: str, kind: str) -> None:
        self._call("delete_template", {"target": target, "kind": kind})

    def put_constraint(self, target: str, kind: str, name: str,
                       constraint: dict) -> None:
        self._call("put_constraint", {"target": target, "kind": kind,
                                      "name": name, "constraint": constraint})

    def delete_constraint(self, target: str, kind: str, name: str) -> None:
        self._call("delete_constraint", {"target": target, "kind": kind,
                                         "name": name})

    def put_data(self, target: str, key: str, meta: ResourceMeta,
                 obj: dict) -> None:
        self._call("put_data", {
            "target": target, "key": key, "obj": obj,
            "meta": {"api_version": meta.api_version, "kind": meta.kind,
                     "name": meta.name, "namespace": meta.namespace}})

    def put_data_batch(self, target: str, entries) -> None:
        self._call("put_data_batch", {"target": target, "entries": [
            {"key": key, "obj": obj,
             "meta": {"api_version": meta.api_version, "kind": meta.kind,
                      "name": meta.name, "namespace": meta.namespace}}
            for key, meta, obj in entries]})

    def delete_data(self, target: str, key: str) -> bool:
        return bool(self._call("delete_data", {"target": target, "key": key},
                               no_retry=True)["removed"])

    def wipe_data(self, target: str) -> None:
        self._call("wipe_data", {"target": target})

    def query_review(self, target: str, review: dict,
                     opts: QueryOpts | None = None):
        out = self._call("query_review", {"target": target, "review": review,
                                          "opts": _opts_to_wire(opts)})
        return [_result_from_wire(r) for r in out["results"]], out.get("trace")

    def query_review_batch(self, target: str, reviews: list[dict],
                           opts: QueryOpts | None = None) -> list[tuple]:
        out = self._call("query_review_batch",
                         {"target": target, "reviews": reviews,
                          "opts": _opts_to_wire(opts)})
        return [([_result_from_wire(r) for r in e["results"]], e.get("trace"))
                for e in out["batch"]]

    def query_audit(self, target: str, opts: QueryOpts | None = None):
        out = self._call("query_audit", {"target": target,
                                         "opts": _opts_to_wire(opts)})
        return [_result_from_wire(r) for r in out["results"]], out.get("trace")

    def dump(self) -> dict:
        return self._call("dump", {})["dump"]
