"""TargetHandler contract.

The native redesign of the reference's TargetHandler interface
(vendor/.../constraint/pkg/client/client.go:103-135).  Where the reference
target supplies a ~230-line *Rego* matching library rendered into the
engine (pkg/target/target.go:29-257), a TPU-native target supplies the
same semantics as host code (`matching_constraints`, `autoreject_review`,
`make_review`) that the drivers call directly — the vectorized driver
additionally builds match *masks* from the same spec (engine/match.py).
"""

from __future__ import annotations

import abc
from typing import Any, Iterable

from gatekeeper_tpu.client.types import Result
from gatekeeper_tpu.store.table import ResourceMeta, ResourceTable


class UnhandledData(Exception):
    """ProcessData/HandleReview: object is not for this target."""


class WipeData:
    """Sentinel passed to remove_data to wipe all cached data for a target
    (reference: pkg/target/target.go WipeData, config_controller.go:185)."""


class TargetHandler(abc.ABC):
    name: str

    @abc.abstractmethod
    def process_data(self, obj: Any) -> tuple[str, ResourceMeta, dict]:
        """Map an object to (cache path key, identity meta, stored doc).
        Raises UnhandledData if the target does not own this object."""

    @abc.abstractmethod
    def handle_review(self, obj: Any) -> dict:
        """Convert a review request into the review payload dict.
        Raises UnhandledData if not recognized."""

    @abc.abstractmethod
    def handle_violation(self, result: Result) -> None:
        """Populate result.resource from result.review."""

    @abc.abstractmethod
    def match_schema(self) -> dict:
        """JSONSchema for constraint spec.match."""

    @abc.abstractmethod
    def validate_constraint(self, constraint: dict) -> None:
        """Raise ClientError on invalid constraint content."""

    # --- native match library (replaces Library() Rego) ---

    @abc.abstractmethod
    def matching_constraints(self, review: dict, constraints: Iterable[dict],
                             table: ResourceTable) -> Iterable[dict]:
        """Constraints whose spec.match selects this review."""

    @abc.abstractmethod
    def autoreject_review(self, review: dict, constraints: Iterable[dict],
                          table: ResourceTable) -> list[tuple[dict, str, dict]]:
        """[(constraint, msg, details)] for constraints that must autoreject
        this review (e.g. namespaceSelector with uncached namespace)."""

    @abc.abstractmethod
    def make_review(self, meta: ResourceMeta, obj: dict) -> dict:
        """Review payload for a cached resource during audit."""

    def make_match_engine(self, table: ResourceTable):
        """Optional vectorized matcher: an object with
        ``mask(constraints) -> bool [n_constraints, n_rows]`` agreeing
        with matching_constraints.  None -> the jax driver matches
        scalar-side (generic test targets)."""
        return None
