"""The ``local`` driver: scalar oracle engine.

Evaluates the hooks dataflow (reference: regolib/src.go — violation =
autoreject ∪ (matching_constraints × template violation); audit =
matching_reviews_and_constraints × template violation) entirely on host
with the scalar interpreter.  This is the conformance reference and the
development engine, playing the role of drivers/local in the reference
(in-process OPA, local.go:28).  The jax driver must agree with it
everywhere.
"""

from __future__ import annotations

import contextlib
import functools
import threading
from typing import Any, Iterator

from gatekeeper_tpu.api.templates import CompiledTemplate
from gatekeeper_tpu.client.interface import Driver, QueryOpts
from gatekeeper_tpu.client.targets import TargetHandler
from gatekeeper_tpu.client.types import Result, enforcement_action_of
from gatekeeper_tpu.errors import ClientError
from gatekeeper_tpu.rego.values import Obj, freeze, thaw
from gatekeeper_tpu.store.table import ResourceMeta, ResourceTable


class TargetState:
    def __init__(self):
        self.table = ResourceTable()
        self.templates: dict[str, CompiledTemplate] = {}
        self.constraints: dict[str, dict[str, dict]] = {}  # kind -> name -> raw
        self._frozen_constraints: dict[tuple[str, str], Any] = {}
        self._inv_cache: tuple[int, Any] | None = None

    def all_constraints(self) -> Iterator[dict]:
        for kind in sorted(self.constraints):
            for name in sorted(self.constraints[kind]):
                yield self.constraints[kind][name]

    def inventory_doc(self) -> Any:
        """Frozen {"cluster": ..., "namespace": ...} doc — the shape of
        data.external[target] that templates see as data.inventory
        (regolib/src.go:55-60).  Cached per table generation."""
        gen = self.table.generation
        if self._inv_cache is not None and self._inv_cache[0] == gen:
            return self._inv_cache[1]
        import urllib.parse

        cluster: dict = {}
        namespace: dict = {}
        for key, row in self.table.rows_items():
            meta = self.table.meta_at(row)
            obj = self.table.object_at(row)
            if meta is None:
                continue
            escaped = urllib.parse.quote(meta.api_version, safe="")
            if meta.namespace is None:
                cluster.setdefault(escaped, {}).setdefault(meta.kind, {})[meta.name] = obj
            else:
                namespace.setdefault(meta.namespace, {}).setdefault(
                    escaped, {}).setdefault(meta.kind, {})[meta.name] = obj
        frozen = freeze({"inventory": {"cluster": cluster, "namespace": namespace}})
        self._inv_cache = (gen, frozen)
        return frozen


class RWLock:
    """Readers-writer lock mirroring the reference drivers' RWMutex
    (local.go:43-48): queries run concurrently, mutations are exclusive.
    Same-thread re-entrance is allowed for writes (JaxDriver overrides
    call super()) and for reads taken while holding the write lock.

    Reader-side cache fills (mask/bindings/format memos) are safe
    concurrently: with writers excluded the table is stable, so racing
    readers compute identical values and last-write-wins is benign."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer: int | None = None
        self._depth = 0
        self._waiting_writers = 0
        # per-thread read depth: re-entrant reads must not block behind
        # a waiting writer (they would deadlock against it)
        self._local = threading.local()

    def acquire_read(self):
        me = threading.get_ident()
        held = getattr(self._local, "depth", 0)
        with self._cond:
            if self._writer == me:       # read within own write: nest
                self._depth += 1
                return
            if held:                     # re-entrant read: already admitted
                self._readers += 1
                self._local.depth = held + 1
                return
            # writer preference (Go sync.RWMutex semantics): fresh
            # readers queue behind pending writers so sustained read
            # load cannot starve mutations indefinitely
            while self._writer is not None or self._waiting_writers:
                self._cond.wait()
            self._readers += 1
            self._local.depth = 1

    def release_read(self):
        me = threading.get_ident()
        with self._cond:
            if self._writer == me:
                self._depth -= 1
                return
            self._readers -= 1
            self._local.depth = getattr(self._local, "depth", 1) - 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        me = threading.get_ident()
        with self._cond:
            if self._writer == me:
                self._depth += 1
                return
            self._waiting_writers += 1
            try:
                while self._writer is not None or self._readers:
                    self._cond.wait()
            finally:
                self._waiting_writers -= 1
            self._writer = me
            self._depth = 1

    def release_write(self):
        with self._cond:
            self._depth -= 1
            if self._depth == 0:
                self._writer = None
                self._cond.notify_all()

    @contextlib.contextmanager
    def read(self):
        """Shared-lock context manager (queries run concurrently)."""
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextlib.contextmanager
    def write(self):
        """Exclusive-lock context manager (mutations)."""
        self.acquire_write()
        try:
            yield
        finally:
            self.release_write()


def locked(fn):
    """Exclusive (writer) lock around a mutating Driver method."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        self._lock.acquire_write()
        try:
            return fn(self, *args, **kwargs)
        finally:
            self._lock.release_write()
    return wrapper


def locked_read(fn):
    """Shared (reader) lock around a query Driver method."""
    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        self._lock.acquire_read()
        try:
            return fn(self, *args, **kwargs)
        finally:
            self._lock.release_read()
    return wrapper


class LocalDriver(Driver):
    """Scalar reference engine (tracing mirrors local.New(local.Tracing(true)),
    main.go:68: construction-time default, overridable per query).
    Thread-safe via one re-entrant instance lock (see `locked`)."""

    def __init__(self, tracing: bool = False):
        self.default_tracing = tracing
        self.targets: dict[str, TargetHandler] = {}
        self.state: dict[str, TargetState] = {}
        self._lock = RWLock()

    # ------------------------------------------------------------------

    def init(self, targets: dict[str, TargetHandler]) -> None:
        self.targets = dict(targets)
        for name in targets:
            self.state.setdefault(name, TargetState())

    def _state(self, target: str) -> TargetState:
        st = self.state.get(target)
        if st is None:
            raise ClientError(f"unknown target {target!r}")
        return st

    @locked
    def put_template(self, target: str, kind: str, compiled: CompiledTemplate) -> None:
        self._state(target).templates[kind] = compiled

    @locked
    def delete_template(self, target: str, kind: str) -> None:
        st = self._state(target)
        st.templates.pop(kind, None)
        st.constraints.pop(kind, None)
        for k in [k for k in st._frozen_constraints if k[0] == kind]:
            del st._frozen_constraints[k]

    @locked
    def put_constraint(self, target: str, kind: str, name: str, constraint: dict) -> None:
        st = self._state(target)
        st.constraints.setdefault(kind, {})[name] = constraint
        st._frozen_constraints[(kind, name)] = freeze(constraint)

    @locked
    def delete_constraint(self, target: str, kind: str, name: str) -> None:
        st = self._state(target)
        st.constraints.get(kind, {}).pop(name, None)
        st._frozen_constraints.pop((kind, name), None)

    @locked
    def put_data(self, target: str, key: str, meta: ResourceMeta, obj: dict) -> None:
        self._state(target).table.upsert(key, obj, meta)

    @locked
    def put_data_batch(self, target: str,
                       entries: list[tuple[str, ResourceMeta, dict]]) -> None:
        """Bulk ingest under ONE writer acquisition (initial list-sync
        floods; per-object locking dominates at 1M objects).  One
        generation bump for the whole batch keeps downstream delta
        caches seeing a single churn event."""
        self._state(target).table.bulk_upsert(
            [(key, obj, meta) for key, meta, obj in entries])

    @locked
    def delete_data(self, target: str, key: str) -> bool:
        return self._state(target).table.remove(key)

    @locked
    def wipe_data(self, target: str) -> None:
        self._state(target).table.wipe()

    # ------------------------------------------------------------------

    def _frozen_constraint(self, st: TargetState, c: dict) -> Any:
        kind = (c.get("kind"), (c.get("metadata") or {}).get("name"))
        return st._frozen_constraints.get(kind) or freeze(c)

    def _eval_pair(self, st: TargetState, target: str, compiled: CompiledTemplate,
                   review: dict, frozen_review: Any, constraint: dict,
                   trace: list | None,
                   shared: dict | None = None) -> Iterator[Result]:
        """One (review, constraint) evaluation — the regolib violation body
        (src.go:19-34): input = {review, constraint}, data.inventory = inv.

        ``shared``: per-review memo dict reused across the constraint
        loop — review-pure comprehensions (rego/closures) evaluate once
        per review instead of once per (review, constraint).  Skipped
        under tracing (the tracer must observe evaluation)."""
        input_doc = Obj({"review": frozen_review,
                         "constraint": self._frozen_constraint(st, constraint)})
        # freezing the whole inventory is O(cache size); skip it for
        # templates that never read data.inventory
        inv = st.inventory_doc() if compiled.uses_inventory else None
        tracer: list | None = [] if trace is not None else None
        step = None
        if trace is not None:
            # per-step event trace (OPA topdown/trace.go equivalent):
            # tracing already bypasses memo caches, so the extra cost of
            # the stepped oracle path is confined to this debug surface
            from gatekeeper_tpu.rego.trace import StepTracer
            step = StepTracer()
        for v in compiled.interp.query_set(
                "violation", input_doc, inv, tracer=tracer, step_tracer=step,
                shared_memo=None if trace is not None else shared):
            if not isinstance(v, Obj) or "msg" not in v:
                continue  # regolib accesses r.msg; absent msg -> no response
            details = v["details"] if "details" in v else Obj()
            yield Result(
                msg=v["msg"] if isinstance(v["msg"], str) else str(thaw(v["msg"])),
                metadata={"details": thaw(details)},
                constraint=constraint,
                review=review,
                enforcement_action=enforcement_action_of(constraint),
            )
        if trace is not None:
            cname = (constraint.get("metadata") or {}).get("name")
            for line in tracer or ():
                trace.append(f"[{compiled.kind}/{cname}] {line}")
            if step is not None and step.events:
                trace.append(f"[{compiled.kind}/{cname}] steps:")
                trace.extend(f"[{compiled.kind}/{cname}] {ln}"
                             for ln in step.pretty().splitlines())

    @locked_read
    def query_review(self, target: str, review: dict,
                     opts: QueryOpts | None = None) -> tuple[list[Result], str | None]:
        st = self._state(target)
        handler = self.targets[target]
        tracing = opts.tracing if opts is not None else self.default_tracing
        trace: list | None = [] if tracing else None
        results: list[Result] = []

        constraints = list(st.all_constraints())
        shed = opts.shed_actions if opts is not None else None
        if shed:
            # brownout: shed-action constraints skipped wholesale — no
            # matching, no autoreject, no evaluation (overload.py)
            constraints = [c for c in constraints
                           if enforcement_action_of(c) not in shed]
        # autoreject (regolib src.go:7-17)
        for c, msg, details in handler.autoreject_review(review, constraints, st.table):
            results.append(Result(msg=msg, metadata={"details": details},
                                  constraint=c, review=review,
                                  enforcement_action=enforcement_action_of(c)))
        frozen_review = freeze(review)
        shared: dict = {}    # one review, many constraints: share
        #                      review-pure comprehension results
        for c in handler.matching_constraints(review, constraints, st.table):
            compiled = st.templates.get(c.get("kind", ""))
            if compiled is None:
                continue
            if trace is not None:
                trace.append(f"eval {c.get('kind')}/{(c.get('metadata') or {}).get('name')} "
                             f"review={review.get('name')}")
            results.extend(self._eval_pair(st, target, compiled, review,
                                           frozen_review, c, trace, shared))
        return results, ("\n".join(trace) if trace is not None else None)

    @locked_read
    def query_audit(self, target: str,
                    opts: QueryOpts | None = None) -> tuple[list[Result], str | None]:
        """The audit cross-product (regolib src.go:38-52 +
        matching_reviews_and_constraints target.go:69-81): every cached
        resource × every constraint.  No autoreject in the audit hook."""
        st = self._state(target)
        handler = self.targets[target]
        tracing = opts.tracing if opts is not None else self.default_tracing
        trace: list | None = [] if tracing else None
        results: list[Result] = []
        constraints = list(st.all_constraints())
        for key, row in sorted(st.table.rows_items()):
            meta = st.table.meta_at(row)
            obj = st.table.object_at(row)
            if meta is None:
                continue
            review = handler.make_review(meta, obj)
            frozen_review = freeze(review)
            shared: dict = {}
            for c in handler.matching_constraints(review, constraints, st.table):
                compiled = st.templates.get(c.get("kind", ""))
                if compiled is None:
                    continue
                results.extend(self._eval_pair(st, target, compiled, review,
                                               frozen_review, c, trace,
                                               shared))
        return results, ("\n".join(trace) if trace is not None else None)

    @locked_read
    def dump(self) -> dict:
        """All templates + constraints + data (local.go:251-284).
        Deep-copied: the snapshot must stay consistent after the lock
        is released, not alias live driver state."""
        import copy
        out: dict = {}
        for tname, st in self.state.items():
            out[tname] = {
                "templates": {k: t.source for k, t in st.templates.items()},
                "constraints": copy.deepcopy(st.constraints),
                "data": copy.deepcopy(
                    {key: st.table.object_at(row)
                     for key, row in sorted(st.table.rows_items())}),
            }
        return out
