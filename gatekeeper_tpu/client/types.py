"""Result/Response types returned by Review/Audit.

Mirrors the constraint framework's types package (reference:
vendor/.../constraint/pkg/types/validation.go:11-91) so control-plane code
(audit manager, webhook) consumes the same shapes.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any


@dataclasses.dataclass
class Result:
    msg: str = ""
    metadata: dict = dataclasses.field(default_factory=dict)
    constraint: dict | None = None      # the full constraint object
    review: Any = None                  # target-specific review payload
    resource: Any = None                # set by HandleViolation for audit hits
    enforcement_action: str = "deny"


ENFORCEMENT_ACTIONS = ("deny", "dryrun", "warn")
"""Recognized ``spec.enforcementAction`` values (reference:
apis/constraints ValidActions).  Anything else is treated as deny —
fail closed on typos."""


def enforcement_action_of(constraint: dict | None) -> str:
    """A constraint's effective enforcement action, normalized."""
    action = ((constraint or {}).get("spec") or {}).get("enforcementAction")
    if isinstance(action, str) and action in ENFORCEMENT_ACTIONS:
        return action
    return "deny"


@dataclasses.dataclass
class Response:
    target: str
    results: list[Result] = dataclasses.field(default_factory=list)
    trace: str | None = None
    input: Any = None

    def trace_dump(self) -> str:
        lines = [f"Target: {self.target}"]
        if self.trace is not None:
            lines += ["Trace:", self.trace]
        else:
            lines.append("Trace: TRACING DISABLED")
        if self.input is not None:
            lines += ["Input:", json.dumps(self.input, indent=2, default=str)]
        return "\n".join(lines)


@dataclasses.dataclass
class Responses:
    by_target: dict[str, Response] = dataclasses.field(default_factory=dict)
    handled: dict[str, bool] = dataclasses.field(default_factory=dict)

    def results(self) -> list[Result]:
        out: list[Result] = []
        for t in sorted(self.by_target):
            out.extend(self.by_target[t].results)
        return out

    def trace_dump(self) -> str:
        return "\n\n".join(r.trace_dump() for _, r in sorted(self.by_target.items()))
