"""The policy client — the façade every control-plane component calls.

Reference: vendor/.../constraint/pkg/client/client.go:24-47 (interface),
462-509 (init), 545-612 (Review/Audit).  Lifecycle and semantics follow
the reference: templates compile + register per target, constraints
validate against the generated CRD, data flows through target
ProcessData, Review/Audit fan out over targets and reconstruct violating
resources via HandleViolation.
"""

from __future__ import annotations

import os
import threading
from typing import Any

from gatekeeper_tpu.api.templates import (
    CompiledTemplate, ConstraintTemplate, compile_target_rego)
from gatekeeper_tpu.client.crd_helpers import (
    CONSTRAINT_GROUP, CONSTRAINT_VERSION, build_crd, validate_cr)
from gatekeeper_tpu.client.interface import Driver, QueryOpts
from gatekeeper_tpu.client.targets import TargetHandler, UnhandledData, WipeData
from gatekeeper_tpu.client.types import Response, Responses
from gatekeeper_tpu.errors import ClientError


class Client:
    def __init__(self, driver: Driver, targets: list[TargetHandler]):
        if not targets:
            raise ClientError("at least one target is required")
        self.driver = driver
        self.targets: dict[str, TargetHandler] = {}
        for t in targets:
            if t.name in self.targets:
                raise ClientError(f"duplicate target {t.name!r}")
            self.targets[t.name] = t
        # kind -> {target -> CompiledTemplate}; plus the generated CRD
        self.templates: dict[str, dict[str, CompiledTemplate]] = {}
        self.crds: dict[str, dict] = {}
        self.constraints: dict[str, dict[str, dict]] = {}
        # readers-writer, mirroring the reference client RWMutex
        # (client.go:545,584 — Review/Audit take RLock, mutations Lock):
        # concurrent admission reviews never serialize on each other
        from gatekeeper_tpu.client.local_driver import RWLock
        self._lock = RWLock()
        driver.init(self.targets)

    # ------------------------------------------------------------------
    # templates (client.go:211-300)

    def _compile_template(self, tmpl: ConstraintTemplate):
        """(compiled_by_target, crd) for a template.  Multi-target
        templates compile per target (``spec.targets[]`` is plural in
        the CRD, constrainttemplate_types.go:27-98; the framework keys
        templates[target][Kind], client.go:211-213); the CRD's match
        schema comes from the first target, mirroring the reference's
        single-schema CRD build."""
        if not tmpl.targets:
            raise ClientError("template has no targets")
        compiled_by_target: dict[str, CompiledTemplate] = {}
        first_handler = None
        for tt in tmpl.targets:
            if tt.target in compiled_by_target:
                raise ClientError(f"duplicate target {tt.target!r}")
            handler = self.targets.get(tt.target)
            if handler is None:
                raise ClientError(f"unknown target {tt.target!r}")
            if first_handler is None:
                first_handler = handler
            # warm-restart fast path: a snapshotted module is the parsed
            # AST of this exact source AFTER it passed hygiene checks and
            # the stage-1 vet (entries are only written below, post-vet),
            # so parse + vet are skipped wholesale on a hit
            from gatekeeper_tpu.resilience import snapshot as _snap
            compiled = None
            if _snap.enabled():
                hit = _snap.load_template_module(tmpl.kind, tt.target,
                                                 tt.rego)
                if hit is not None:
                    try:
                        from gatekeeper_tpu.api.templates import \
                            rebuild_from_module
                        module, uses_inv = hit[0]
                        compiled = rebuild_from_module(
                            tmpl.kind, tt.target, tt.rego, module, uses_inv)
                    except Exception:   # noqa: BLE001 — cold rebuild
                        compiled = None
            if compiled is None:
                compiled = compile_target_rego(tmpl.kind, tt.target, tt.rego)
                # Stage-1 static vet (analysis/vetter.py): error findings
                # reject the template at ingestion, before anything is
                # registered.  providers=None here — the client has no
                # provider registry in scope (providers may legitimately be
                # registered after the template); the reconciler enforces
                # provider existence with the live set.
                from gatekeeper_tpu.analysis import has_errors, vet_module
                diags = vet_module(compiled.module, providers=None,
                                   file=tmpl.kind)
                if has_errors(diags):
                    from gatekeeper_tpu.errors import VetError
                    raise VetError(diags)
                if _snap.enabled():
                    _snap.save_template_module(
                        tmpl.kind, tt.target, tt.rego,
                        (compiled.module, compiled.uses_inventory))
            compiled_by_target[tt.target] = compiled
        return compiled_by_target, build_crd(tmpl, first_handler.match_schema())

    def create_crd(self, template_doc: dict) -> dict:
        """Validate the template and build its constraint CRD without
        registering anything (used by the webhook's synchronous template
        validation, policy.go:211-227)."""
        _, crd = self._compile_template(ConstraintTemplate.from_dict(template_doc))
        return crd

    def add_template(self, template_doc: dict) -> Responses:
        with self._lock.write():
            tmpl = ConstraintTemplate.from_dict(template_doc)
            compiled_by_target, crd = self._compile_template(tmpl)
            self.templates[tmpl.kind] = compiled_by_target
            self.crds[tmpl.kind] = crd
            self.constraints.setdefault(tmpl.kind, {})
            handled = {}
            for target, compiled in compiled_by_target.items():
                self.driver.put_template(target, tmpl.kind, compiled)
                handled[target] = True
            return Responses(handled=handled)

    def remove_template(self, template_doc: dict) -> Responses:
        with self._lock.write():
            tmpl = ConstraintTemplate.from_dict(template_doc)
            handled = {}
            targets = self.templates.pop(tmpl.kind, {})
            self.crds.pop(tmpl.kind, None)
            self.constraints.pop(tmpl.kind, None)
            for target in targets:
                self.driver.delete_template(target, tmpl.kind)
                handled[target] = True
            return Responses(handled=handled)

    # ------------------------------------------------------------------
    # constraints (client.go:340-432)

    def validate_constraint(self, constraint: dict) -> None:
        kind = constraint.get("kind", "")
        crd = self.crds.get(kind)
        if crd is None:
            raise ClientError(f"no template registered for constraint kind {kind!r}")
        validate_cr(constraint, crd)
        for target, handler in self.targets.items():
            if target in self.templates.get(kind, {}):
                handler.validate_constraint(constraint)

    def add_constraint(self, constraint: dict) -> Responses:
        with self._lock.write():
            self.validate_constraint(constraint)
            kind = constraint["kind"]
            name = constraint["metadata"]["name"]
            self.constraints.setdefault(kind, {})[name] = constraint
            handled = {}
            for target in self.templates.get(kind, {}):
                self.driver.put_constraint(target, kind, name, constraint)
                handled[target] = True
            return Responses(handled=handled)

    def remove_constraint(self, constraint: dict) -> Responses:
        with self._lock.write():
            kind = constraint.get("kind", "")
            name = (constraint.get("metadata") or {}).get("name", "")
            self.constraints.get(kind, {}).pop(name, None)
            handled = {}
            for target in self.templates.get(kind, {}):
                self.driver.delete_constraint(target, kind, name)
                handled[target] = True
            return Responses(handled=handled)

    # ------------------------------------------------------------------
    # data (client.go:152-209)

    def add_data(self, obj: Any) -> Responses:
        with self._lock.write():
            handled = {}
            for name, handler in self.targets.items():
                if isinstance(obj, WipeData) or obj is WipeData:
                    self.driver.wipe_data(name)
                    handled[name] = True
                    continue
                try:
                    key, meta, doc = handler.process_data(obj)
                except UnhandledData:
                    continue
                self.driver.put_data(name, key, meta, doc)
                handled[name] = True
            return Responses(handled=handled)

    def add_data_batch(self, objs: list) -> Responses:
        """Bulk AddData: one lock acquisition + one driver batch write
        per target for the whole list.  Semantically identical to
        looping add_data (same paths, same per-object UnhandledData
        skips); the reference has no batch AddData because its informer
        delivers events singly — but its initial list-sync is exactly a
        batch, and at 1M objects per-call overhead dominates."""
        import gc
        with self._lock.write():
            # cyclic-GC passes during the bulk loop traverse the whole
            # (million-object) resource graph repeatedly; pause
            # collection for the bounded duration of the batch (~30%
            # of 1M-object ingest time)
            gc_was_enabled = gc.isenabled()
            gc.disable()
            try:
                handled = {}
                any_wipe = any(isinstance(o, WipeData) or o is WipeData
                               for o in objs)
                for name, handler in self.targets.items():
                    batch_fn = getattr(handler, "process_data_batch", None)
                    if batch_fn is not None and not any_wipe:
                        # wipe-free batches (the overwhelmingly common
                        # case) take the target's native batch extractor
                        entries = [e for e in batch_fn(objs)
                                   if e is not None]
                        if entries:
                            self.driver.put_data_batch(name, entries)
                            handled[name] = True
                        continue
                    entries: list = []

                    def flush():
                        if entries:
                            self.driver.put_data_batch(name, entries)
                            entries.clear()
                            handled[name] = True

                    for obj in objs:
                        if isinstance(obj, WipeData) or obj is WipeData:
                            # order matters: objects queued BEFORE the
                            # wipe must land before it (and be wiped),
                            # exactly as the looped form behaves
                            flush()
                            self.driver.wipe_data(name)
                            handled[name] = True
                            continue
                        try:
                            entries.append(handler.process_data(obj))
                        except UnhandledData:
                            continue
                    flush()
                return Responses(handled=handled)
            finally:
                if len(objs) >= 65536 and \
                        os.environ.get("GATEKEEPER_NO_GC_FREEZE") != "1":
                    # a million-object resource cache makes every later
                    # cyclic-GC pass traverse the whole graph (~4s per
                    # large allocation burst).  The cache is long-lived
                    # and acyclic (parsed JSON), so move the current
                    # heap to GC's permanent generation — refcounting
                    # still reclaims it; only cycle *detection* skips it.
                    # Young-generation collect only: a full pass would
                    # itself traverse the million objects we are about
                    # to freeze (~3.5s at 1M for nothing)
                    gc.collect(1)
                    gc.freeze()
                if gc_was_enabled:
                    gc.enable()

    def remove_data(self, obj: Any) -> Responses:
        with self._lock.write():
            handled = {}
            for name, handler in self.targets.items():
                if isinstance(obj, WipeData) or obj is WipeData:
                    self.driver.wipe_data(name)
                    handled[name] = True
                    continue
                try:
                    key, _, _ = handler.process_data(obj)
                except UnhandledData:
                    continue
                self.driver.delete_data(name, key)
                handled[name] = True
            return Responses(handled=handled)

    # ------------------------------------------------------------------
    # queries (client.go:545-612)

    def review(self, obj: Any, tracing: bool = False,
               shed_actions: frozenset[str] | None = None) -> Responses:
        # queries take the READ side (client.go:545 RLock): concurrent
        # admission reviews proceed in parallel, excluded only by
        # mutations
        with self._lock.read():
            return self._review_locked(obj, tracing, shed_actions)

    def _review_locked(self, obj: Any, tracing: bool,
                       shed_actions: frozenset[str] | None = None
                       ) -> Responses:
        responses = Responses()
        for name, handler in self.targets.items():
            try:
                review = handler.handle_review(obj)
            except UnhandledData:
                continue
            results, trace = self.driver.query_review(
                name, review, QueryOpts(tracing=tracing,
                                        shed_actions=shed_actions))
            for r in results:
                handler.handle_violation(r)
            responses.by_target[name] = Response(
                target=name, results=results, trace=trace,
                input={"review": review} if tracing else None)
            responses.handled[name] = True
        return responses

    def review_batch(self, objs: list, tracing: bool = False,
                     shed_actions: frozenset[str] | None = None
                     ) -> list[Responses]:
        """Review a micro-batch under one read-lock acquisition /
        constraint snapshot (the webhook batcher's engine pass).

        When the driver exposes ``query_review_batch`` (the jax driver's
        [B, C] device pass, SURVEY §7 step 7) the whole batch is
        evaluated as one matrix per target; otherwise per-review scalar
        queries run under the shared snapshot.  ``shed_actions`` is the
        brownout controller's shed set — those enforcement actions are
        skipped before any evaluation (webhook/overload.py)."""
        with self._lock.read():
            if tracing:
                return [self._review_locked(obj, tracing, shed_actions)
                        for obj in objs]
            batched = self.driver.query_review_batch
            responses = [Responses() for _ in objs]
            for name, handler in self.targets.items():
                idx: list[int] = []
                reviews: list = []
                for i, obj in enumerate(objs):
                    try:
                        reviews.append(handler.handle_review(obj))
                        idx.append(i)
                    except UnhandledData:
                        continue
                if not reviews:
                    continue
                outs = batched(name, reviews,
                               QueryOpts(tracing=False,
                                         shed_actions=shed_actions))
                for i, (results, trace) in zip(idx, outs):
                    for r in results:
                        handler.handle_violation(r)
                    responses[i].by_target[name] = Response(
                        target=name, results=results, trace=trace)
                    responses[i].handled[name] = True
            return responses

    def predict_review_seconds(self, n_reviews: int) -> float | None:
        """Cost-model-predicted seconds to evaluate a review batch of
        ``n_reviews`` (summed over targets).  None when the driver has
        no predictor or the model is uncalibrated — the batcher treats
        None as "no opinion" and never sheds on it."""
        fn = getattr(self.driver, "predict_review_batch_seconds", None)
        if fn is None:
            return None
        total: float | None = None
        for name in self.targets:
            pred = fn(name, n_reviews)
            if pred is not None:
                total = pred if total is None else total + pred
        return total

    def certified_review_rungs(self, max_n: int | None = None
                               ) -> list[int] | None:
        """Batch sizes inside every target's Stage-7 certified compile
        surface (the micro-batcher's deadline-shrink ladder), or None
        when any target lacks a fully certified surface — the batcher
        then falls back to blind halving."""
        fn = getattr(self.driver, "certified_review_rungs", None)
        if fn is None:
            return None
        out: set[int] | None = None
        for name in self.targets:
            rungs = fn(name, max_n)
            if rungs is None:
                return None
            out = set(rungs) if out is None else out & set(rungs)
        return sorted(out) if out else None

    def prefetch_external(self, objs: list) -> None:
        """Warm the external-data provider caches for a micro-batch
        ahead of evaluation (the webhook batcher wires this in): one
        batched fetch round per provider covering every key any review
        in the batch will look up.  Best-effort and a no-op on drivers
        without the prefetch surface."""
        fn = getattr(self.driver, "prefetch_external_for_reviews", None)
        if fn is None:
            return
        with self._lock.read():
            for name, handler in self.targets.items():
                reviews: list = []
                for obj in objs:
                    try:
                        reviews.append(handler.handle_review(obj))
                    except UnhandledData:
                        continue
                if reviews:
                    fn(name, reviews)

    def audit(self, tracing: bool = False,
              limit_per_constraint: int | None = None,
              full: bool = False) -> Responses:
        """Full cross-product audit.  ``limit_per_constraint`` pushes the
        audit manager's violations cap (reference manager.go:35) down to
        the driver, where the jax engine turns it into a device top-k
        instead of formatting everything and truncating on the host.
        ``full=True`` defeats the driver's sweep memoization (mask /
        bindings / format caches) so the sweep measures a genuine
        re-preparation + re-upload + re-evaluation of every pair."""
        with self._lock.read():
            return self._audit_locked(tracing, limit_per_constraint, full)

    def _audit_locked(self, tracing: bool,
                      limit_per_constraint: int | None = None,
                      full: bool = False) -> Responses:
        responses = Responses()
        for name, handler in self.targets.items():
            results, trace = self.driver.query_audit(
                name, QueryOpts(tracing=tracing,
                                limit_per_constraint=limit_per_constraint,
                                full=full))
            for r in results:
                handler.handle_violation(r)
            responses.by_target[name] = Response(target=name, results=results,
                                                 trace=trace)
            responses.handled[name] = True
        return responses

    # ------------------------------------------------------------------
    # continuous enforcement (enforce/reactor.py rides these)

    def react(self, kind: str | None = None) -> dict | None:
        """Fold the store's dirty pages for one resource ``kind`` (or
        all kinds when None) into the verdict ledger — the reactor's
        rung 1: a single-object event becomes a single-page re-eval
        with no sweep in between.  Reader lock, like audit: the table
        is not mutated, the ledger has its own lock.  No-op (None) on
        drivers without the paged surface or with pages off."""
        fn = getattr(self.driver, "react_kind", None)
        if fn is None:
            return None
        with self._lock.read():
            out: dict | None = None
            for name in self.targets:
                r = fn(name, kind)
                if r is not None:
                    out = r if out is None else {
                        k: out.get(k, 0) + v for k, v in r.items()}
            return out

    def resync(self, kind: str | None = None) -> dict | None:
        """Force a whole-kind diff re-apply against the existing ledger
        entry (rungs 2/3): the entry is marked cold but keeps its rows,
        so the rebuild emits exactly the true appear/clear diff — a
        clean resync is event-free, never a phantom storm."""
        fn = getattr(self.driver, "resync_kind", None)
        if fn is None:
            return None
        with self._lock.read():
            out: dict | None = None
            for name in self.targets:
                r = fn(name, kind)
                if r is not None:
                    out = r if out is None else {
                        k: out.get(k, 0) + v for k, v in r.items()}
            return out

    def sync_kind(self, api_version: str, kind: str, objs: list) -> int:
        """Replace the store's residents of one (apiVersion, kind) with
        ``objs`` — the relist half of a rung-2 resync.  Listed objects
        are upserted; residents absent from the list are removed.
        Returns the number of stale residents deleted."""
        removed = 0
        with self._lock.write():
            residents = getattr(self.driver, "kind_residents", None)
            for name, handler in self.targets.items():
                live_keys = set()
                for obj in objs:
                    try:
                        key, meta, doc = handler.process_data(obj)
                    except UnhandledData:
                        continue
                    live_keys.add(key)
                    self.driver.put_data(name, key, meta, doc)
                if residents is None:
                    continue
                for key in residents(name, api_version, kind):
                    if key not in live_keys:
                        self.driver.delete_data(name, key)
                        removed += 1
        return removed

    def reset(self) -> None:
        with self._lock.write():
            for kind, targets in list(self.templates.items()):
                for target in targets:
                    self.driver.delete_template(target, kind)
            for name in self.targets:
                self.driver.wipe_data(name)
            self.templates.clear()
            self.crds.clear()
            self.constraints.clear()

    def dump(self) -> dict:
        return self.driver.dump()


class Backend:
    """One-client-per-backend guard (backend.go:10-67)."""

    def __init__(self, driver: Driver):
        self.driver = driver
        self._has_client = False

    def new_client(self, targets: list[TargetHandler]) -> Client:
        if self._has_client:
            raise ClientError("only one client per backend is allowed")
        self._has_client = True
        return Client(self.driver, targets)
