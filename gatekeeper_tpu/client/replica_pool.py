"""Replicated engine pool: the reference's HA story as a Driver.

The reference scales admission horizontally: N webhook pods each hold a
FULL copy of the engine state (templates, constraints, synced data —
rebuilt per pod from watches) and the Service load-balances admission
requests across them (deploy/gatekeeper.yaml:161 StatefulSet +
pkg/util/ha_status.go per-pod status slots; no state is sharded).

``ReplicaPool`` packages that shape behind the Driver seam: mutations
broadcast to every replica (the watch-replication analogue), reviews
round-robin across replicas (the Service analogue), audits run on one
replica (the reference audits per pod too — results are idempotent
status writes).  With subprocess workers (``spawn_workers``) this turns
the GIL-bound scalar admission path into true multi-core serving on one
host, exactly as multiple pods would on one node.
"""

from __future__ import annotations

import itertools
import os
import subprocess
import sys
import threading
from typing import Any

from gatekeeper_tpu.client.interface import Driver, QueryOpts
from gatekeeper_tpu.client.remote_driver import (RemoteDriver,
                                                 WorkerUnreachableError)
from gatekeeper_tpu.client.targets import TargetHandler
from gatekeeper_tpu.errors import ClientError
from gatekeeper_tpu.store.table import ResourceMeta


class ReplicaPool(Driver):
    """Driver fan-out over N equivalent replicas."""

    def __init__(self, drivers: list[Driver]):
        if not drivers:
            raise ClientError("ReplicaPool needs at least one replica")
        self.drivers = list(drivers)
        self._rr = itertools.count()
        self._procs: list[subprocess.Popen] = []

    # -- replica selection ------------------------------------------------

    def _next(self) -> Driver:
        return self.drivers[next(self._rr) % len(self.drivers)]

    def _all(self, fn: str, *args) -> list:
        """Apply a mutation on every replica.  A replica whose
        broadcast fails is EVICTED from rotation before the error
        surfaces — the remaining replicas stay mutually consistent and
        queries never round-robin onto half-updated state (the
        reference analogue: a failing pod drops out of the Service on
        readiness; it does not keep receiving admission traffic)."""
        out: list = []
        failed: list[tuple[Driver, Exception]] = []
        for d in list(self.drivers):
            try:
                out.append(getattr(d, fn)(*args))
            except Exception as e:
                failed.append((d, e))
        if failed:
            dead = {id(d) for d, _e in failed}
            survivors = [d for d in self.drivers if id(d) not in dead]
            if not survivors:
                raise ClientError(
                    f"all replicas failed {fn}: {failed[0][1]}")
            self.drivers = survivors     # atomic swap for readers
            raise ClientError(
                f"{len(failed)} replica(s) evicted after failed {fn}: "
                f"{failed[0][1]}")
        return out

    # -- Driver seam: mutations broadcast ---------------------------------

    def init(self, targets: dict[str, TargetHandler]) -> None:
        self._all("init", targets)

    def put_template(self, target: str, kind: str, compiled) -> None:
        self._all("put_template", target, kind, compiled)

    def delete_template(self, target: str, kind: str) -> None:
        self._all("delete_template", target, kind)

    def put_constraint(self, target: str, kind: str, name: str,
                       constraint: dict) -> None:
        self._all("put_constraint", target, kind, name, constraint)

    def delete_constraint(self, target: str, kind: str, name: str) -> None:
        self._all("delete_constraint", target, kind, name)

    def put_data(self, target: str, key: str, meta: ResourceMeta,
                 obj: dict) -> None:
        self._all("put_data", target, key, meta, obj)

    def put_data_batch(self, target: str, entries) -> None:
        self._all("put_data_batch", target, entries)

    def delete_data(self, target: str, key: str) -> bool:
        return any(self._all("delete_data", target, key))

    def wipe_data(self, target: str) -> None:
        self._all("wipe_data", target)

    # -- Driver seam: queries distributed ---------------------------------

    def _failover(self, fn_name: str, *args):
        """Run a query on the next replica; a replica that errors is
        evicted and the query fails over to the survivors (a crashed
        worker must not fail admission — the Service analogue routes
        around a dead pod).  Raises only when every replica failed."""
        last: Exception | None = None
        for _attempt in range(len(self.drivers)):
            d = self._next()
            try:
                return getattr(d, fn_name)(*args)
            except WorkerUnreachableError as e:
                # transport failure only: a semantic error (4xx the
                # worker answered with) would fail identically on
                # every replica and must surface, not cascade-evict
                last = e
                self.drivers = [x for x in self.drivers if x is not d] \
                    or self.drivers
                if len(self.drivers) == 1 and self.drivers[0] is d:
                    break       # d was the only replica left
        raise ClientError(f"all replicas failed {fn_name}: {last}")

    def query_review(self, target: str, review: dict,
                     opts: QueryOpts | None = None):
        return self._failover("query_review", target, review, opts)

    def query_review_batch(self, target: str, reviews: list[dict],
                           opts: QueryOpts | None = None) -> list[tuple]:
        return self._failover("query_review_batch", target, reviews, opts)

    def query_audit(self, target: str, opts: QueryOpts | None = None):
        # audits are whole-state queries; any single replica answers
        # (the reference runs the audit on each pod independently and
        # the status writes are last-writer-wins, ha_status.go)
        return self._failover("query_audit", target, opts)

    def dump(self) -> dict:
        return self.drivers[0].dump()

    # -- subprocess worker management -------------------------------------

    @classmethod
    def spawn_workers(cls, n: int, timeout: float = 60.0,
                      env: dict | None = None) -> "ReplicaPool":
        """Launch ``n`` engine-worker subprocesses
        (``python -m gatekeeper_tpu.cmd.worker``) on ephemeral ports and
        return a pool of RemoteDrivers over them.  Workers are separate
        OS processes, so scalar admission evaluation escapes the GIL —
        one host serves like ``n`` webhook pods."""
        procs: list[tuple[subprocess.Popen, str]] = []
        try:
            for _ in range(n):
                # child_env: if THIS process already fell back to the
                # scalar/CPU path (dead device tunnel), the workers are
                # pinned to JAX_PLATFORMS=cpu instead of each burning a
                # probe timeout rediscovering the dead plugin
                from gatekeeper_tpu.utils.device_probe import child_env
                proc = subprocess.Popen(
                    [sys.executable, "-m", "gatekeeper_tpu.cmd.worker",
                     "--port", "0"],
                    stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
                    env={**child_env(), **(env or {})}, text=True,
                    cwd=os.path.dirname(os.path.dirname(
                        os.path.dirname(os.path.abspath(__file__)))))
                # the worker prints "engine worker up at <url>" once
                # ready; read it on a thread so a silently-hung worker
                # (stuck import, buffered output) cannot block past the
                # deadline — readline() alone would wait forever
                line = _readline_with_timeout(
                    proc.stderr, timeout,
                    lambda ln: "engine worker up at" in ln)
                if line is None or "engine worker up at" not in line:
                    raise ClientError(
                        f"worker failed to start within {timeout}s "
                        f"(exit={proc.poll()})")
                url = line.rsplit(" ", 1)[-1].strip()
                procs.append((proc, url))
                # drain further stderr so the pipe never blocks the child
                threading.Thread(target=_drain, args=(proc.stderr,),
                                 daemon=True).start()
            pool = cls([RemoteDriver(url) for _proc, url in procs])
            pool._procs = [p for p, _u in procs]
            return pool
        except Exception:
            for proc, _url in procs:
                proc.terminate()
            raise

    def close(self) -> None:
        for proc in self._procs:
            proc.terminate()
        for proc in self._procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        self._procs.clear()

    def __enter__(self) -> "ReplicaPool":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def _drain(stream) -> None:
    try:
        for _ in stream:
            pass
    except Exception:
        pass


def _readline_with_timeout(stream, timeout: float, want) -> str | None:
    """First line matching `want` (or the line that ended the stream),
    or None on timeout.  Runs the blocking readline on a daemon thread;
    on timeout the thread is abandoned (the caller terminates the
    subprocess, which unblocks it)."""
    box: list[str | None] = [None]
    done = threading.Event()

    def run():
        while True:
            ln = stream.readline()
            if not ln or want(ln):
                box[0] = ln or None
                done.set()
                return

    t = threading.Thread(target=run, daemon=True)
    t.start()
    done.wait(timeout)
    return box[0]
