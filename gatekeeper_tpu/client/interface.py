"""The Driver seam — where evaluation engines plug in.

The reference's Driver interface is six methods over Rego sources and
path-addressed JSON (vendor/.../drivers/interface.go:21-33).  The native
equivalent is typed rather than stringly:

  reference                      this seam
  ---------------------------------------------------------------
  Init                           init(targets)
  PutModule(name, rego)          put_template(target, kind, compiled)
  DeleteModule(name)             delete_template(target, kind)
  PutData("/constraints/...")    put_constraint(target, kind, name, c)
  PutData("/external/...")       put_data(target, key, meta, obj)
  DeleteData(path)               delete_constraint / delete_data / wipe_data
  Query("hooks[t].violation")    query_review(target, review, opts)
  Query("hooks[t].audit")        query_audit(target, opts)
  Dump                           dump()

Two drivers implement it: ``local`` (scalar oracle engine, the dev /
conformance reference — analogue of drivers/local) and ``jax`` (vectorized
device engine with scalar fallback).  Both must pass the same conformance
suite, like the reference's local and remote drivers
(client_test.go:17-23).
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any

from gatekeeper_tpu.api.templates import CompiledTemplate
from gatekeeper_tpu.client.targets import TargetHandler
from gatekeeper_tpu.client.types import Result
from gatekeeper_tpu.store.table import ResourceMeta


@dataclasses.dataclass
class QueryOpts:
    tracing: bool = False  # drivers.Tracing (interface.go:9-19)
    # audit: stop formatting results after N per constraint (the audit
    # manager's -constraintViolationsLimit, reference manager.go:35; the
    # jax driver then only host-formats up to N violating pairs per
    # constraint while still counting the rest on device)
    limit_per_constraint: int | None = None
    # audit: force a FULL sweep — the jax driver drops its mask /
    # bindings / format memoization for this sweep so every
    # constraint×resource pair is genuinely re-prepared, re-uploaded and
    # re-evaluated ("full sweep" vs "memoized steady" are two separately
    # metered numbers; the scalar oracle is always full, so it ignores
    # this flag)
    full: bool = False
    # overload brownout (webhook/overload.py): enforcement actions to
    # SKIP entirely this query — e.g. frozenset({"dryrun"}) or
    # frozenset({"dryrun", "warn"}).  Constraints with a shed action are
    # filtered out before any evaluation (scalar or device); "deny" is
    # never a legal member — deny constraints are never shed, only the
    # failurePolicy path may reject them wholesale.
    shed_actions: frozenset[str] | None = None


class Driver(abc.ABC):
    @abc.abstractmethod
    def init(self, targets: dict[str, TargetHandler]) -> None: ...

    @abc.abstractmethod
    def put_template(self, target: str, kind: str, compiled: CompiledTemplate) -> None: ...

    @abc.abstractmethod
    def delete_template(self, target: str, kind: str) -> None: ...

    @abc.abstractmethod
    def put_constraint(self, target: str, kind: str, name: str, constraint: dict) -> None: ...

    @abc.abstractmethod
    def delete_constraint(self, target: str, kind: str, name: str) -> None: ...

    @abc.abstractmethod
    def put_data(self, target: str, key: str, meta: ResourceMeta, obj: dict) -> None: ...

    def put_data_batch(self, target: str,
                       entries: list[tuple[str, ResourceMeta, dict]]) -> None:
        """Bulk ingest; drivers override to take their write lock once
        (LocalDriver) or ship one wire call (RemoteDriver) — this
        default only guarantees the semantics."""
        for key, meta, obj in entries:
            self.put_data(target, key, meta, obj)

    @abc.abstractmethod
    def delete_data(self, target: str, key: str) -> bool: ...

    @abc.abstractmethod
    def wipe_data(self, target: str) -> None: ...

    @abc.abstractmethod
    def query_review(self, target: str, review: dict,
                     opts: QueryOpts | None = None) -> tuple[list[Result], str | None]: ...

    def query_review_batch(self, target: str, reviews: list[dict],
                           opts: QueryOpts | None = None) -> list[tuple]:
        """Batch admission; drivers override to evaluate as one pass
        (JaxDriver's [C, B] device path, RemoteDriver's single wire
        call).  The default is the per-review loop, so every call site
        may invoke this unconditionally."""
        return [self.query_review(target, rv, opts) for rv in reviews]

    @abc.abstractmethod
    def query_audit(self, target: str,
                    opts: QueryOpts | None = None) -> tuple[list[Result], str | None]: ...

    @abc.abstractmethod
    def dump(self) -> dict: ...
