"""Self-test probe: run the conformance scenario table against a live
driver.

Native port of the constraint framework's Probe
(vendor/.../constraint/pkg/client/probe_client.go:10-50): wrap a
Driver in a fresh Backend/Client over the built-in probe target and
expose each e2e scenario (e2e_tests.go) as a runnable check.  The
framework ships this so an embedding application can self-validate an
engine at startup/readiness; a failure message carries the engine
dump, exactly like the Go (`probe_client.go:42-46`).

The scenario semantics are the same table
tests/test_client_conformance.py pins in CI; the probe is the
runtime-callable twin.
"""

from __future__ import annotations

from typing import Callable

from gatekeeper_tpu.client.targets import TargetHandler, UnhandledData
from gatekeeper_tpu.client.types import Result
from gatekeeper_tpu.store.table import ResourceMeta


class ProbeTarget(TargetHandler):
    """The probe's target handler — a native transcription of the
    framework's test handler (vendor/.../client/test_handler.go:14-119):
    data keyed by Name, constraints match when their kind equals the
    review's ForConstraint, autoreject when a constraint carries a
    namespaceSelector while no v1/Namespace is cached."""

    name = "probe.target"

    def process_data(self, obj):
        if isinstance(obj, dict) and "Name" in obj:
            meta = ResourceMeta(api_version="v1", kind="ProbeData",
                                name=obj["Name"], namespace=None)
            return obj["Name"], meta, obj
        raise UnhandledData(f"unhandled: {obj!r}")

    def handle_review(self, obj):
        if isinstance(obj, dict) and "Name" in obj:
            return obj
        raise UnhandledData(f"unhandled review: {obj!r}")

    def handle_violation(self, result: Result):
        result.resource = result.review

    def match_schema(self):
        return {"properties": {"label": {"type": "string"}}}

    def validate_constraint(self, constraint):
        return None

    def make_review(self, meta, obj):
        return obj

    def matching_constraints(self, review, constraints, table):
        for c in constraints:
            if c.get("kind") == review.get("ForConstraint"):
                yield c

    def autoreject_review(self, review, constraints, table):
        has_ns = any(
            (m := table.meta_at(row)) is not None and m.kind == "Namespace"
            and m.api_version == "v1"
            for _, row in table.rows_items())
        out = []
        for c in constraints:
            match = (c.get("spec") or {}).get("match") or {}
            if "namespaceSelector" in match and not has_ns:
                out.append((c, "REJECTION", {}))
        return out


_DENY_ALL = """package foo
violation[{"msg": "DENIED", "details": {}}] {
	"always" == "always"
}"""


def _template(kind: str = "Foo") -> dict:
    return {"apiVersion": "templates.gatekeeper.sh/v1alpha1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": kind.lower()},
            "spec": {"crd": {"spec": {"names": {"kind": kind}}},
                     "targets": [{"target": ProbeTarget.name,
                                  "rego": _DENY_ALL}]}}


def _constraint(kind: str = "Foo", name: str = "ph",
                match: dict | None = None) -> dict:
    spec: dict = {}
    if match is not None:
        spec["match"] = match
    return {"apiVersion": "constraints.gatekeeper.sh/v1alpha1",
            "kind": kind, "metadata": {"name": name}, "spec": spec}


def _data(name: str) -> dict:
    return {"Name": name, "ForConstraint": "Foo"}


class ProbeError(Exception):
    pass


def _want(cond: bool, msg: str, rsps) -> None:
    if not cond:
        raise ProbeError(f"{msg}: {rsps!r}")


# --- the scenario table (e2e_tests.go:65-540, same names) -------------

def _add_template(c):
    c.add_template(_template())


def _deny_all(c):
    c.add_template(_template())
    c.add_constraint(_constraint())
    rsps = c.review(_data("Sara"))
    res = rsps.results()
    _want(len(res) == 1 and res[0].msg == "DENIED", "deny all", rsps)


def _deny_all_audit(c, n: int = 1):
    c.add_template(_template())
    c.add_constraint(_constraint())
    for i in range(n):
        c.add_data(_data(f"obj{i}"))
    rsps = c.audit()
    res = rsps.results()
    _want(len(res) == n and all(r.msg == "DENIED" for r in res),
          f"audit x{n}", rsps)


def _autoreject_all(c):
    # e2e_tests.go:183-246: the rejectable constraint yields BOTH the
    # REJECTION and its normal evaluation result (2 results total)
    c.add_template(_template())
    c.add_constraint(_constraint(match={"namespaceSelector": {
        "matchLabels": {"hi": "there"}}}))
    rsps = c.review(_data("Sara"))
    msgs = sorted(str(r.msg) for r in rsps.results())
    _want(len(msgs) == 2 and "REJECTION" in msgs, "autoreject", rsps)


def _remove_data(c):
    c.add_template(_template())
    c.add_constraint(_constraint())
    c.add_data(_data("Sara"))
    c.add_data(_data("Max"))
    _want(len(c.audit().results()) == 2, "pre-remove audit", None)
    c.remove_data(_data("Max"))
    rsps = c.audit()
    _want(len(rsps.results()) == 1, "post-remove audit", rsps)


def _remove_constraint(c):
    c.add_template(_template())
    c.add_constraint(_constraint())
    c.add_data(_data("Sara"))
    _want(len(c.audit().results()) == 1, "pre-remove audit", None)
    c.remove_constraint(_constraint())
    rsps = c.audit()
    _want(len(rsps.results()) == 0, "post-remove audit", rsps)


def _remove_template(c):
    c.add_template(_template())
    c.add_constraint(_constraint())
    c.add_data(_data("Sara"))
    c.remove_template(_template())
    rsps = c.audit()
    _want(len(rsps.results()) == 0, "post-remove-template audit", rsps)


def _tracing(c, on: bool):
    c.add_template(_template())
    c.add_constraint(_constraint())
    rsps = c.review(_data("Sara"), tracing=on)
    for resp in rsps.by_target.values():
        if on:
            _want(resp.trace is not None, "trace expected", rsps)
        else:
            _want(resp.trace is None, "no trace expected", rsps)


def _audit_tracing(c, on: bool):
    c.add_template(_template())
    c.add_constraint(_constraint())
    c.add_data(_data("Sara"))
    rsps = c.audit(tracing=on)
    for resp in rsps.by_target.values():
        if on:
            _want(resp.trace is not None, "audit trace expected", rsps)
        else:
            _want(resp.trace is None, "no audit trace expected", rsps)


SCENARIOS: dict[str, Callable] = {
    "Add Template": _add_template,
    "Deny All": _deny_all,
    "Deny All Audit": lambda c: _deny_all_audit(c, 1),
    "Deny All Audit x2": lambda c: _deny_all_audit(c, 2),
    "Autoreject All": _autoreject_all,
    "Remove Data": _remove_data,
    "Remove Constraint": _remove_constraint,
    "Remove Template": _remove_template,
    "Tracing Off": lambda c: _tracing(c, False),
    "Tracing On": lambda c: _tracing(c, True),
    "Audit Tracing Enabled": lambda c: _audit_tracing(c, True),
    "Audit Tracing Disabled": lambda c: _audit_tracing(c, False),
}


class Probe:
    """probe_client.go Probe: a client over the probe target, exposing
    each scenario as a zero-arg callable returning None or raising
    ProbeError with the engine dump appended."""

    def __init__(self, driver):
        from gatekeeper_tpu.client.client import Backend
        # a FRESH driver only (the Go Probe likewise constructs its own
        # Backend): registering the probe target on a driver that is
        # already serving a client would clobber that client's target
        # registry — the exact hazard the one-client-per-backend guard
        # exists to prevent
        if getattr(driver, "targets", None):
            raise ValueError(
                "Probe requires a fresh driver; this one already serves "
                f"targets {sorted(driver.targets)} — construct a new "
                "driver instance for the probe")
        self.client = Backend(driver).new_client([ProbeTarget()])

    def test_funcs(self) -> dict[str, Callable[[], None]]:
        return {name: self._run_test(name) for name in SCENARIOS}

    def _run_test(self, name: str) -> Callable[[], None]:
        def run() -> None:
            self.client.reset()
            try:
                SCENARIOS[name](self.client)
            except Exception as e:
                try:
                    dump = self.client.dump()
                except Exception as e2:     # noqa: BLE001
                    dump = str(e2)
                raise ProbeError(
                    f"Error: {e}\n\nEngine dump: {dump}") from e
        return run


def list_builtins() -> list[str]:
    """``--builtins``: one line per registered Rego builtin, dotted
    name sorted, with unsupported stubs marked and their recorded
    reason shown (the `_unsupported` factory tags its stubs).  The
    sanctioned-egress pointer lives here too: readers checking why
    http.send is refused find external_data in the same listing."""
    from gatekeeper_tpu.rego import builtins as bi
    lines = []
    for name in sorted(bi.REGISTRY):
        dotted = ".".join(name)
        fn = bi.REGISTRY[name]
        reason = getattr(fn, "unsupported_reason", None)
        if reason is not None:
            lines.append(f"  {dotted:36s} UNSUPPORTED: {reason}")
        elif name == ("external_data",):
            lines.append(f"  {dotted:36s} provider lookups (batched, "
                         "TTL-cached, circuit-broken; see Provider CRs)")
        else:
            lines.append(f"  {dotted}")
    return lines


def lint_template_doc(doc: dict, file: str = "") -> list:
    """Run both static-analysis stages over one ConstraintTemplate doc
    (gatekeeper_tpu/analysis): the Stage-1 AST vet, then an attempted
    lowering with Stage-2 IR verification.  A template the vectorizer
    cannot lower is a warning (``rego_not_vectorizable``): it still
    evaluates on the scalar oracle, just not on the device path.
    Providers come from the live ExternalDataRuntime when one exists;
    otherwise provider references are not checked (same contract as
    Client ingestion)."""
    from gatekeeper_tpu.analysis import vet_module, verify_program
    from gatekeeper_tpu.analysis.diagnostics import WARNING, Diagnostic
    from gatekeeper_tpu.api.templates import compile_target_rego
    from gatekeeper_tpu.errors import Location, RegoError
    from gatekeeper_tpu.externaldata.runtime import get_runtime
    from gatekeeper_tpu.ir.lower import CannotLower, lower_template

    rt = get_runtime()
    providers = set(rt.provider_names()) if rt is not None else None
    kind = ((((doc.get("spec") or {}).get("crd") or {}).get("spec") or {})
            .get("names") or {}).get("kind") or \
        (doc.get("metadata") or {}).get("name") or "<template>"
    label = file or kind
    diags = []
    for tt in ((doc.get("spec") or {}).get("targets") or ()):
        try:
            compiled = compile_target_rego(kind, tt.get("target") or "",
                                           tt.get("rego") or "")
        except RegoError as err:
            loc = err.location
            diags.append(Diagnostic(err.code, "error", err.message,
                                    Location(loc.row, loc.col, label)))
            continue
        diags.extend(vet_module(compiled.module, providers=providers,
                                file=label))
        try:
            lowered = lower_template(compiled.module, compiled.interp)
        except CannotLower as e:
            diags.append(Diagnostic(
                "rego_not_vectorizable", WARNING,
                f"template does not lower to a device program ({e}); "
                "it will evaluate on the scalar oracle",
                Location(file=label)))
            continue
        diags.extend(verify_program(lowered, providers=providers,
                                    file=label))
    return diags


def _doc_kind(doc: dict) -> str:
    return ((((doc.get("spec") or {}).get("crd") or {}).get("spec") or {})
            .get("names") or {}).get("kind") or \
        (doc.get("metadata") or {}).get("name") or "<template>"


def _scalar_fallback_pins() -> set:
    """Template kinds pinned ``scalar-fallback`` in
    library/lowering_buckets.json — the acknowledgment record a strict
    lint honors: a pinned kind's ``rego_not_vectorizable`` warning is
    expected, not a regression (keys are ``Kind`` or ``Kind (path)``)."""
    import json as _json
    import os as _os
    path = _os.path.join(_os.path.dirname(_os.path.dirname(
        _os.path.abspath(__file__))), "library", "lowering_buckets.json")
    try:
        with open(path, encoding="utf-8") as fh:
            data = _json.load(fh)
    except (OSError, ValueError):
        return set()
    return {k.split(" (")[0] for k, v in data.items()
            if isinstance(v, str) and v.startswith("scalar-fallback")}


def _severity_rc(n_err: int, n_warn: int) -> int:
    """The analysis-subcommand exit contract, shared by --lint /
    --policyset / --cost / --certify: 0 clean, 1 warning-severity
    findings only, 2 any error-severity finding (or unreadable
    input)."""
    return 2 if n_err else (1 if n_warn else 0)


def _load_work(paths: list[str], use_library: bool):
    """Shared --certify/--footprint/--shardplan work-list builder:
    ConstraintTemplate docs from yaml files plus (optionally) the
    built-in library with one example constraint each.  Returns None
    when any input is unreadable (the caller exits 2)."""
    import sys

    import yaml
    work: list[tuple[str, dict, list]] = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as fh:
                loaded = list(yaml.safe_load_all(fh))
        except (OSError, yaml.YAMLError) as e:
            print(f"{p}: cannot load: {e}", file=sys.stderr)
            return None
        work.extend((p, d, []) for d in loaded
                    if isinstance(d, dict)
                    and d.get("kind") == "ConstraintTemplate")
    if use_library:
        from gatekeeper_tpu.library import all_docs
        work.extend(("<library>", tdoc, [cdoc])
                    for tdoc, cdoc in all_docs())
    return work


def _compile_work(work, errs: dict):
    """Shared per-template compile+lower loop for the analysis
    subcommands: yields (kind, compiled, lowered-or-None,
    example-constraints).  Parse/compile failures print a FAIL line
    and bump ``errs["n"]``; scalar-fallback templates yield with
    ``lowered=None`` so each subcommand can word its own pin line."""
    import sys

    from gatekeeper_tpu.api.templates import compile_target_rego
    from gatekeeper_tpu.ir.lower import CannotLower, lower_template
    for _label, tdoc, cdocs in work:
        kind = _doc_kind(tdoc)
        compiled = lowered = None
        for tt in ((tdoc.get("spec") or {}).get("targets") or ()):
            try:
                compiled = compile_target_rego(
                    kind, tt.get("target") or "", tt.get("rego") or "")
                lowered = lower_template(compiled.module, compiled.interp)
            except CannotLower:
                lowered = None
            except Exception as e:      # noqa: BLE001 — parse/compile
                errs["n"] += 1
                print(f"  FAIL {kind}: {type(e).__name__}: {e}",
                      file=sys.stderr)
                compiled = None
            break
        if compiled is None:
            continue
        yield kind, compiled, lowered, cdocs


def run_lint(paths: list[str], use_library: bool = False,
             strict: bool = False) -> int:
    """``--lint``: print diagnostics with locations.  Exit contract
    (:func:`_severity_rc`): 2 on any error-severity finding or
    unreadable input, 1 on warnings-that-matter (``--strict``
    escalates warnings, except a pinned kind's
    ``rego_not_vectorizable`` — see :func:`_scalar_fallback_pins`),
    0 clean."""
    import yaml
    docs: list[tuple[str, dict]] = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as fh:
                loaded = list(yaml.safe_load_all(fh))
        except (OSError, yaml.YAMLError) as e:
            import sys
            print(f"{p}: cannot load: {e}", file=sys.stderr)
            return 2
        docs.extend((p, d) for d in loaded
                    if isinstance(d, dict)
                    and d.get("kind") == "ConstraintTemplate")
    if use_library:
        from gatekeeper_tpu.library import all_docs
        docs.extend(("<library>", tdoc) for tdoc, _c in all_docs())
    pins = _scalar_fallback_pins() if strict else set()
    n_err = 0
    n_warn = 0
    for label, doc in docs:
        kind = _doc_kind(doc)
        for d in lint_template_doc(doc, file=label):
            print(d.format())
            if d.severity == "error":
                n_err += 1
            elif strict and not (d.code == "rego_not_vectorizable"
                                 and kind in pins):
                n_warn += 1
    tail = f", {n_warn} unpinned warning(s)" if strict else ""
    print(f"lint: {len(docs)} template(s), {n_err} error(s){tail}")
    return _severity_rc(n_err, n_warn)


def _library_entries() -> list:
    """(kind, LoweredProgram | None, [example constraint doc]) per
    built-in library template — the policy set the --policyset/--cost
    reports analyze."""
    from gatekeeper_tpu.api.templates import compile_target_rego
    from gatekeeper_tpu.ir.lower import CannotLower, lower_template
    from gatekeeper_tpu.library import all_docs
    entries = []
    for tdoc, cdoc in all_docs():
        kind = _doc_kind(tdoc)
        lowered = None
        for tt in ((tdoc.get("spec") or {}).get("targets") or ()):
            try:
                compiled = compile_target_rego(
                    kind, tt.get("target") or "", tt.get("rego") or "")
                lowered = lower_template(compiled.module, compiled.interp)
            except (CannotLower, Exception):    # noqa: B014
                lowered = None
            break
        entries.append((kind, lowered, [cdoc]))
    return entries


def run_policyset() -> int:
    """``--policyset``: the Stage-3 whole-set report over the built-in
    library — shared predicate subprograms (what the audit sweep
    dedups), shadowing/unreachability findings, and the top static
    costs."""
    from gatekeeper_tpu.analysis.policyset import analyze_policy_set
    entries = _library_entries()
    report = analyze_policy_set(entries)
    groups = report["shared_subprograms"]
    for g in groups:
        print(f"  shared {g['digest']} [{g['ekind']}] "
              f"sites={g['sites']}: {', '.join(g['kinds'])}")
    for d in report["findings"]:
        print("  " + d.format())
    # Stage-6 regex-lowering verdicts: which constant patterns run as
    # in-program DFAs vs host lookup tables (regex_off_dfa findings
    # above carry the per-pattern reasons)
    for kind, info in sorted(report.get("dfa_lowering", {}).items()):
        print(f"  dfa {kind}: {info['in_program']} in-program, "
              f"{len(info['off_dfa'])} host-table")
    # Stage-5 row-locality verdicts: cross-row templates are shard_map
    # ineligible and excluded from footprint-driven selective
    # invalidation
    from gatekeeper_tpu.analysis import footprint
    n_cross_row = 0
    for kind, low, _c in entries:
        if low is None:
            continue
        fp = footprint.analyze(kind, low)
        if not fp.row_local:
            n_cross_row += 1
            reasons = "; ".join(fp.cross_row_reasons) or "cross-row"
            print(f"  locality {kind}: cross-row (shard_map ineligible) "
                  f"— {reasons}")
    top = sorted(report["template_costs"].items(),
                 key=lambda kv: -kv[1]["units"])[:5]
    for kind, cv in top:
        print(f"  cost {kind}: {cv['units']} units "
              f"(gathers={cv['gathers']} matmul_flops={cv['matmul_flops']} "
              f"padding_waste={cv['padding_waste']})")
    n_vec = sum(1 for _k, low, _c in entries if low is not None)
    print(f"policyset: {len(entries)} template(s) ({n_vec} lowered), "
          f"{len(groups)} shared subprogram group(s), "
          f"{len(report['findings'])} finding(s), "
          f"{n_cross_row} cross-row")
    n_err = sum(1 for d in report["findings"] if d.severity == "error")
    n_warn = sum(1 for d in report["findings"] if d.severity != "error")
    return _severity_rc(n_err, n_warn)


def run_cost() -> int:
    """``--cost``: predicted-vs-measured static cost over the built-in
    library.  Builds a GATEKEEPER_COST_PROBE_N-row mixed workload (one
    constraint per template), runs one warm full device sweep for the
    measured ``device_s``, fits the seconds-per-unit scale
    (costmodel.calibrate), and reports the per-template predicted
    seconds that scale implies."""
    import os as _os
    import random
    from gatekeeper_tpu.analysis import costmodel
    from gatekeeper_tpu.client.client import Backend
    import gatekeeper_tpu.engine.jax_driver as jd_mod
    from gatekeeper_tpu.library import all_docs, make_mixed
    from gatekeeper_tpu.target.k8s import K8sValidationTarget

    n = int(_os.environ.get("GATEKEEPER_COST_PROBE_N", "2000"))
    entries = _library_entries()
    units = {kind: costmodel.estimate(low, n, 1).units()
             for kind, low, _c in entries if low is not None}
    total_units = sum(units.values())
    jd = jd_mod.JaxDriver()
    c = Backend(jd).new_client([K8sValidationTarget()])
    for tdoc, cdoc in all_docs():
        c.add_template(tdoc)
        c.add_constraint(cdoc)
    c.add_data_batch(make_mixed(random.Random(7), n))
    measured = None
    if not jd.scalar_only:
        saved = jd_mod.SMALL_WORKLOAD_EVALS
        jd_mod.SMALL_WORKLOAD_EVALS = 0
        try:
            c.audit(limit_per_constraint=20, full=True)   # compile warm
            c.audit(limit_per_constraint=20, full=True)
        finally:
            jd_mod.SMALL_WORKLOAD_EVALS = saved
        measured = (jd.last_sweep_phases or {}).get("device_s")
    # the exit contract's warning tier: templates over the configured
    # install-time unit budget (the same knob the reconciler gate uses)
    budget_env = _os.environ.get("GATEKEEPER_COST_BUDGET_UNITS")
    n_over = 0
    if budget_env:
        try:
            budget_units = float(budget_env)
        except ValueError:
            budget_units = None
        if budget_units is not None:
            for kind, u in sorted(units.items()):
                if u > budget_units:
                    n_over += 1
                    print(f"  over-budget {kind}: {u:.3g} units "
                          f"> {budget_units:.3g}")
    if measured is None or total_units <= 0:
        print(f"cost: {len(units)} lowered template(s), "
              f"{total_units:.3g} units at n={n}; no device measurement "
              "(scalar-only backend)")
        return _severity_rc(0, n_over)
    scale = costmodel.calibrate([(total_units, measured)])
    for kind in sorted(units, key=lambda k: -units[k]):
        pred = costmodel.predict_seconds(units[kind], scale)
        print(f"  {kind}: {units[kind]:.3g} units -> "
              f"predicted {pred * 1e3:.3f} ms")
    print(f"cost: n={n}, measured device_s={measured:.4f}, "
          f"predicted total={costmodel.predict_seconds(total_units, scale):.4f} "
          f"(scale={scale:.3e} s/unit, {len(units)} templates)")
    return _severity_rc(0, n_over)


def run_trace(out_path: str | None = None) -> int:
    """``--trace [--out file]``: capture one forced-full library sweep
    under the span tracer and emit Chrome trace-event JSON (Perfetto /
    chrome://tracing loadable) plus the sweep's per-template device-
    time attribution under the ``gatekeeperTrace`` metadata key (extra
    top-level keys are explicitly allowed by the trace-event format).

    Exit contract: 0 with a device-attributed trace, 1 when the sweep
    ran scalar-only (a host-span-only trace still emits), 2 when the
    sweep failed outright."""
    import json as _json
    import os as _os
    import random
    import sys as _sys
    from gatekeeper_tpu.client.client import Backend
    import gatekeeper_tpu.engine.jax_driver as jd_mod
    from gatekeeper_tpu.library import all_docs, make_mixed
    from gatekeeper_tpu.obs.trace import get_tracer
    from gatekeeper_tpu.target.k8s import K8sValidationTarget

    n = int(_os.environ.get("GATEKEEPER_TRACE_PROBE_N", "500"))
    tracer = get_tracer()
    try:
        jd = jd_mod.JaxDriver()
        c = Backend(jd).new_client([K8sValidationTarget()])
        for tdoc, cdoc in all_docs():
            c.add_template(tdoc)
            c.add_constraint(cdoc)
        c.add_data_batch(make_mixed(random.Random(7), n))
        saved = jd_mod.SMALL_WORKLOAD_EVALS
        jd_mod.SMALL_WORKLOAD_EVALS = 0
        try:
            c.audit(limit_per_constraint=20, full=True)   # compile warm
            tracer.reset()      # keep only the measured sweep's spans
            c.audit(limit_per_constraint=20, full=True)
        finally:
            jd_mod.SMALL_WORKLOAD_EVALS = saved
    except Exception as e:      # noqa: BLE001 — render a verdict
        print(f"trace: sweep failed: {type(e).__name__}: {e}",
              file=_sys.stderr)
        return 2
    phases = jd.last_sweep_phases or {}
    payload = tracer.export()
    payload["gatekeeperTrace"] = {
        "workload_rows": n,
        "device_s": phases.get("device_s"),
        "phases": {k: v for k, v in phases.items() if k != "attribution"},
        "attribution": phases.get("attribution"),
    }
    text = _json.dumps(payload, sort_keys=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text)
    else:
        print(text)
    att = phases.get("attribution")
    n_templates = len((att or {}).get("templates", []))
    print(f"trace: {len(payload['traceEvents'])} events, "
          f"{n_templates} attributed template(s), "
          f"device_s={phases.get('device_s')}"
          + (f" -> {out_path}" if out_path else ""), file=_sys.stderr)
    if att is None:
        print("trace: WARNING scalar-only sweep — no device attribution",
              file=_sys.stderr)
        return 1
    return 0


def run_certify(paths: list[str], use_library: bool = False) -> int:
    """``--certify``: Stage-4 translation validation
    (analysis/transval.py) over template files and/or the built-in
    library.  Each device-lowered template is checked against the
    interpreter on its bounded small-model universe; scalar-fallback
    templates are reported as pinned (there is no device program to
    certify).  Exit contract (:func:`_severity_rc`): 2 on any
    counterexample or unloadable input, 1 if every lowered template
    certified but some universe was truncated by the model budget,
    0 fully certified.

    GATEKEEPER_TRANSVAL_CORPUS=<dir> additionally serializes every
    counterexample found into the regression corpus directory
    (tests/corpus/transval/ replays them first in the parity suite)."""
    import os as _os
    import sys
    import time as _time

    from gatekeeper_tpu.analysis import transval

    work = _load_work(paths, use_library)
    if work is None:
        return 2
    corpus_dir = _os.environ.get("GATEKEEPER_TRANSVAL_CORPUS")
    t0 = _time.perf_counter()
    errs = {"n": 0}
    n_cert = n_pin = n_ce = n_trunc = models = 0
    for kind, compiled, lowered, cdocs in _compile_work(work, errs):
        if lowered is None:
            n_pin += 1
            print(f"  pin  {kind}: scalar fallback (no device program)")
            continue
        lowered = transval.maybe_miscompiled(kind, lowered)
        try:
            result = transval.validate_template(
                kind, compiled, lowered=lowered,
                constraints=cdocs or None)
        except Exception as e:          # noqa: BLE001
            errs["n"] += 1
            print(f"  FAIL {kind}: validator error: {e}", file=sys.stderr)
            continue
        if isinstance(result, transval.Certificate):
            n_cert += 1
            models += result.models_checked
            n_trunc += 1 if result.truncated else 0
            excused = result.excused_f32 + result.excused_mixed
            print(f"  ok   {kind}: certified "
                  f"({result.models_checked} models, fp={result.fp_models}"
                  + (f", excused={excused}" if excused else "") + ")")
        else:
            n_ce += 1
            print(f"  FAIL {kind}: counterexample ({result.note}) "
                  f"expected={result.expected} actual={result.actual}",
                  file=sys.stderr)
            if corpus_dir:
                print(f"       saved: "
                      f"{transval.save_counterexample(corpus_dir, result)}")
    wall = _time.perf_counter() - t0
    print(f"certify: {len(work)} template(s), {n_cert} certified, "
          f"{n_pin} pinned, {n_ce} counterexample(s), "
          f"{models} models in {wall:.1f}s")
    return _severity_rc(n_ce + errs["n"], n_trunc)


def run_footprint(paths: list[str], use_library: bool = False) -> int:
    """``--footprint``: Stage-5 dependency analysis
    (analysis/footprint.py) over template files and/or the built-in
    library.  For each device-lowered template, print the column
    read-set with sensitivity classes, external-provider reads, and
    the row-locality verdict, then perturbation-validate the footprint
    against smallmodel worlds; scalar-fallback templates are reported
    as pinned (no device program, so the whole kind invalidates on any
    change).  Exit contract (:func:`_severity_rc`): 2 on any footprint
    violation or unloadable input, 1 when every footprint validated
    but some template is cross-row (shard_map ineligible, selective
    invalidation disabled for it), 0 fully row-local and validated."""
    import sys
    import time as _time

    from gatekeeper_tpu.analysis import footprint

    work = _load_work(paths, use_library)
    if work is None:
        return 2
    t0 = _time.perf_counter()
    errs = {"n": 0}
    n_ok = n_pin = n_cross = n_viol = 0
    for kind, compiled, lowered, cdocs in _compile_work(work, errs):
        if lowered is None:
            n_pin += 1
            print(f"  pin  {kind}: scalar fallback (whole-kind "
                  "invalidation, shard_map ineligible)")
            continue
        try:
            fp = footprint.analyze(kind, lowered)
            fp = footprint.maybe_narrowed(kind, fp)
            found = footprint.validate_footprint(
                kind, compiled, lowered, fp, constraints=cdocs or None)
        except Exception as e:          # noqa: BLE001
            errs["n"] += 1
            print(f"  FAIL {kind}: analyzer error: {e}", file=sys.stderr)
            continue
        verdict = "row-local" if fp.row_local else "CROSS-ROW"
        tag = "ok  " if fp.row_local else "warn"
        print(f"  {tag} {kind}: {verdict}, "
              f"{len(fp.columns)} column(s)"
              + (f", providers={','.join(fp.providers)}"
                 if fp.providers else ""))
        for col in fp.columns:
            print(f"         reads {col.format()}")
        if not fp.row_local:
            n_cross += 1
            for reason in fp.cross_row_reasons:
                print(f"         cross-row: {reason}")
        else:
            n_ok += 1
        for v in found:
            n_viol += 1
            print(f"  FAIL {v.format()}", file=sys.stderr)
    wall = _time.perf_counter() - t0
    print(f"footprint: {len(work)} template(s), {n_ok} row-local, "
          f"{n_cross} cross-row, {n_pin} pinned, "
          f"{n_viol} violation(s) in {wall:.1f}s")
    return _severity_rc(n_viol + errs["n"], n_cross)


def _ensure_sim_devices(n: int) -> None:
    """Give this process at least ``n`` CPU devices for the simulated
    mesh, BEFORE first backend contact (after that the count is
    frozen; the config update then raises and we leave whatever the
    environment provided)."""
    import os

    try:
        import jax
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:   # noqa: BLE001 — older jax / backend already up
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def run_shardplan(paths: list[str], use_library: bool = False) -> int:
    """``--shardplan``: Stage-6 partition-plan certification
    (analysis/shardplan.py) over template files and/or the built-in
    library.  For each device-lowered template, derive the
    resource-axis partition plan (per-node sharding states, required
    collectives, padding constraints, per-shard H2D layout) and
    execute it on a 2-shard simulated mesh against the unsharded
    oracle; CROSS-ROW templates are certified shard-ineligible with
    the footprint's reason and scalar-fallback templates are reported
    as pinned (no device program, replicated path).  Exit contract
    (:func:`_severity_rc`): 2 on any plan violation or unloadable
    input, 1 when every eligible plan validated but some template is
    ineligible or pinned, 0 fully shard-eligible."""
    import sys
    import time as _time

    _ensure_sim_devices(2)
    from gatekeeper_tpu.analysis import shardplan

    work = _load_work(paths, use_library)
    if work is None:
        return 2
    t0 = _time.perf_counter()
    errs = {"n": 0}
    n_elig = n_inelig = n_pin = n_viol = 0
    for kind, compiled, lowered, cdocs in _compile_work(work, errs):
        if lowered is None:
            n_pin += 1
            print(f"  pin  {kind}: scalar fallback (no device program, "
                  "replicated path)")
            continue
        try:
            plan = shardplan.analyze(kind, lowered)
            found: list = []
            if plan.eligible:
                plan, found = shardplan.validate_plan(
                    kind, compiled, lowered, plan,
                    constraints=cdocs or None)
        except Exception as e:          # noqa: BLE001
            errs["n"] += 1
            print(f"  FAIL {kind}: analyzer error: {e}", file=sys.stderr)
            continue
        if plan.eligible:
            n_elig += 1
            n_shard = sum(1 for _i, s in plan.node_shardings
                          if s == shardplan.SHARDED)
            cols = ", ".join(f"{op}[{ax}]:{operand}"
                             for op, ax, operand in plan.collectives)
            val = (f", validated@{plan.shards_validated}"
                   if plan.validated else "")
            print(f"  ok   {kind}: shard-eligible, {n_shard}/"
                  f"{len(plan.node_shardings)} sharded node(s){val}")
            print(f"         collectives: {cols}")
            print(f"         padding: {'; '.join(plan.padding)}")
        else:
            n_inelig += 1
            print(f"  warn {kind}: shard-ineligible — {plan.reason}")
        for v in found:
            n_viol += 1
            print(f"  FAIL {v.format()}", file=sys.stderr)
    wall = _time.perf_counter() - t0
    print(f"shardplan: {len(work)} template(s), {n_elig} shard-eligible, "
          f"{n_inelig} ineligible, {n_pin} pinned, "
          f"{n_viol} violation(s) in {wall:.1f}s")
    return _severity_rc(n_viol + errs["n"], n_inelig + n_pin)


def run_compilesurface(paths: list[str], use_library: bool = False) -> int:
    """``--compilesurface``: Stage-7 compile-surface certification
    (analysis/compilesurface.py) over template files and/or the
    built-in library.  For each device-lowered template, statically
    enumerate every shape signature its jitted programs can be
    dispatched with (the pad-geometry ladders of ir/prep.py under the
    deployment caps) and print the certified signature count per
    padded axis; a template whose surface is input-unbounded under the
    caps is an error-severity finding (an attacker-controlled retrace
    storm), and scalar-fallback templates are reported as pinned (no
    device program, nothing to certify).  Exit contract
    (:func:`_severity_rc`): 2 on any unbounded surface or unloadable
    input, 1 when every device surface is bounded but some template is
    pinned, 0 fully certified."""
    import sys
    import time as _time

    from gatekeeper_tpu.analysis import compilesurface

    work = _load_work(paths, use_library)
    if work is None:
        return 2
    t0 = _time.perf_counter()
    errs = {"n": 0}
    n_cert = n_unbounded = n_pin = 0
    total_sigs = 0
    for kind, compiled, lowered, cdocs in _compile_work(work, errs):
        if lowered is None:
            n_pin += 1
            print(f"  pin  {kind}: scalar fallback (no device program, "
                  "nothing to certify)")
            continue
        try:
            cert = compilesurface.analyze(kind, lowered)
        except Exception as e:          # noqa: BLE001
            errs["n"] += 1
            print(f"  FAIL {kind}: analyzer error: {e}", file=sys.stderr)
            continue
        if cert.bounded:
            n_cert += 1
            total_sigs += cert.n_signatures
            axes = ", ".join(f"{cls}[{lo}..{cap}]:{n}"
                             for cls, lo, cap, n in cert.axes)
            print(f"  ok   {kind}: {cert.n_signatures} signature(s), "
                  f"{cert.delta_rungs} delta rung(s)")
            print(f"         axes: {axes or '(static only)'}")
        else:
            n_unbounded += 1
            print(f"  FAIL {kind}: compile_surface_unbounded — "
                  f"{cert.reason}", file=sys.stderr)
    wall = _time.perf_counter() - t0
    print(f"compilesurface: {len(work)} template(s), {n_cert} certified, "
          f"{n_unbounded} unbounded, {n_pin} pinned, "
          f"{total_sigs} total signature(s) in {wall:.1f}s")
    return _severity_rc(n_unbounded + errs["n"], n_pin)


def run_memsurface(paths: list[str], use_library: bool = False) -> int:
    """``--memsurface``: Stage-8 memory-surface certification
    (analysis/memsurface.py) over template files and/or the built-in
    library.  For each device-lowered template, print the certified
    worst-signature peak and resident footprint against the installed
    HBM budget; a peak past the budget is an error-severity
    ``hbm_budget_exceeded`` finding, and scalar-fallback templates are
    reported as pinned (no device program, zero device bytes).

    Claimed certificates are validated, not trusted: the probe builds
    the real Bindings for each template at a small world
    (``GATEKEEPER_MS_PROBE_N``, default 64 resources) and checks
    per-array that the certificate's claim at the exact built shapes
    dominates the bytes actually materialized — a certificate that
    under-claims any array (the ``GATEKEEPER_MEMSURFACE_TEST_UNDER``
    seam seeds one deliberately) is an error-severity
    ``memsurface_underclaim`` finding.  Exit contract
    (:func:`_severity_rc`): 2 on any budget violation, under-claim, or
    unloadable input, 1 when some template is pinned, 0 fully
    certified within budget."""
    import os as _os
    import random
    import sys
    import time as _time

    import numpy as np

    from gatekeeper_tpu.analysis import memsurface
    from gatekeeper_tpu.analysis.transval import _world_state
    from gatekeeper_tpu.ir.prep import build_bindings
    from gatekeeper_tpu.library import make_mixed

    work = _load_work(paths, use_library)
    if work is None:
        return 2
    t0 = _time.perf_counter()
    errs = {"n": 0}
    n_cert = n_over = n_pin = n_under = 0
    probe_n = int(_os.environ.get("GATEKEEPER_MS_PROBE_N", "64"))
    st, _rows, _handler = _world_state(make_mixed(random.Random(13),
                                                  probe_n))
    budget = memsurface.budget_bytes()
    certs: dict = {}
    # build_bindings packs value+presence (".v"/".p") and constraint-set
    # (".B"/".bitmap") companions under one modeled base name
    suffixes = (".v", ".p", ".B", ".bitmap")
    for kind, compiled, lowered, cdocs in _compile_work(work, errs):
        if lowered is None:
            n_pin += 1
            certs[kind] = memsurface.scalar_surface(kind)
            print(f"  pin  {kind}: scalar fallback (host-evaluated, "
                  "no device bytes to certify)")
            continue
        try:
            cert = memsurface.analyze(kind, lowered)
        except Exception as e:          # noqa: BLE001
            errs["n"] += 1
            print(f"  FAIL {kind}: analyzer error: {e}", file=sys.stderr)
            continue
        certs[kind] = cert
        # ---- validate: claimed bytes must dominate the built arrays
        under: list[str] = []
        try:
            bindings = build_bindings(lowered.spec, st.table, cdocs)
        except Exception as e:          # noqa: BLE001
            errs["n"] += 1
            print(f"  FAIL {kind}: bindings build error: {e}",
                  file=sys.stderr)
            continue
        model_item: dict[str, int] = {}
        for name, _dcls, itemsize in cert.bindings:
            model_item[name] = max(model_item.get(name, 0), itemsize)
        grouped: dict[str, list] = {}
        for aname, arr in bindings.arrays.items():
            mname = aname
            if mname not in model_item:
                for suf in suffixes:
                    base = aname[:-len(suf)] if aname.endswith(suf) else None
                    if base and base in model_item:
                        mname = base
                        break
            grouped.setdefault(mname, []).append(arr)
        for mname, arrs in sorted(grouped.items()):
            built = sum(int(a.nbytes) for a in arrs)
            if mname not in model_item:
                under.append(f"{mname} unmodeled ({built} B built)")
                continue
            claimed = model_item[mname] * max(
                int(np.prod(a.shape)) for a in arrs)
            if claimed < built:
                under.append(f"{mname} claims {claimed} B < "
                             f"{built} B built")
        if under:
            n_under += 1
            print(f"  FAIL {kind}: memsurface_underclaim — "
                  + "; ".join(under[:3]), file=sys.stderr)
            continue
        peak = cert.peak_bytes()
        dims = {"c": bindings.c_pad, "r": bindings.r_pad}
        resident = cert.resident_bytes(
            dims, shapes={k: a.shape for k, a in bindings.arrays.items()})
        reason = memsurface.budget_reason(cert)
        if reason is not None:
            n_over += 1
            print(f"  FAIL {kind}: {reason}", file=sys.stderr)
            continue
        n_cert += 1
        print(f"  ok   {kind}: peak {peak / (1 << 20):.1f} MiB @ worst "
              f"signature, {resident / (1 << 20):.2f} MiB resident "
              f"@ n={probe_n}")
    set_bytes = memsurface.policy_set_bytes(certs=certs)
    wall = _time.perf_counter() - t0
    print(f"memsurface: {len(certs)} template(s), {n_cert} certified, "
          f"{n_over} over budget, {n_under} under-claimed, {n_pin} "
          f"pinned; policy set {set_bytes / (1 << 30):.2f} GiB of "
          f"{budget / (1 << 30):.0f} GiB budget in {wall:.1f}s")
    return _severity_rc(n_over + n_under + errs["n"], n_pin)


def run_whatif() -> int:
    """``--whatif``: self-validate the what-if engine's four parity
    contracts over the built-in library (ROADMAP item 5) —

    - shadow: one combined live ∪ candidate sweep, candidate half
      bit-identical to a standalone candidate install;
    - replay: a store-snapshot re-audit reproduces the live verdicts;
    - fleet: a 2-cluster stacked mega-sweep matches the per-cluster
      loop oracle;
    - stream: a webhook-recorded admission corpus replays exactly both
      scalar and through the device micro-batcher (identical digests),
      with byte-capped events surfaced in ``skipped_oversize``.

    Exit contract (:func:`_severity_rc`): 2 on any parity break, 1 when
    parity held but only on the scalar fallback (semantics validated,
    device NOT — same distinction as the engine probe verdict line),
    0 clean on the device path."""
    import os as _os
    import random
    import tempfile

    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.library import all_docs, make_mixed
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    from gatekeeper_tpu.whatif import (ShadowSession, fleet_audit,
                                       fleet_loop_oracle, make_cluster,
                                       normalize_results, replay_admissions,
                                       replay_admissions_batched,
                                       replay_snapshot,
                                       standalone_candidate_verdicts,
                                       verdict_digest)

    n = int(_os.environ.get("GATEKEEPER_WHATIF_PROBE_N", "300"))
    pairs = all_docs()
    templates = [t for t, _c in pairs]
    constraints = [c for _t, c in pairs]
    driver = JaxDriver()
    handler = K8sValidationTarget()
    client = Backend(driver).new_client([handler])
    for d in templates:
        client.add_template(d)
    for d in constraints:
        client.add_constraint(d)
    client.add_data_batch(make_mixed(random.Random(7), n))
    state = driver._state(handler.name).table.snapshot_state()
    baseline = normalize_results(
        client.audit(limit_per_constraint=20, full=True).results())
    n_err = 0

    candidate = constraints[1:]
    with ShadowSession(client, tag="candidate") as sess:
        sess.stage(templates, candidate)
        rep = sess.sweep(limit_per_constraint=20)
    oracle = standalone_candidate_verdicts(templates, candidate, state, 20)
    ok = rep.shadow == oracle and rep.live == baseline
    n_err += 0 if ok else 1
    print(f"  {'ok  ' if ok else 'FAIL'} shadow: live={len(rep.live)} "
          f"candidate={len(rep.shadow)} added={len(rep.added)} "
          f"cleared={len(rep.cleared)} digest={rep.shadow_digest} "
          f"oracle={verdict_digest(oracle)} "
          f"shared_groups={rep.dedup['groups_cross_version']}")

    rrep = replay_snapshot(templates, constraints, state, 20)
    ok = rrep.verdicts == baseline
    n_err += 0 if ok else 1
    print(f"  {'ok  ' if ok else 'FAIL'} replay: "
          f"{rrep.n_resources} resource(s) -> {len(rrep.verdicts)} "
          f"verdict(s) digest={rrep.digest} in {rrep.wall_s:.2f}s")

    fleet = [make_cluster(f"c{i}", templates, constraints,
                          objs=make_mixed(random.Random(100 + i), n // 3))
             for i in range(2)]
    frep = fleet_audit(fleet, 20)
    _v, digests, _w = fleet_loop_oracle(fleet, 20)
    ok = frep.digests == digests
    n_err += 0 if ok else 1
    print(f"  {'ok  ' if ok else 'FAIL'} fleet: {frep.n_clusters} "
          f"cluster(s), {len(frep.kinds_stacked)} stacked / "
          f"{len(frep.kinds_replicated)} replicated kind(s), "
          f"{frep.device_dispatches} dispatch(es), digests="
          f"{','.join(frep.digests)}")

    # admission-stream replay: record a small corpus through the
    # webhook handler into a throwaway capture log, replay it scalar
    # AND through the device micro-batcher, and demand exact
    # reproduction with bit-identical stream digests; one synthetic
    # byte-capped event must land in skipped_oversize, not be guessed
    # at (rollout's promotion gate consumes exactly this report).
    from gatekeeper_tpu.obs import flightrecorder as fr
    from gatekeeper_tpu.webhook.policy import ValidationHandler
    with tempfile.TemporaryDirectory() as tmp:
        saved_env = {k: _os.environ.get(k)
                     for k in ("GATEKEEPER_FLIGHT_DIR",
                               "GATEKEEPER_FLIGHT_ADMISSION")}
        saved_rec = fr._recorder
        _os.environ["GATEKEEPER_FLIGHT_DIR"] = tmp
        _os.environ["GATEKEEPER_FLIGHT_ADMISSION"] = "1"
        fr._recorder = None
        try:
            vh = ValidationHandler(client)
            recorded = make_mixed(random.Random(11), min(n, 48))
            for obj in recorded:
                vh.handle({
                    "uid": "u", "operation": "CREATE",
                    "kind": {"group": "", "version": "v1",
                             "kind": obj.get("kind", "")},
                    "name": (obj.get("metadata") or {}).get("name", ""),
                    "userInfo": {"username": "probe", "groups": []},
                    "object": obj})
            events = fr.load_admission_corpus(tmp)
        finally:
            tmp_rec = fr._recorder
            fr._recorder = saved_rec
            for k, v in saved_env.items():
                if v is None:
                    _os.environ.pop(k, None)
                else:
                    _os.environ[k] = v
            try:
                if tmp_rec is not None and tmp_rec._capture is not None:
                    tmp_rec._capture.close()
            except Exception:   # noqa: BLE001 — probe hygiene only
                pass
    events.append({"request": {"object": {"__truncated__": True,
                                          "metadata": {"name": "big"}}},
                   "allowed": True, "verdicts": []})
    srep = replay_admissions(events, client)
    brep = replay_admissions_batched(events, client)
    ok = (srep.exact and brep.exact
          and srep.replayed == brep.replayed == len(recorded)
          and srep.digest == brep.digest
          and srep.skipped_oversize == brep.skipped_oversize == 1)
    n_err += 0 if ok else 1
    print(f"  {'ok  ' if ok else 'FAIL'} stream: {brep.replayed} "
          f"event(s) replayed, {brep.skipped_oversize} oversize "
          f"skipped, {brep.skipped} error(s), scalar={srep.digest} "
          f"batched={brep.digest}")

    scalar = bool(getattr(driver, "scalar_only", False))
    if scalar:
        print("  warn scalar-only backend: parity validated on the "
              "oracle path, device NOT")
    print(f"whatif: {n_err} parity failure(s) "
          f"({'scalar-fallback' if scalar else 'device'})")
    return _severity_rc(n_err, 1 if scalar else 0)


def run_rollout(use_library: bool = False) -> int:
    """``--rollout [--library]``: self-contained candidate promotion
    against a seeded corpus (ROADMAP item 5, PR 18).  Builds a live
    client (a 4-template subset by default, the full builtin library
    with ``--library``), records an admission corpus through the
    webhook handler into a throwaway capture log, then drives a
    constraint-only candidate through the full promotion ladder —
    shadow sweep → batched corpus replay (scalar-oracle parity) →
    dryrun → warn → deny — and prints the per-rung evidence, the
    capture-log health counters, and a 4-cluster fleet graduation
    plan.  All snapshot/flight side effects land in a temp dir.

    Exit contract (:func:`_severity_rc`): 2 when the candidate fails
    to graduate (or the fleet plan blocks/holds a cluster), 1 when it
    graduated but only on the scalar fallback, 0 clean on device."""
    import os as _os
    import random
    import sys
    import tempfile
    import time as _time

    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.library import all_docs, make_mixed
    from gatekeeper_tpu.obs import flightrecorder as fr
    from gatekeeper_tpu.rollout import PromotionController, graduate_fleet
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    from gatekeeper_tpu.webhook.policy import ValidationHandler
    from gatekeeper_tpu.whatif import make_cluster

    t0 = _time.perf_counter()
    n = int(_os.environ.get("GATEKEEPER_ROLLOUT_PROBE_N", "200"))
    pairs = all_docs() if use_library else all_docs()[:4]
    templates = [t for t, _c in pairs]
    constraints = [c for _t, c in pairs]
    driver = JaxDriver()
    handler = K8sValidationTarget()
    client = Backend(driver).new_client([handler])
    for d in templates:
        client.add_template(d)
    for d in constraints:
        client.add_constraint(d)
    client.add_data_batch(make_mixed(random.Random(7), n))
    n_err = n_warn = 0

    with tempfile.TemporaryDirectory() as tmp:
        saved_env = {k: _os.environ.get(k)
                     for k in ("GATEKEEPER_FLIGHT_DIR",
                               "GATEKEEPER_FLIGHT_ADMISSION",
                               "GATEKEEPER_SNAPSHOT_DIR")}
        saved_rec = fr._recorder
        _os.environ["GATEKEEPER_FLIGHT_DIR"] = tmp
        _os.environ["GATEKEEPER_FLIGHT_ADMISSION"] = "1"
        _os.environ["GATEKEEPER_SNAPSHOT_DIR"] = \
            _os.path.join(tmp, "snaps")
        fr._recorder = None
        try:
            vh = ValidationHandler(client)
            for obj in make_mixed(random.Random(23), min(n, 64)):
                vh.handle({
                    "uid": "u", "operation": "CREATE",
                    "kind": {"group": "", "version": "v1",
                             "kind": obj.get("kind", "")},
                    "name": (obj.get("metadata") or {}).get("name", ""),
                    "userInfo": {"username": "probe", "groups": []},
                    "object": obj})
            events = fr.load_admission_corpus(tmp)
            st = fr.get_flight_recorder().capture_stats() or {}
            ok = bool(events) and st.get("dropped", 0) == 0 \
                and st.get("write_errors", 0) == 0
            n_err += 0 if ok else 1
            print(f"  {'ok  ' if ok else 'FAIL'} capture: "
                  f"{len(events)} event(s) in "
                  f"{st.get('segments', 0)} segment(s), "
                  f"{st.get('dropped', 0)} drop(s), "
                  f"{st.get('torn_truncated', 0)} torn tail(s), "
                  f"{st.get('write_errors', 0)} write error(s)")

            # the candidate drops one constraint — a shrink can never
            # deny what the recorded set allowed, so the evidence
            # gates must all pass
            candidate = constraints[1:]
            ctrl = PromotionController(
                client, templates, candidate, name="probe",
                events=events, verify_parity=True)
            final = ctrl.run(target_rung="deny")
            for h in ctrl.history:
                ev = ctrl.evidence.get(h["to"], {})
                keys = ("added", "cleared", "replayed",
                        "skipped_oversize", "parity", "enforcement")
                detail = ", ".join(f"{k}={ev[k]}" for k in keys
                                   if k in ev)
                print(f"    {h['frm']} -> {h['to']}: {h['reason']}"
                      f"{'  [' + detail + ']' if detail else ''}")
            g = ctrl.evidence.get("replay_gate", {})
            ok = final == "deny" and g.get("parity") is True
            n_err += 0 if ok else 1
            print(f"  {'ok  ' if ok else 'FAIL'} promote: "
                  f"state={final} rung={ctrl.installed} — "
                  f"{g.get('replayed', 0)} event(s) replayed, "
                  f"{g.get('unexpected_denials', '?')} unexpected "
                  f"denial(s), {g.get('skipped_oversize', 0)} "
                  f"oversize, scalar={g.get('scalar_digest', '')} "
                  f"batched={g.get('batched_digest', '')}")
            enforced = all(
                ((client.constraints.get(c["kind"]) or {})
                 .get(c["metadata"]["name"]) or {})
                .get("spec", {}).get("enforcementAction") == "deny"
                for c in candidate)
            n_err += 0 if enforced else 1
            if not enforced:
                print("  FAIL promote: live constraints not at deny",
                      file=sys.stderr)

            # fleet graduation plan: the same candidate across a
            # 4-cluster fleet, map-reduce blocks of 2
            fleet = [make_cluster(
                f"c{i}", templates, constraints,
                objs=make_mixed(random.Random(200 + i), max(n // 4, 8)))
                for i in range(4)]
            frep = graduate_fleet(fleet, templates, candidate,
                                  limit_per_constraint=20, block_size=2)
            ok = frep.graduated == frep.n_clusters
            n_err += 0 if ok else 1
            print(f"  {'ok  ' if ok else 'FAIL'} plan: "
                  f"{frep.headline()}")
        finally:
            tmp_rec = fr._recorder
            fr._recorder = saved_rec
            for k, v in saved_env.items():
                if v is None:
                    _os.environ.pop(k, None)
                else:
                    _os.environ[k] = v
            try:
                if tmp_rec is not None and tmp_rec._capture is not None:
                    tmp_rec._capture.close()
            except Exception:   # noqa: BLE001 — probe hygiene only
                pass

    scalar = bool(getattr(driver, "scalar_only", False))
    if scalar:
        n_warn += 1
        print("  warn scalar-only backend: promotion gates validated "
              "on the oracle path, device NOT")
    wall = _time.perf_counter() - t0
    print(f"rollout: {n_err} gate failure(s) "
          f"({'scalar-fallback' if scalar else 'device'}) "
          f"in {wall:.1f}s")
    return _severity_rc(n_err, n_warn)


def run_pages(paths: list[str], use_library: bool = False) -> int:
    """``--pages``: self-validate the continuous-enforcement paged
    sweep (enforce/, ROADMAP item 2) over template files and/or the
    built-in library.  Two identically-churned clients run side by
    side — ``GATEKEEPER_PAGES=on`` vs the legacy full path — and every
    sweep's verdicts must match bit-identically while the paged client
    maintains its VerdictLedger by per-page deltas.  Prints the page
    geometry (rows/page, page count, occupancy), per-sweep dirty work
    (pages evaluated vs total, evaluations saved, delta events), the
    ledger size, and per-kind eligibility with fallback reasons.  Exit
    contract (:func:`_severity_rc`): 2 on any parity break or
    unreadable input, 1 when parity held but some kind fell back to
    the full-kind path (cross-row / scalar-pin — delta maintenance
    disabled for it), 0 all kinds paged with parity."""
    import copy
    import os as _os
    import random
    import sys
    import time as _time

    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.library import make_mixed
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    from gatekeeper_tpu.whatif import normalize_results

    work = _load_work(paths, use_library)
    if work is None:
        return 2
    if not work:
        print("pages: no ConstraintTemplate docs "
              "(pass template yaml or --library)", file=sys.stderr)
        return 2

    n = int(_os.environ.get("GATEKEEPER_PAGES_PROBE_N", "300"))
    objs = make_mixed(random.Random(7), n)

    def _build():
        driver = JaxDriver()
        handler = K8sValidationTarget()
        client = Backend(driver).new_client([handler])
        for _label, tdoc, cdocs in work:
            client.add_template(tdoc)
            for c in cdocs:
                client.add_constraint(c)
        client.add_data_batch(copy.deepcopy(objs))
        return driver, handler, client

    prev = _os.environ.get("GATEKEEPER_PAGES")

    def _sweep(client, pages: bool):
        _os.environ["GATEKEEPER_PAGES"] = "on" if pages else "off"
        try:
            return normalize_results(
                client.audit(limit_per_constraint=20).results())
        finally:
            if prev is None:
                _os.environ.pop("GATEKEEPER_PAGES", None)
            else:
                _os.environ["GATEKEEPER_PAGES"] = prev

    # churn batches built once from the seed objects, applied
    # identically to both clients: metadata noise (invisible to most
    # footprints — the paged sweep should skip almost everything),
    # then an image edit that actually flips verdicts
    rng = random.Random(11)
    churn_n = max(n // 100, 1)
    batches = []
    b = []
    for o in rng.sample(objs, min(churn_n, len(objs))):
        o = copy.deepcopy(o)
        o.setdefault("metadata", {}).setdefault(
            "annotations", {})["probe/pages"] = "noise-1"
        b.append(o)
    batches.append(b)
    pods = [o for o in objs
            if isinstance((o.get("spec") or {}).get("containers"), list)
            and (o.get("spec") or {}).get("containers")]
    b = []
    for o in rng.sample(pods, min(churn_n, len(pods))):
        o = copy.deepcopy(o)
        o["spec"]["containers"][0]["image"] = "evil.io/pages-probe:1"
        b.append(o)
    if b:
        batches.append(b)

    t0 = _time.perf_counter()
    jd_p, h_p, cl_p = _build()
    _jd_o, _h_o, cl_o = _build()
    n_err = 0
    for i, batch in enumerate([None] + batches):
        if batch:
            cl_p.add_data_batch(copy.deepcopy(batch))
            cl_o.add_data_batch(copy.deepcopy(batch))
        got = _sweep(cl_p, True)
        want = _sweep(cl_o, False)
        pg = (jd_p.last_sweep_phases or {}).get("pages", {})
        ok = got == want
        n_err += 0 if ok else 1
        label = ("cold build" if i == 0
                 else f"churn {i} ({len(batch)} upsert(s))")
        print(f"  {'ok  ' if ok else 'FAIL'} sweep {i}: {label} — "
              f"{len(got)} verdict(s), "
              f"{pg.get('pages_evaluated', 0)}/{pg.get('n_pages', 0)} "
              f"page(s) evaluated, "
              f"{pg.get('evaluations_saved', 0)} evaluation(s) saved, "
              f"{pg.get('events', 0)} delta event(s)")

    st = jd_p._state(h_p.name)
    table = st.table
    n_warn = 0
    from gatekeeper_tpu.enforce.devpages import devpages_mode
    dv_on = devpages_mode()
    dv_report = jd_p.devpages_report(h_p.name)
    for kind in sorted(st.templates):
        reason = jd_p._pages_ineligible(st, kind, st.templates[kind])
        dv_reason = dv_report.get(kind, "unknown")
        resid = ("device-resident" if dv_reason is None
                 else f"host ({dv_reason})")
        if reason is None:
            print(f"  ok   {kind}: paged (delta-maintained, {resid})")
        else:
            n_warn += 1
            print(f"  warn {kind}: full-kind fallback — {reason} "
                  f"[{resid}]")
    if dv_on:
        dv = (jd_p.last_sweep_phases or {}).get("devpages", {})
        n_dev = sum(1 for r in dv_report.values() if r is None)
        print(f"  devpages: {n_dev}/{len(dv_report)} kind(s) "
              f"device-eligible; last sweep "
              f"{dv.get('kinds_device', 0)} on device, "
              f"{dv.get('h2d_bytes', 0)} H2D byte(s), "
              f"{dv.get('scatter_rows', 0)} scattered row(s), "
              f"{dv.get('delta_events', 0)} in-jit delta event(s), "
              f"{dv.get('direct_clears', 0)} direct clear(s)")
    led = st.ledger
    occ = table.n_rows / max(1, table.n_pages * table.page_rows)
    wall = _time.perf_counter() - t0
    print(f"pages: page_rows={table.page_rows} pages={table.n_pages} "
          f"rows={table.n_rows} occupancy={occ:.0%}; "
          f"ledger {led.total_violations() if led else 0} violation(s) "
          f"seq={led.seq if led else 0}; "
          f"{len(st.templates) - n_warn}/{len(st.templates)} kind(s) "
          f"paged; {n_err} parity failure(s) in {wall:.1f}s")
    return _severity_rc(n_err, n_warn)


def run_health() -> int:
    """``probe --health``: the k8s liveness/readiness consumer.  One
    JSON line with the backend supervisor's serving posture (state,
    degradation reason, probe timestamps) and the warm-restart
    persistent-cache counters; exit 0 only while the device backend is
    healthy — a degraded/recovering/poisoned pod still serves correct
    verdicts (scalar fallback) but reports not-ready so the operator
    sees the posture, mirroring the reference's status.byPod[]."""
    import json
    import time as _time

    from gatekeeper_tpu.resilience.snapshot import restart_report
    from gatekeeper_tpu.resilience.supervisor import HEALTHY, get_supervisor

    sup = get_supervisor()
    st = sup.status()
    rep = restart_report()
    iso = lambda t: (_time.strftime("%Y-%m-%dT%H:%M:%SZ", _time.gmtime(t))
                     if t else None)
    out = {
        "state": st["state"],
        "backend": st["backend"],
        "reason": st["reason"],
        "since": iso(st["since"]),
        "last_probe_at": iso(st["last_probe_at"]),
        "last_ok_at": iso(st["last_ok_at"]),
        "reprobe_attempts": st["reprobe_attempts"],
        "restart_persistent_cache_hits":
            rep["restart_persistent_cache_hits"],
        "restart_persistent_cache_misses":
            rep["restart_persistent_cache_misses"],
    }
    # watch-driven enforcement posture (enforce/reactor.py): the state
    # machine (live/degraded/resyncing) per live reactor.  Informational
    # only — a degraded watch falls back to sweep cadence, still
    # serving correct verdicts, so it does not change the exit code.
    from gatekeeper_tpu.enforce.reactor import export_state
    reactors = export_state()
    if reactors:
        out["reactors"] = [
            {"name": r["name"], "state": r["state"],
             "state_age_s": r["state_age_s"],
             "last_sweep_age_s": r.get("last_sweep_age_s")}
            for r in reactors]
    print(json.dumps(out))
    if st["state"] != HEALTHY:
        print(f"HEALTH FAIL ({st['state']}: {st['reason']})")
        return 2
    print(f"HEALTH OK ({st['backend']})")
    return 0


def _run_subcommand(argv: list[str]) -> int | None:
    """One dispatcher for every analysis subcommand: flag matching,
    ``--library``/``--strict``/``--out`` extraction and positional
    (yaml path) splitting live here instead of one copy per flag; the
    shared 0/1/2 exit contract is :func:`_severity_rc` inside each
    runner.  Returns None when no analysis flag is present (the caller
    falls through to the engine probe)."""
    use_library = "--library" in argv
    strict = "--strict" in argv
    pos = [a for a in argv if a not in ("--library", "--strict")]
    out = None
    if "--out" in pos:
        i = pos.index("--out")
        out = pos[i + 1] if i + 1 < len(pos) else None
        del pos[i:i + 2]
    table = (
        ("--whatif", lambda rest: run_whatif()),
        ("--rollout", lambda rest: run_rollout(use_library=use_library)),
        ("--policyset", lambda rest: run_policyset()),
        ("--cost", lambda rest: run_cost()),
        ("--trace", lambda rest: run_trace(out)),
        ("--certify", lambda rest: run_certify(
            rest, use_library=use_library)),
        ("--footprint", lambda rest: run_footprint(
            rest, use_library=use_library)),
        ("--shardplan", lambda rest: run_shardplan(
            rest, use_library=use_library)),
        ("--compilesurface", lambda rest: run_compilesurface(
            rest, use_library=use_library)),
        ("--memsurface", lambda rest: run_memsurface(
            rest, use_library=use_library)),
        ("--pages", lambda rest: run_pages(
            rest, use_library=use_library)),
        ("--lint", lambda rest: run_lint(
            rest, use_library=use_library, strict=strict)),
    )
    for flag, fn in table:
        if flag in argv:
            return fn([a for a in pos if a != flag])
    return None


def main(argv=None) -> int:
    """``python -m gatekeeper_tpu.client.probe``: self-validate both
    engines (the readiness wiring the reference's Probe exists for).
    ``--builtins`` lists the builtin registry instead of probing;
    ``--lint <template.yaml>... [--library]`` runs the static-analysis
    pass, ``--certify`` the Stage-4 translation validator, and
    ``--compilesurface`` the Stage-7 compile-surface certifier, and
    ``--memsurface`` the Stage-8 memory-surface certifier instead;
    analysis subcommands share one exit contract: 0 clean, 1 warnings
    only, 2 any error-severity finding or unreadable input.

    The verdict line names the backend that actually served the [jax]
    scenarios: with a dead/unreachable device the driver falls back to
    the scalar oracle, which validates SEMANTICS but not the device —
    a reader gating a deploy must see that distinction, and
    GATEKEEPER_PROBE_REQUIRE_DEVICE=1 turns it into a failure."""
    import os
    import sys

    argv = sys.argv[1:] if argv is None else argv
    if "--builtins" in argv:
        print("\n".join(list_builtins()))
        return 0
    if "--health" in argv:
        return run_health()
    rc = _run_subcommand(argv)
    if rc is not None:
        return rc

    from gatekeeper_tpu.client.local_driver import LocalDriver
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    failures = 0
    jax_scalar_only = False
    construct_failed: set[str] = set()
    for label, cls in (("local", LocalDriver), ("jax", JaxDriver)):
        try:
            probe = Probe(cls())
        except Exception as e:      # noqa: BLE001 — a readiness probe
            failures += 1           # must render a verdict, not a trace
            construct_failed.add(label)
            print(f"  FAIL [{label}] <driver construction>: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            continue
        if label == "jax" and getattr(probe.client.driver,
                                      "scalar_only", False):
            jax_scalar_only = True
        for name, fn in probe.test_funcs().items():
            try:
                fn()
                print(f"  ok   [{label}] {name}")
            except Exception as e:  # noqa: BLE001 — incl. ProbeError
                failures += 1
                print(f"  FAIL [{label}] {name}: "
                      f"{str(e).splitlines()[0]}", file=sys.stderr)
    if jax_scalar_only:
        from gatekeeper_tpu.utils.device_probe import probe_devices
        print("WARNING: device backend unavailable "
              f"({probe_devices().reason}) — the [jax] scenarios ran on "
              "the scalar fallback; semantics validated, device NOT",
              file=sys.stderr)
        if os.environ.get("GATEKEEPER_PROBE_REQUIRE_DEVICE") == "1":
            print("PROBE FAIL (device required but unavailable)")
            return 2
    # A failed JaxDriver CONSTRUCTION means no jax scenario ran at all —
    # the verdict line a deploy gate greps must not claim "device" (or
    # even "scalar-fallback") for an engine that never existed.
    if "jax" in construct_failed:
        backend = "unavailable"
    else:
        backend = "scalar-fallback" if jax_scalar_only else "device"
    print(("PROBE FAIL" if failures else "PROBE PASS")
          + f" (jax engine served by: {backend})")
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
