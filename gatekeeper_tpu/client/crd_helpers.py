"""Constraint-kind CRD construction and validation.

Reference: vendor/.../constraint/pkg/client/crd_helpers.go — each template
generates a cluster-scoped CRD in group ``constraints.gatekeeper.sh``
whose spec schema combines the target's MatchSchema with the template's
parameters schema (:32-47); constraints are validated against it plus
name/kind/group/version checks (:100-125).
"""

from __future__ import annotations

import re
from typing import Any

from gatekeeper_tpu.api.templates import ConstraintTemplate
from gatekeeper_tpu.errors import ClientError

CONSTRAINT_GROUP = "constraints.gatekeeper.sh"
CONSTRAINT_VERSION = "v1alpha1"

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


def crd_to_v1(doc: dict) -> dict:
    """Convert a v1beta1 CustomResourceDefinition document to the
    apiextensions v1 shape (spec.versions[] + per-version schema) —
    v1beta1 was removed in Kubernetes 1.22, so real-cluster writes go
    v1-first with this conversion."""
    spec = doc.get("spec") or {}
    schema = ((spec.get("validation") or {}).get("openAPIV3Schema")
              or {"type": "object"})
    schema = {**schema, "x-kubernetes-preserve-unknown-fields": True}
    out_spec = {k: v for k, v in spec.items()
                if k not in ("version", "validation")}
    out_spec["versions"] = [{"name": spec.get("version", "v1"),
                             "served": True, "storage": True,
                             "schema": {"openAPIV3Schema": schema}}]
    return {**doc, "apiVersion": "apiextensions.k8s.io/v1",
            "spec": out_spec}


def build_crd(template: ConstraintTemplate, match_schema: dict) -> dict:
    if not template.kind:
        raise ClientError("template has no CRD kind")
    if template.name != template.kind.lower():
        raise ClientError(
            f"template name {template.name!r} must equal lowercase of CRD kind "
            f"{template.kind!r} (crd_helpers.go name validation)")
    plural = template.kind.lower()
    spec_schema: dict = {
        "type": "object",
        "properties": {
            "match": match_schema,
        },
    }
    if isinstance(template.parameters_schema, dict):
        spec_schema["properties"]["parameters"] = template.parameters_schema
    return {
        "apiVersion": "apiextensions.k8s.io/v1beta1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{CONSTRAINT_GROUP}"},
        "spec": {
            "group": CONSTRAINT_GROUP,
            "version": CONSTRAINT_VERSION,
            "names": {"kind": template.kind, "plural": plural,
                      "listKind": template.kind + "List",
                      "singular": template.kind.lower()},
            "scope": "Cluster",
            "validation": {"openAPIV3Schema": {
                "type": "object",
                "properties": {"spec": spec_schema},
            }},
        },
    }


def validate_cr(constraint: dict, crd: dict) -> None:
    """crd_helpers.go:100-125 validateCR."""
    api_version = constraint.get("apiVersion", "")
    expected_av = f"{CONSTRAINT_GROUP}/{CONSTRAINT_VERSION}"
    if api_version != expected_av:
        raise ClientError(f"constraint apiVersion must be {expected_av}, "
                          f"got {api_version!r}")
    kind = constraint.get("kind", "")
    crd_kind = crd["spec"]["names"]["kind"]
    if kind != crd_kind:
        raise ClientError(f"constraint kind {kind!r} does not match CRD kind {crd_kind!r}")
    name = (constraint.get("metadata") or {}).get("name", "")
    if not name:
        raise ClientError("constraint has no metadata.name")
    if len(name) > 63 or not _DNS1123.match(name):
        raise ClientError(f"invalid constraint name {name!r}: must be a DNS-1123 label")
    schema = (crd["spec"].get("validation") or {}).get("openAPIV3Schema")
    if schema:
        errs: list[str] = []
        _validate_schema(constraint, schema, "", errs)
        if errs:
            raise ClientError("constraint schema violations: " + "; ".join(errs))


def _validate_schema(value: Any, schema: Any, path: str, errs: list[str]) -> None:
    """Minimal OpenAPI v3 subset validator: type / properties / items /
    additionalProperties / enum.  Malformed schema nodes (e.g. the demos'
    `items: string`) are ignored the way apiextensions treats unknown shapes."""
    if not isinstance(schema, dict):
        return
    t = schema.get("type")
    if t and not _type_ok(value, t):
        errs.append(f"{path or '.'}: expected {t}, got {type(value).__name__}")
        return
    if "enum" in schema and isinstance(schema["enum"], list):
        if value not in schema["enum"]:
            errs.append(f"{path or '.'}: {value!r} not in enum {schema['enum']!r}")
    props = schema.get("properties")
    if isinstance(props, dict) and isinstance(value, dict):
        for k, sub in props.items():
            if k in value:
                _validate_schema(value[k], sub, f"{path}.{k}", errs)
    addl = schema.get("additionalProperties")
    if isinstance(addl, dict) and isinstance(value, dict):
        props = props if isinstance(props, dict) else {}
        for k, v in value.items():
            if k not in props:
                _validate_schema(v, addl, f"{path}.{k}", errs)
    items = schema.get("items")
    if isinstance(items, dict) and isinstance(value, list):
        for i, v in enumerate(value):
            _validate_schema(v, items, f"{path}[{i}]", errs)


def _type_ok(value: Any, t: str) -> bool:
    if t == "object":
        return isinstance(value, dict)
    if t == "array":
        return isinstance(value, list)
    if t == "string":
        return isinstance(value, str)
    if t == "integer":
        return isinstance(value, int) and not isinstance(value, bool)
    if t == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if t == "boolean":
        return isinstance(value, bool)
    if t == "null":
        return value is None
    return True
