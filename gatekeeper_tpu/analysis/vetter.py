"""Stage 1: the Rego front-end vetter.

Walks a parsed template module (rego/ast_nodes.py) and emits
:class:`Diagnostic` records for defects that today only surface when
the webhook or audit sweep actually evaluates the template:

==============================  ========  =============================
code                            severity  finding
==============================  ========  =============================
rego_unknown_builtin            error     call name not in the builtin
                                          registry and not a module
                                          function
rego_unsupported_builtin        warning   registered stub (``_unsupported``)
                                          that is undefined at eval
rego_impure_builtin             warning   IMPURE_BUILTINS member — the
                                          result can vary between
                                          evaluations (blocks sharing)
rego_unsafe_var                 error     variable consumed with no
                                          admissible binding order
rego_recursion                  error     rule participates in a
                                          reference cycle
rego_dead_rule                  warning   rule unreachable from any
                                          ``violation`` rule
rego_unbounded_comprehension    error     comprehension head variable
                                          has no generator in its body
rego_bad_provider_ref           error     ``external_data`` names a
                                          provider absent from the
                                          declared set (only checked
                                          when a provider set is given)
rego_dynamic_provider_ref       warning   ``external_data`` provider
                                          argument is not a string
                                          literal — unverifiable
                                          statically
==============================  ========  =============================

Safety analysis reuses the needs/binds computation the body reorderer
already trusts (rego/reorder.py ``_Analysis``) and replays its greedy
schedule: a clause where no admissible ordering exists is exactly the
case ``reorder_body`` gives up on and the interpreter later surfaces as
an eval-time unsafe-variable error — the vetter moves that to install
time.
"""

from __future__ import annotations

from gatekeeper_tpu.analysis.diagnostics import ERROR, WARNING, Diagnostic
from gatekeeper_tpu.errors import Location
from gatekeeper_tpu.rego.ast_nodes import (
    Assign, Call, Compare, Comprehension, Literal, Module, ObjectTerm, Ref,
    Rule, Scalar, SomeDecl, Term, Var, walk_terms,
)
from gatekeeper_tpu.rego.reorder import (
    _Analysis, _collect_pattern_vars, _GLOBALS, _is_wild,
)

# Call names the interpreter resolves specially, outside the registry
# (rego/interp.py _eval_call): trace's no-op fast path, the internal
# comparison shim the parser emits, and the walk generator.
_SPECIAL_CALLS = frozenset({("trace",), ("internal", "compare"), ("walk",)})


def vet_module(module: Module, providers: "set[str] | None" = None,
               file: str = "") -> list[Diagnostic]:
    """Vet one parsed module.  ``providers=None`` skips the
    provider-existence check (caller has no provider registry in scope —
    e.g. Client-side ingestion, where providers may legitimately be
    registered later); pass a concrete set (possibly empty) to enforce
    ``rego_bad_provider_ref``."""
    diags: list[Diagnostic] = []
    rule_names = {r.name for r in module.rules}
    for rule in module.rules:
        clause: Rule | None = rule
        while clause is not None:
            _vet_calls(rule, clause, rule_names, providers, file, diags)
            _vet_safety(rule, clause, rule_names, file, diags)
            clause = clause.els
    _vet_recursion(module, rule_names, file, diags)
    _vet_dead_rules(module, rule_names, file, diags)
    return diags


def _loc(loc: Location, file: str) -> Location:
    if file and not loc.file:
        return Location(row=loc.row, col=loc.col, file=file)
    return loc


# --- builtin / provider checks ----------------------------------------

def _vet_calls(rule: Rule, clause: Rule, rule_names: set,
               providers: "set[str] | None", file: str,
               diags: list[Diagnostic]) -> None:
    from gatekeeper_tpu.analysis.purity import is_impure_builtin
    from gatekeeper_tpu.rego import builtins as bi

    def visit(term: Term, loc: Location) -> None:
        if not isinstance(term, Call):
            return
        name = term.name
        dotted = ".".join(name)
        if name == ("external_data",):
            _vet_external_data(term, providers, loc, diags)
        if name in bi.REGISTRY:
            fn = bi.REGISTRY[name]
            reason = getattr(fn, "unsupported_reason", None)
            if reason is not None:
                diags.append(Diagnostic(
                    "rego_unsupported_builtin", WARNING,
                    f"builtin {dotted} is an unsupported stub "
                    f"({reason}); it is undefined at evaluation", loc))
            if is_impure_builtin(name):
                diags.append(Diagnostic(
                    "rego_impure_builtin", WARNING,
                    f"builtin {dotted} is impure: results may vary "
                    "between evaluations and block result sharing", loc))
        elif name in _SPECIAL_CALLS:
            pass
        elif len(name) == 1 and name[0] in rule_names:
            pass  # user-defined function
        else:
            diags.append(Diagnostic(
                "rego_unknown_builtin", ERROR,
                f"unknown builtin or function {dotted}", loc))

    _walk_clause_terms(clause, visit, _loc(rule.loc, file), file)


def _vet_external_data(call: Call, providers: "set[str] | None",
                       loc: Location, diags: list[Diagnostic]) -> None:
    provider_term: Term | None = None
    if len(call.args) == 1 and isinstance(call.args[0], ObjectTerm):
        for k, v in call.args[0].pairs:
            if isinstance(k, Scalar) and k.value == "provider":
                provider_term = v
    if isinstance(provider_term, Scalar) and isinstance(provider_term.value,
                                                       str):
        if providers is not None and provider_term.value not in providers:
            known = ", ".join(sorted(providers)) or "<none>"
            diags.append(Diagnostic(
                "rego_bad_provider_ref", ERROR,
                f"external_data references provider "
                f"{provider_term.value!r} which is not registered "
                f"(registered: {known})", loc))
    else:
        diags.append(Diagnostic(
            "rego_dynamic_provider_ref", WARNING,
            "external_data provider argument is not a string literal; "
            "the reference cannot be verified statically", loc))


def _walk_clause_terms(clause: Rule, visit, head_loc: Location,
                       file: str) -> None:
    """Visit every term of ONE clause (not the else chain), attributing
    head terms to the rule location and body terms to their literal."""
    for t in (clause.args or ()):
        walk_terms(t, lambda x: visit(x, head_loc))
    if clause.key is not None:
        walk_terms(clause.key, lambda x: visit(x, head_loc))
    if clause.value is not None:
        walk_terms(clause.value, lambda x: visit(x, head_loc))
    for lit in clause.body:
        lloc = _loc(lit.loc, file)
        walk_terms(lit, lambda x: visit(x, lloc))


# --- variable safety --------------------------------------------------

def _literal_info(an: _Analysis, lit: Literal) -> tuple[set, set]:
    """needs/binds of one literal, with the interpreter's ``walk``
    special case applied on top of the reorderer's analysis: the 2-arg
    statement form ``walk(x, [path, value])`` unifies its second
    argument as a pattern (rego/interp.py ``_eval_call``), so those
    variables are binds, not needs.  Negated literals keep the base
    analysis — everything under ``not`` must already be bound."""
    needs, binds = an.literal(lit)
    if lit.negated:
        return needs, binds
    walk_binds: set[str] = set()

    def visit(t: Term) -> None:
        if isinstance(t, Call) and t.name == ("walk",) and len(t.args) == 2:
            _collect_pattern_vars(t.args[1], walk_binds)

    walk_terms(lit, visit)
    if walk_binds:
        needs = needs - walk_binds
        binds = binds | walk_binds
    return needs, binds


def _vet_safety(rule: Rule, clause: Rule, rule_names: set, file: str,
                diags: list[Diagnostic]) -> None:
    an = _Analysis(rule_names)
    params: set[str] = set()
    for p in (clause.args or ()):
        _collect_pattern_vars(p, params)
    infos = [_literal_info(an, l) for l in clause.body]
    all_binds: set[str] = set(params)
    for _, b in infos:
        all_binds |= b

    # comprehension-head safety first: a head variable with no
    # generator gets its dedicated code, and is then excluded from the
    # generic unsafe-var reporting below (the outer analysis propagates
    # it as a clause-level need too — one finding, not two).  The outer
    # scope is over-approximated as everything the clause OR any of its
    # comprehension bodies can bind, so only genuinely generator-less
    # variables fire.
    comp_scope = all_binds | _all_comprehension_binds(clause, an)
    comp_flagged: set[str] = set()
    for lit in clause.body:
        lloc = _loc(lit.loc, file)
        walk_terms(lit, lambda t, _l=lloc: _vet_comprehension(
            t, rule, rule_names, comp_scope, _l, diags, comp_flagged))
    for t in [clause.key, clause.value]:
        if t is not None:
            walk_terms(t, lambda x: _vet_comprehension(
                x, rule, rule_names, comp_scope, _loc(rule.loc, file),
                diags, comp_flagged))

    # vars needed somewhere but bound nowhere in the clause
    reported: set[str] = set(comp_flagged)
    for lit, (needs, _) in zip(clause.body, infos):
        for v in sorted(needs - all_binds):
            if v not in reported:
                reported.add(v)
                diags.append(Diagnostic(
                    "rego_unsafe_var", ERROR,
                    f"variable {v!r} is unsafe in rule {rule.name!r}: "
                    "nothing in the clause binds it", _loc(lit.loc, file)))

    # replay the reorderer's greedy schedule; a stall = no admissible
    # ordering (mutually-dependent literals)
    bound = set(params) | reported
    remaining = list(range(len(clause.body)))
    while remaining:
        picked = None
        for idx in remaining:
            if infos[idx][0] <= bound:
                picked = idx
                break
        if picked is None:
            stuck = sorted(set().union(
                *(infos[i][0] for i in remaining)) - bound)
            diags.append(Diagnostic(
                "rego_unsafe_var", ERROR,
                f"no admissible binding order in rule {rule.name!r}: "
                f"variable(s) {', '.join(repr(v) for v in stuck)} cannot "
                "be bound before use", _loc(clause.body[remaining[0]].loc,
                                            file)))
            bound |= set().union(*(infos[i][1] for i in remaining))
            break
        remaining.remove(picked)
        bound |= infos[picked][1]

    # head terms may only consume bound variables
    head_needs: set[str] = set()
    for t in [clause.key, clause.value] + list(clause.args or ()):
        if t is not None:
            an.term(t, False, head_needs, set())
    for v in sorted(head_needs - bound - params):
        diags.append(Diagnostic(
            "rego_unsafe_var", ERROR,
            f"variable {v!r} in the head of rule {rule.name!r} is never "
            "bound by the body", _loc(rule.loc, file)))


def _all_comprehension_binds(clause: Rule, an: _Analysis) -> set:
    """Union of every comprehension body's binds anywhere in the clause
    — the over-approximated scope nested comprehensions see."""
    out: set[str] = set()

    def visit(t: Term) -> None:
        if isinstance(t, Comprehension):
            for lit in t.body:
                _n, b = _literal_info(an, lit)
                out.update(b)

    for lit in clause.body:
        walk_terms(lit, visit)
    for t in [clause.key, clause.value]:
        if t is not None:
            walk_terms(t, visit)
    return out


def _vet_comprehension(term: Term, rule: Rule, rule_names: set,
                       outer: set, loc: Location,
                       diags: list[Diagnostic], flagged: set) -> None:
    if not isinstance(term, Comprehension):
        return
    an = _Analysis(rule_names)
    inner_binds: set[str] = set()
    for lit in term.body:
        _n, b = _literal_info(an, lit)
        inner_binds |= b
    head_vars: set[str] = set()

    def head_visit(t: Term) -> None:
        if isinstance(t, Var) and t.name not in _GLOBALS \
                and t.name not in rule_names and not _is_wild(t.name):
            head_vars.add(t.name)

    for h in term.head:
        walk_terms(h, head_visit)
    scope = inner_binds | outer
    for v in sorted(head_vars - scope):
        if v in flagged:
            continue
        flagged.add(v)
        diags.append(Diagnostic(
            "rego_unbounded_comprehension", ERROR,
            f"comprehension in rule {rule.name!r} iterates variable "
            f"{v!r} with no generator: the head ranges over an "
            "unbounded domain", loc))


# --- rule graph: recursion + dead rules -------------------------------

def _rule_edges(module: Module, rule_names: set) -> dict[str, set[str]]:
    edges: dict[str, set[str]] = {r.name: set() for r in module.rules}

    def refs_of(clause: Rule) -> set[str]:
        out: set[str] = set()

        def visit(t: Term) -> None:
            if isinstance(t, Var) and t.name in rule_names:
                out.add(t.name)
            elif isinstance(t, Call) and len(t.name) == 1 \
                    and t.name[0] in rule_names:
                out.add(t.name[0])
            elif isinstance(t, Ref) and isinstance(t.base, Var) \
                    and t.base.name in rule_names:
                out.add(t.base.name)

        walk_terms(clause, visit)
        return out

    for rule in module.rules:
        clause: Rule | None = rule
        while clause is not None:
            edges[rule.name] |= refs_of(clause)
            clause = clause.els
    return edges


def _vet_recursion(module: Module, rule_names: set, file: str,
                   diags: list[Diagnostic]) -> None:
    edges = _rule_edges(module, rule_names)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in edges}
    on_cycle: set[str] = set()

    def dfs(n: str, stack: list[str]) -> None:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(edges[n]):
            if color[m] == GRAY:
                on_cycle.update(stack[stack.index(m):])
            elif color[m] == WHITE:
                dfs(m, stack)
        stack.pop()
        color[n] = BLACK

    for n in sorted(edges):
        if color[n] == WHITE:
            dfs(n, [])
    for rule in module.rules:
        if rule.name in on_cycle:
            diags.append(Diagnostic(
                "rego_recursion", ERROR,
                f"rule {rule.name!r} is recursive (rule references form "
                "a cycle)", _loc(rule.loc, file)))
            on_cycle.discard(rule.name)  # one finding per name


def _vet_dead_rules(module: Module, rule_names: set, file: str,
                    diags: list[Diagnostic]) -> None:
    if "violation" not in rule_names:
        return  # conformance checking rejects these modules already
    edges = _rule_edges(module, rule_names)
    live: set[str] = set()
    frontier = ["violation"]
    while frontier:
        n = frontier.pop()
        if n in live:
            continue
        live.add(n)
        frontier.extend(edges.get(n, ()))
    seen: set[str] = set()
    for rule in module.rules:
        if rule.name not in live and rule.name not in seen:
            seen.add(rule.name)
            diags.append(Diagnostic(
                "rego_dead_rule", WARNING,
                f"rule {rule.name!r} is not reachable from any "
                "'violation' rule", _loc(rule.loc, file)))
