"""Stage 3, part 2: whole-policy-set analysis.

Stages 1 and 2 vet one template in isolation; this module reasons over
the *set* of installed policies:

- **Cross-template predicate dedup** — conjunct subtrees of lowered
  programs are canonically hashed (:func:`canonical_conjuncts`): input
  leaves normalize to their prep-request identity (resource path +
  extraction mode, not the per-template serial name) and per-constraint
  scalars backed by a value that is uniform across the kind's
  constraints (string literals lower this way — ir/lower._as_id) fold
  to the resolved constant.  Subtrees appearing under more than one
  template therefore collide — e.g. the ``input.review.object.kind ==
  "Pod"`` gate most library templates open with.  A :class:`DedupPlan`
  rewrites every member program to read the predicate from one injected
  boolean input, which the audit sweep computes ONCE on the host
  (:func:`eval_shared_host`, a numpy twin of engine/veval's evaluator)
  instead of once per member kind on device.  Soundness: an injected
  ``r_bool``/``e_bool`` input *fires* exactly its stored value
  (veval._fires on a bool is ``defined & value`` with defined = ones),
  and the stored value is the original subtree's fires lattice
  evaluated over the same bound arrays.

- **Match shadowing / unreachability** — the match-criteria semantics
  of engine/match.py lifted to a static subsumption order:
  constraint B is *shadowed* when an installed A of the same kind with
  JSON-equal parameters matches a superset of B's objects at
  equal-or-stricter enforcement, and *unreachable* when its match
  criteria statically match nothing (non-list/empty ``kinds``, empty
  ``namespaces``).

- **Cost-budget admission** — every template is priced by the static
  cost model (:mod:`.costmodel`) at reference scale and gated on
  ``GATEKEEPER_COST_BUDGET=warn|strict|off``.

All findings are :class:`.diagnostics.Diagnostic` records in the
``cost_*`` / ``set_*`` families so the reconcilers forward them into
``status.byPod[]`` unchanged.  Upstream Gatekeeper has no equivalent
pass — see BASELINE.md.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import numpy as np

from gatekeeper_tpu.analysis import costmodel
from gatekeeper_tpu.analysis.diagnostics import (
    ERROR, WARNING, Diagnostic,
)
from gatekeeper_tpu.errors import Location
from gatekeeper_tpu.ir.program import Node, Program, RuleSpec

# ---------------------------------------------------------------------------
# canonical conjunct hashing


class _Unshareable(Exception):
    """Subtree cannot be proven identical across templates."""


# ops whose semantics are closed over canonicalized inputs; everything
# else (ptable_*, in_cset, cset_*_memb, elem_keys_missing, keyed_val)
# is inherently per-constraint-parameter and never shared
_SHAREABLE_OPS = frozenset({
    "const", "input", "table", "dfa_match", "cmp", "and", "or", "not",
    "arith", "any_e", "all_e", "count_e",
})

_SIMPLE_SCALARS = (str, int, float, bool, bytes, type(None))


def _stable_repr(v) -> str:
    """repr() with container contents in sorted order.  Canonical
    digests feed sha1 — a frozenset-valued cval (encoded membership
    set) must hash identically under every PYTHONHASHSEED, but
    ``repr(frozenset(...))`` follows hash-table order."""
    if isinstance(v, (set, frozenset)):
        return "{" + ", ".join(sorted(_stable_repr(x) for x in v)) + "}"
    if isinstance(v, dict):
        items = sorted((_stable_repr(k), _stable_repr(x))
                       for k, x in v.items())
        return "{" + ", ".join(f"{k}: {x}" for k, x in items) + "}"
    if isinstance(v, tuple):
        return "(" + ", ".join(_stable_repr(x) for x in v) + ",)"
    if isinstance(v, list):
        return "[" + ", ".join(_stable_repr(x) for x in v) + "]"
    return repr(v)


def _fn_fingerprint(fn) -> tuple | None:
    """Structural identity of a host-table fn: code object + closure
    cells + defaults, admitted only when every captured value is a
    simple scalar or a named callable.  None = not provable equal."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return None

    def _cell(v):
        if isinstance(v, _SIMPLE_SCALARS):
            return ("v", type(v).__name__, repr(v))
        if callable(v) and getattr(v, "__qualname__", None):
            return ("f", getattr(v, "__module__", ""), v.__qualname__)
        raise _Unshareable()

    try:
        cells = tuple(_cell(c.cell_contents)
                      for c in (fn.__closure__ or ()))
        defaults = tuple(_cell(v) for v in (fn.__defaults__ or ()))
    except (_Unshareable, ValueError):
        return None
    return (code.co_filename, code.co_firstlineno, code.co_code.hex(),
            cells, defaults)


def _spec_maps(spec) -> dict:
    return {
        "r": {rc.name: rc for rc in spec.r_cols},
        "e": {ec.name: ec for ec in spec.e_cols},
        "cv": {cv.name: cv for cv in spec.cvals},
        "t": {t.name: t for t in spec.tables},
        "d": {d.name: d for d in getattr(spec, "dfas", ())},
        "ij": {ij.name: ij for ij in spec.inv_joins},
    }


class _Canon:
    """Canonicalizer for one kind's program: node index -> (form,
    r-dependent, unreduced element axes, compute-node count)."""

    def __init__(self, program: Program, spec, constraints: list[dict]):
        self.p = program
        self.maps = _spec_maps(spec)
        self.constraints = constraints
        self.cache: dict[int, tuple] = {}

    def node(self, i: int) -> tuple:
        hit = self.cache.get(i)
        if hit is None:
            hit = self._canon(self.p.nodes[i])
            self.cache[i] = hit
        return hit

    def _uniform_cval(self, name: str) -> tuple:
        """Fold a per-constraint scalar whose resolved value is the
        same for every constraint of the kind (string/encoded literals
        always are — the same literal resolves identically under every
        constraint) into a canonical constant."""
        cv = self.maps["cv"].get(name)
        if cv is None or not self.constraints:
            raise _Unshareable()
        try:
            vals = [cv.fn(c) for c in self.constraints]
        except Exception:
            raise _Unshareable() from None
        v0 = vals[0]
        if v0 is None or any(type(v) is not type(v0) or v != v0
                             for v in vals[1:]):
            raise _Unshareable()
        return ("cconst", cv.kind, _stable_repr(v0))

    def _canon(self, n: Node) -> tuple:
        op = n.op
        if op not in _SHAREABLE_OPS:
            raise _Unshareable()
        if op == "const":
            value, dtype = n.meta
            return (("const", _stable_repr(value), dtype), False,
                    frozenset(), 0)
        if op == "input":
            name, kind = n.meta
            axis_char = kind[0]
            if axis_char == "r":
                rc = self.maps["r"].get(name)
                if rc is not None:
                    return (("rcol", rc.path, rc.mode), True,
                            frozenset(), 0)
                ij = self.maps["ij"].get(name)
                if ij is not None:
                    return (("ij", ij.kind, ij.inv_path, ij.src_path,
                             ij.exclude_same_name, ij.namespaced_only),
                            True, frozenset(), 0)
                raise _Unshareable()
            if axis_char == "e":
                ec = self.maps["e"].get(name)
                if ec is None:
                    raise _Unshareable()
                return (("ecol", ec.axis, ec.rel, ec.mode), True,
                        frozenset({ec.axis}), 0)
            return (self._uniform_cval(name), False, frozenset(), 0)
        arg = [self.node(a) for a in n.args]
        r = any(a[1] for a in arg)
        eaxes = frozenset().union(*(a[2] for a in arg)) if arg \
            else frozenset()
        compute = 1 + sum(a[3] for a in arg)
        forms = tuple(a[0] for a in arg)
        if op == "table":
            t = self.maps["t"].get(n.meta[0])
            if t is None or t.ext_providers:
                # provider-backed tables can observe breaker/cache state
                # that shifts between member binding builds mid-sweep
                raise _Unshareable()
            fp = _fn_fingerprint(t.fn)
            if fp is None:
                raise _Unshareable()
            form = ("table", forms[0], t.out, t.src_val, t.regex, fp)
        elif op == "dfa_match":
            d = self.maps["d"].get(n.meta[0])
            if d is None:
                raise _Unshareable()
            # fully determined by the source column + pattern: two
            # templates matching the same regex over the same column
            # share one devtab gather
            form = ("dfa", forms[0], d.pattern)
        elif op in ("cmp", "arith"):
            form = (op, n.meta[0], forms[0], forms[1])
        elif op in ("and", "or"):
            form = (op, forms[0], forms[1])
        elif op == "not":
            form = ("not", forms[0])
        else:                               # any_e / all_e / count_e
            form = (op, n.meta[0], forms[0])
            eaxes = frozenset()             # the element axis is reduced
        if len(eaxes) > 1:
            raise _Unshareable()            # no single injectable shape
        return (form, r, eaxes, compute)


def canonical_conjuncts(lowered, constraints: list[dict]) -> dict:
    """node_idx -> (digest, ekind, axis) for every rule-conjunct root
    that qualifies for cross-template sharing: canonicalizable, varies
    over the resource (or element) axis, and contains at least one
    compute node (a bare input is cheaper to read directly than to
    share)."""
    program = lowered.program
    canon = _Canon(program, lowered.spec, constraints)
    out: dict[int, tuple] = {}
    roots = {ci for rule in program.rules for ci in rule.conjuncts}
    for idx in sorted(roots):
        try:
            form, r, eaxes, compute = canon.node(idx)
        except _Unshareable:
            continue
        if compute < 1 or not (r or eaxes):
            continue
        digest = hashlib.sha1(repr(form).encode()).hexdigest()[:12]
        if eaxes:
            out[idx] = (digest, "e", next(iter(eaxes)))
        else:
            out[idx] = (digest, "r", None)
    return out


def template_digests(lowered, constraints: list[dict] | None = None) -> set:
    """Digest set of one template's shareable conjuncts.  Without
    constraints (template install time, none exist yet) a parameterless
    dummy stands in: literal-backed scalars still resolve, genuinely
    parameter-dependent ones drop out as unshareable."""
    if lowered is None:
        return set()
    cons = constraints or [{"spec": {"parameters": {}}}]
    return {d for d, _, _ in canonical_conjuncts(lowered, cons).values()}


# ---------------------------------------------------------------------------
# the dedup plan


@dataclasses.dataclass
class SharedMember:
    kind: str
    node_idx: int           # representative root in the ORIGINAL program
    sites: int              # distinct conjunct roots with this digest


@dataclasses.dataclass
class SharedGroup:
    digest: str
    ekind: str              # "r" | "e"
    axis: str | None        # element axis key for ekind == "e"
    binding: str            # injected input binding name
    members: dict[str, SharedMember]

    @property
    def total_sites(self) -> int:
        return sum(m.sites for m in self.members.values())


@dataclasses.dataclass
class DedupPlan:
    groups: dict[str, SharedGroup]          # digest -> group
    rewritten: dict[str, Program]           # kind -> rewritten program
    originals: dict[str, Program]           # kind -> original program
    kind_digests: dict[str, list[str]]      # kind -> digests it reads


def shared_binding(digest: str, ekind: str) -> str:
    return (f"__shared_e__:{digest}" if ekind == "e"
            else f"__shared__:{digest}")


# ---------------------------------------------------------------------------
# shadow policy-set version tags (whatif/shadow.py)
#
# A shadow install stages a candidate set BESIDE the live one in the
# same client, under constraint kinds mangled with a version tag.  The
# canonical conjunct digests above are computed from program structure
# and folded params only — never from the kind name — so identical
# conjuncts in the live and candidate versions of a template land in
# the same SharedGroup automatically: cross-version sharing is the
# cross-template mechanism, verbatim.

SHADOW_SEP = "__WHATIF__"
"""Kind-name separator for shadow policy-set versions.  Double
underscore + caps keeps it out of the CamelCase namespace real
template kinds use."""


def shadow_kind(kind: str, tag: str) -> str:
    """Mangle a template/constraint kind into its shadow-version name."""
    if SHADOW_SEP in kind:
        raise ValueError(f"already a shadow kind: {kind}")
    if not tag or not tag.replace("-", "").replace("_", "").isalnum():
        raise ValueError(f"bad shadow tag: {tag!r}")
    return f"{kind}{SHADOW_SEP}{tag}"


def split_shadow_kind(kind: str) -> tuple[str, str | None]:
    """(logical kind, version tag or None for the live set)."""
    base, sep, tag = kind.partition(SHADOW_SEP)
    return (base, tag) if sep else (base, None)


def is_shadow_kind(kind: str) -> bool:
    return SHADOW_SEP in kind


def cross_version_groups(plan: DedupPlan) -> dict:
    """Accounting for the shadow report: of the plan's shared groups,
    how many span policy-set versions (live + at least one shadow tag,
    or two tags), vs. sharing within one version only."""
    cross = 0
    within = 0
    sites_cross = 0
    for g in plan.groups.values():
        versions = {split_shadow_kind(k)[1] for k in g.members}
        if len(versions) > 1:
            cross += 1
            sites_cross += g.total_sites
        else:
            within += 1
    return {"groups_cross_version": cross, "groups_within_version": within,
            "sites_cross_version": sites_cross}


def build_dedup_plan(kinds: dict) -> DedupPlan:
    """kinds: kind -> (LoweredProgram, constraints).  Groups every
    shareable conjunct digest with >= 2 sites across the set and
    rewrites each member program to read the injected shared input.
    Rebuilt from scratch every full sweep — it is a pure function of
    the installed set and costs milliseconds, so nothing is cached to
    go stale."""
    per_kind: dict[str, dict[int, tuple]] = {}
    count: dict[str, int] = {}
    meta: dict[str, tuple] = {}
    for kind in sorted(kinds):
        lowered, constraints = kinds[kind]
        if lowered is None or not constraints:
            continue
        conj = canonical_conjuncts(lowered, constraints)
        per_kind[kind] = conj
        for digest, ekind, axis in conj.values():
            count[digest] = count.get(digest, 0) + 1
            meta[digest] = (ekind, axis)
    shared = {d for d, n in count.items() if n >= 2}
    groups: dict[str, SharedGroup] = {}
    rewritten: dict[str, Program] = {}
    originals: dict[str, Program] = {}
    kind_digests: dict[str, list[str]] = {}
    for d in sorted(shared):
        ekind, axis = meta[d]
        groups[d] = SharedGroup(d, ekind, axis, shared_binding(d, ekind),
                                {})
    for kind, conj in per_kind.items():
        repl: dict[int, str] = {}           # node idx -> digest
        for idx, (digest, ekind, _axis) in conj.items():
            if digest in shared and meta[digest][0] == ekind:
                repl[idx] = digest
        if not repl:
            continue
        program = kinds[kind][0].program
        nodes = list(program.nodes)
        injected: dict[str, int] = {}
        new_idx: dict[int, int] = {}
        used: list[str] = []
        for idx in sorted(repl):
            digest = repl[idx]
            g = groups[digest]
            if digest not in injected:
                injected[digest] = len(nodes)
                nodes.append(Node("input", (),
                                  (g.binding, f"{g.ekind}_bool")))
                used.append(digest)
            new_idx[idx] = injected[digest]
            if kind not in g.members:
                g.members[kind] = SharedMember(kind, idx, 0)
            g.members[kind].sites += 1
        rules = tuple(RuleSpec(
            conjuncts=tuple(new_idx.get(ci, ci) for ci in r.conjuncts),
            elem_axis=r.elem_axis) for r in program.rules)
        rewritten[kind] = Program(tuple(nodes), rules)
        originals[kind] = program
        kind_digests[kind] = used
    # a group can end up with a single applied site (ekind-mismatched
    # twins dropped above): its member program already reads the
    # injected input, so the group stays — it just saves nothing, and
    # reporting/savings math discounts it via total_sites
    return DedupPlan(groups=groups, rewritten=rewritten,
                     originals=originals, kind_digests=kind_digests)


# ---------------------------------------------------------------------------
# host twin evaluator (numpy mirror of engine/veval._Evaluator over the
# shareable op subset — kept in exact step with veval semantics)


def _np_fires(dv):
    d, v = dv
    if v.dtype == np.bool_:
        return d & v
    return d


class _HostEval:
    def __init__(self, program: Program, arrays: dict):
        self.p = program
        self.arrays = arrays
        self.cache: dict[int, tuple] = {}

    def _arr(self, name: str) -> np.ndarray:
        a = np.asarray(self.arrays[name])
        if a.dtype in (np.int8, np.int16):      # veval._widen_args
            a = a.astype(np.int32)
        return a

    def _to3(self, a: np.ndarray, axes: str) -> np.ndarray:
        if axes == "c":
            # shared subtrees are constraint-uniform by construction
            # (canonicalization folded every c input): one constraint
            # row stands in for all of them
            return a[:1].reshape(1, 1, 1)
        if axes == "r":
            return a.reshape(1, a.shape[0], 1)
        return a.reshape(1, a.shape[0], a.shape[1])

    def node(self, i: int):
        hit = self.cache.get(i)
        if hit is None:
            hit = self._eval(self.p.nodes[i])
            self.cache[i] = hit
        return hit

    def _eval(self, n: Node):
        op = n.op
        ones = lambda v: np.ones(v.shape, dtype=bool)  # noqa: E731
        if op == "const":
            value, dtype = n.meta
            v = np.asarray(value, dtype=dtype).reshape(1, 1, 1)
            return np.ones((1, 1, 1), dtype=bool), v
        if op == "input":
            name, kind = n.meta
            axes = kind[0]
            if kind.endswith("_num"):
                return (self._to3(self._arr(name + ".p"), axes),
                        self._to3(self._arr(name + ".v"), axes))
            if kind.endswith("_id"):
                v = self._to3(self._arr(name), axes)
                return v >= 0, v
            v = self._to3(self._arr(name), axes)
            return ones(v), v
        if op == "table":
            (tname,) = n.meta
            d_i, idx = self.node(n.args[0])
            ci = np.clip(idx, 0, None)
            return (d_i & self._arr(tname + ".ok")[ci],
                    self._arr(tname + ".v")[ci])
        if op == "dfa_match":
            from gatekeeper_tpu.ir.prep import _STR_PREFIX
            (dname,) = n.meta
            d_i, idx = self.node(n.args[0])
            # the numpy twin of veval._dfa_device_table: scan the packed
            # interner bytes through the transition table, trailing TERM
            # step, host-fallback xv for device-ineligible ids
            trans = self._arr(dname + ".trans")
            payload = self._arr("__strbytes__")[:, len(_STR_PREFIX):]
            payload = payload.astype(np.int64)
            state = np.zeros((payload.shape[0],), dtype=np.int64)
            for j in range(payload.shape[1]):
                state = trans[state, payload[:, j]]
            hit = self._arr(dname + ".accept")[trans[state, 0]]
            devtab = np.where(self._arr("__strdfaok__"), hit,
                              self._arr(dname + ".xv"))
            v = devtab[np.clip(idx, 0, None)]
            return d_i & v, v
        if op == "cmp":
            (cop,) = n.meta
            da, va = self.node(n.args[0])
            db, vb = self.node(n.args[1])
            d = da & db
            v = {"==": np.equal, "!=": np.not_equal, "<": np.less,
                 "<=": np.less_equal, ">": np.greater,
                 ">=": np.greater_equal}[cop](va, vb)
            return d, v
        if op in ("and", "or"):
            a = _np_fires(self.node(n.args[0]))
            b = _np_fires(self.node(n.args[1]))
            v = (a & b) if op == "and" else (a | b)
            return ones(v), v
        if op == "not":
            a = _np_fires(self.node(n.args[0]))
            return ones(a), ~a
        if op in ("any_e", "all_e", "count_e"):
            (axis,) = n.meta
            pres = self._arr(f"__elem__:{axis}")[None]
            a = _np_fires(self.node(n.args[0]))
            if op == "any_e":
                v = np.any(a & pres, axis=2, keepdims=True)
                return ones(v), v
            if op == "all_e":
                v = np.all(a | ~pres, axis=2, keepdims=True)
                return ones(v), v
            v = np.sum((a & pres).astype(np.float32), axis=2,
                       keepdims=True)
            return np.ones(v.shape, dtype=bool), v
        if op == "arith":
            (aop,) = n.meta
            da, va = self.node(n.args[0])
            db, vb = self.node(n.args[1])
            d = da & db
            if aop == "+":
                v = va + vb
            elif aop == "-":
                v = va - vb
            elif aop == "*":
                v = va * vb
            else:
                d = d & (vb != 0)
                v = va / np.where(vb == 0, np.float32(1.0), vb)
            return d, v
        raise ValueError(f"unshareable IR op reached the host twin: {op!r}")


class _RowSlicedArrays:
    """Lazy dict-view gathering each bound array's row axis down to a
    row subset (by ir/prep.binding_axes).  The page-partitioned dedup
    host-eval reads through this, so a churn-sweep re-eval of a shared
    conjunct touches O(dirty) rows instead of r_pad.  Arrays without a
    row axis (tables, cvals) pass through untouched — shared subtrees
    are constraint-uniform, so their non-row inputs are row-count
    independent."""

    def __init__(self, arrays: dict, rows: np.ndarray):
        self._arrays = arrays
        self._rows = rows

    def __getitem__(self, name: str):
        a = self._arrays[name]
        try:
            from gatekeeper_tpu.ir.prep import binding_axes
            axes = binding_axes(name)
        except Exception:   # noqa: BLE001 — injected/unknown binding
            return a
        if "r" not in axes:
            return a
        return np.take(np.asarray(a), self._rows,
                       axis=axes.index("r"))

    def get(self, name: str, default=None):
        if name not in self._arrays:
            return default
        return self[name]

    def __contains__(self, name: str) -> bool:
        return name in self._arrays


def eval_shared_host(program: Program, node_idx: int, arrays: dict,
                     ekind: str, rows: np.ndarray | None = None
                     ) -> np.ndarray:
    """Fires lattice of one shared conjunct, computed once on the host
    over the bound arrays of any member kind.  Returns bool [r_pad]
    (ekind 'r') or [r_pad, e_pad] (ekind 'e') — the injected value the
    rewritten programs read.  With ``rows``, evaluates only that row
    subset (the caller splices the result into a cached column)."""
    if rows is not None:
        arrays = _RowSlicedArrays(arrays, rows)
    ev = _HostEval(program, arrays)
    f = _np_fires(ev.node(node_idx))
    f = np.broadcast_to(f, (1,) + f.shape[1:]) if f.ndim == 3 else f
    if ekind == "e":
        return np.ascontiguousarray(f[0]).astype(bool)
    return np.ascontiguousarray(f[0, :, 0]).astype(bool)


# ---------------------------------------------------------------------------
# match shadowing / unreachability (static mirror of engine/match._View.mask)

ENFORCE_RANK = {"dryrun": 0, "warn": 1, "deny": 2}


def _rank(doc: dict) -> int:
    action = (doc.get("spec") or {}).get("enforcementAction", "deny")
    return ENFORCE_RANK.get(action, 2)


def match_unreachable(match: dict) -> str | None:
    """Reason string when the criteria statically match nothing, by the
    exact engine semantics: non-list / empty ``kinds`` zeroes the kind
    mask, empty ``namespaces`` zeroes the namespace mask."""
    if "kinds" in match:
        kinds = match["kinds"]
        if not isinstance(kinds, list):
            return "spec.match.kinds is not a list — matches no object"
        live = False
        for ks in kinds:
            if not isinstance(ks, dict):
                continue
            groups = ks.get("apiGroups") or []
            knames = ks.get("kinds") or []
            g_ok = "*" in groups or any(isinstance(g, str) for g in groups)
            k_ok = "*" in knames or any(isinstance(k, str) for k in knames)
            if g_ok and k_ok:
                live = True
        if not live:
            return ("no spec.match.kinds entry names both an apiGroup "
                    "and a kind — matches no object")
    ns = match.get("namespaces")
    if "namespaces" in match and isinstance(ns, list) and not ns:
        return "spec.match.namespaces is empty — matches no object"
    return None


def _kinds_entry_covers(a: dict, b: dict) -> bool:
    ag = a.get("apiGroups") or []
    bg = b.get("apiGroups") or []
    ak = a.get("kinds") or []
    bk = b.get("kinds") or []
    g = "*" in ag or ("*" not in bg and set(bg) <= set(ag))
    k = "*" in ak or ("*" not in bk and set(bk) <= set(ak))
    return g and k


def match_subsumes(a: dict, b: dict) -> bool:
    """True when A's criteria match a superset of B's under the engine
    semantics — only the four clauses the engine evaluates (kinds,
    namespaces, namespaceSelector, labelSelector) exist; selectors are
    covered only by exact equality or absence in A.  A statically
    unreachable B is the set_unreachable finding's job, not this
    one's."""
    if match_unreachable(b) is not None:
        return False
    if "kinds" in a:
        a_kinds = a["kinds"]
        if not isinstance(a_kinds, list):
            return False                    # A matches nothing
        if "kinds" not in b:
            return False                    # B kind-wildcard, A restricted
        for be in b["kinds"]:
            if not isinstance(be, dict):
                continue
            if not any(isinstance(ae, dict) and _kinds_entry_covers(ae, be)
                       for ae in a_kinds):
                return False
    a_ns = a.get("namespaces")
    if "namespaces" in a and a_ns is not None:
        b_ns = b.get("namespaces")
        if "namespaces" not in b or not isinstance(b_ns, list) \
                or not isinstance(a_ns, list) \
                or not set(s for s in b_ns if isinstance(s, str)) \
                <= set(s for s in a_ns if isinstance(s, str)):
            return False
    if a.get("namespaceSelector") is not None:
        if json.dumps(a.get("namespaceSelector"), sort_keys=True) != \
                json.dumps(b.get("namespaceSelector"), sort_keys=True):
            return False
    if a.get("labelSelector"):
        if json.dumps(a.get("labelSelector"), sort_keys=True) != \
                json.dumps(b.get("labelSelector"), sort_keys=True):
            return False
    return True


def _params_equal(a: dict, b: dict) -> bool:
    pa = (a.get("spec") or {}).get("parameters")
    pb = (b.get("spec") or {}).get("parameters")
    return json.dumps(pa, sort_keys=True) == json.dumps(pb, sort_keys=True)


def constraint_set_warnings(kind: str, name: str, doc: dict,
                            installed: list) -> list[Diagnostic]:
    """set_* findings for one reconciled constraint against the other
    installed constraints of its kind (``installed``: (name, doc)
    pairs, the reconciled constraint excluded)."""
    out: list[Diagnostic] = []
    loc = Location(file=f"{kind}/{name}")
    match = (doc.get("spec") or {}).get("match") or {}
    reason = match_unreachable(match)
    if reason is not None:
        out.append(Diagnostic("set_unreachable", WARNING, reason, loc))
    for oname, odoc in installed:
        if oname == name or not _params_equal(doc, odoc):
            continue
        omatch = (odoc.get("spec") or {}).get("match") or {}
        if match_subsumes(omatch, match) and _rank(odoc) >= _rank(doc):
            out.append(Diagnostic(
                "set_shadowed", WARNING,
                f"subsumed by constraint {oname!r}: identical parameters, "
                f"superset match criteria, equal-or-stricter enforcement "
                f"— this constraint can never add a violation", loc))
        elif match_subsumes(match, omatch) and _rank(doc) >= _rank(odoc):
            out.append(Diagnostic(
                "set_shadows", WARNING,
                f"subsumes constraint {oname!r}: identical parameters, "
                f"superset match criteria, equal-or-stricter enforcement "
                f"— {oname!r} can never add a violation", loc))
    return out


# ---------------------------------------------------------------------------
# cost-budget admission + duplicate-predicate vetting (reconcile-time)


def vet_template_cost(lowered, kind: str) -> list[Diagnostic]:
    """cost_* findings for one template at reference scale.  strict
    mode escalates a blown budget to an error (the reconciler rejects
    the template); warn records it; off skips."""
    mode = costmodel.budget_mode()
    if mode == "off" or lowered is None:
        return []
    cv = costmodel.estimate(lowered, costmodel.REF_ROWS, 1)
    units = cv.units()
    budget = costmodel.budget_units()
    if units <= budget:
        return []
    sev = ERROR if mode == "strict" else WARNING
    return [Diagnostic(
        "cost_budget_exceeded", sev,
        f"predicted static cost {units:.3g} units at {costmodel.REF_ROWS} "
        f"rows exceeds GATEKEEPER_COST_BUDGET_UNITS={budget:.3g} "
        f"(mode={mode}; gathers={cv.gathers} compares={cv.compares} "
        f"matmul_flops={cv.matmul_flops})",
        Location(file=kind))]


def dfa_subset_warnings(kind: str, lowered) -> list[Diagnostic]:
    """regex_off_dfa findings: constant regex/glob patterns of the
    template that stayed on the host lookup-table path, and why
    (unsupported construct, DFA state blowup, or GATEKEEPER_DFA=off).
    Informational — results are identical either way; only the
    high-cardinality rebuild cost differs."""
    out: list[Diagnostic] = []
    for pattern, reason in getattr(lowered, "regex_offdfa", ()) or ():
        out.append(Diagnostic(
            "regex_off_dfa", WARNING,
            f"pattern {pattern!r} is outside the in-program DFA subset "
            f"({reason}); its matches run as a host lookup table, rebuilt "
            f"per unique value on churn",
            Location(file=kind)))
    return out


def duplicate_predicate_warnings(kind: str, lowered,
                                 others: dict) -> list[Diagnostic]:
    """set_duplicate_predicate findings: conjuncts of the new template
    whose canonical digest already appears in an installed template
    (``others``: kind -> LoweredProgram).  Informational — the audit
    sweep dedups them automatically."""
    mine = template_digests(lowered)
    if not mine:
        return []
    out: list[Diagnostic] = []
    for okind in sorted(others):
        if okind == kind:
            continue
        shared = mine & template_digests(others[okind])
        if shared:
            out.append(Diagnostic(
                "set_duplicate_predicate", WARNING,
                f"{len(shared)} predicate subprogram(s) identical to "
                f"template {okind!r} ({', '.join(sorted(shared))}); the "
                f"audit sweep evaluates each once per sweep (dedup)",
                Location(file=kind)))
    return out


# ---------------------------------------------------------------------------
# whole-set report (probe --policyset)


def analyze_policy_set(entries: list, n_rows: int = costmodel.REF_ROWS) -> dict:
    """entries: (kind, LoweredProgram | None, constraints) triples.
    Returns the full policy-set report: shared-subprogram groups, per-
    kind static cost, and shadowing/unreachability findings."""
    kinds = {k: (low, cons) for k, low, cons in entries if low is not None}
    plan = build_dedup_plan(kinds)
    groups = []
    for d in sorted(plan.groups):
        g = plan.groups[d]
        if g.total_sites < 2:
            continue
        groups.append({
            "digest": d, "ekind": g.ekind, "axis": g.axis,
            "kinds": sorted(g.members),
            "sites": g.total_sites,
        })
    costs = {}
    for kind, low, cons in entries:
        if low is None:
            continue
        cv = costmodel.estimate(low, n_rows, max(len(cons), 1))
        costs[kind] = cv.as_dict()
    findings: list[Diagnostic] = []
    dfa_lowering: dict[str, dict] = {}
    for kind, low, cons in entries:
        installed = [((c.get("metadata") or {}).get("name", ""), c)
                     for c in cons]
        for cname, cdoc in installed:
            others = [(n, d) for n, d in installed if n != cname]
            findings.extend(
                constraint_set_warnings(kind, cname, cdoc, others))
        if low is not None:
            n_dfa = len(getattr(low.spec, "dfas", ()))
            off = list(getattr(low, "regex_offdfa", ()) or ())
            if n_dfa or off:
                dfa_lowering[kind] = {
                    "in_program": n_dfa,
                    "off_dfa": [{"pattern": p, "reason": r}
                                for p, r in off],
                }
            findings.extend(dfa_subset_warnings(kind, low))
    return {
        "shared_subprograms": groups,
        "template_costs": costs,
        "dfa_lowering": dfa_lowering,
        "findings": findings,
    }
