"""Small-model universe derivation for translation validation.

Stage 4, part 1 (see :mod:`.transval` for the checker): given one
template's lowered program, derive the *finite abstract domains* its
input columns actually range over, and enumerate a deterministic,
bounded universe of concrete worlds ("models") that exercises every
domain value at least once.

The key observation (the same one behind bounded model checking) is
that a lowered program is a finite circuit over a fixed set of typed
input slots — the PrepSpec requests (ir/prep.py).  Each slot only ever
flows into compares/gathers/membership tests against a *finite* set of
literals: constants in the Rego source, values folded out of the
constraint parameters, and the structural alternatives every extraction
mode distinguishes (absent vs present, truthy vs literal-false, empty
vs non-empty list).  Checking equivalence on one representative per
abstract class per slot — plus the float32 lattice boundary, where the
device's known ordering deviation lives — covers the program's entire
behavioral surface up to the mined literal set.

Everything here is deterministic: no clocks, no RNG, no iteration over
unsorted sets (the selflint nondeterminism rule applies to this module
in spirit — certificates must be bit-reproducible across processes and
PYTHONHASHSEED values).
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any

# float32 has 24 mantissa bits: 2**24 is the last contiguous integer;
# 2**24 + 1 rounds to 2**24 on device (the lowering contract's known
# ordering deviation — ir/lower.py), which the validator must exercise
# so the f32-excusal path is itself covered.
F32_EDGE = 2 ** 24


class _Absent:
    """Domain sentinel: the slot's path is left out of the object."""

    def __repr__(self) -> str:  # pragma: no cover - debug only
        return "<absent>"


ABSENT = _Absent()


# ---------------------------------------------------------------------------
# literal mining


@dataclasses.dataclass
class LiteralPool:
    strs: tuple[str, ...] = ()
    nums: tuple[float, ...] = ()


def _walk_json(v: Any, strs: set, nums: set) -> None:
    if isinstance(v, str):
        strs.add(v)
    elif isinstance(v, bool):
        pass
    elif isinstance(v, (int, float)):
        nums.add(v)
    elif isinstance(v, dict):
        for k, x in v.items():
            if isinstance(k, str):
                strs.add(k)
            _walk_json(x, strs, nums)
    elif isinstance(v, (list, tuple, set, frozenset)):
        for x in v:
            _walk_json(x, strs, nums)


def mine_literals(module, constraints: list[dict]) -> LiteralPool:
    """Every scalar literal the program can compare against: Rego
    source scalars (module AST) + scalars reachable in the constraint
    docs' parameters.  Sorted + capped so domains stay small and
    deterministic."""
    strs: set = set()
    nums: set = set()
    if module is not None:
        from gatekeeper_tpu.rego.ast_nodes import Scalar, walk_terms

        def spot(t):
            if isinstance(t, Scalar):
                _walk_json(t.value, strs, nums)

        for rule in module.rules:
            walk_terms(rule, spot)
    for c in constraints:
        _walk_json(((c.get("spec") or {}).get("parameters")) or {},
                   strs, nums)
    # identity-ish strings are never useful compare fodder
    strs.discard("")
    return LiteralPool(
        strs=tuple(sorted(strs))[:8],
        nums=tuple(sorted(n for n in nums if abs(n) < 2 ** 53))[:5],
    )


# ---------------------------------------------------------------------------
# slots & domains


@dataclasses.dataclass
class Slot:
    """One independently-varied degree of freedom of the model world.

    kind: 'scalar' (resource path), 'meta' (review identity field),
    'elem' (per-element rel path on an axis), 'memb' (dict whose keys
    a membership matrix tests), 'keyedval' (dict read through a
    constraint-chosen key), 'elemkeys' (per-element truthy-key set).
    """

    kind: str
    path: tuple[str, ...]
    domain: tuple
    default: int                    # index into domain
    axis: str | None = None


@dataclasses.dataclass
class ModelPlan:
    slots: list[Slot]
    # axis key -> base path, outer axes first (build order)
    axes: list[tuple[str, tuple[str, ...]]]
    inv_joins: list
    pool: LiteralPool
    truncated: bool = False

    def domain_sizes(self) -> dict:
        return {"slots": len(self.slots),
                "axes": len(self.axes),
                "values": sum(len(s.domain) for s in self.slots)}


# DFA-boundary strings for the in-program regex lowering
# (ops/regex_dfa): the empty string (start-state accept), the widest
# device-eligible row (raw 124 bytes -> encoded 127 < max_str_len, the
# trailing-terminator edge of the device scan), one byte past it
# (encoded 128: ineligible, host-xv route-back), and a non-ASCII
# payload (also routed back).  Deliberately NO trailing-newline
# strings: `$` ~ `\Z` on the device is a documented deviation and a
# counterexample here would pin every regex template to scalar.
_DFA_EDGE_STRS = ("", "x" * 124, "y" * 125, "café-ü")


def _str_domain(pool: LiteralPool) -> tuple:
    return (ABSENT, *pool.strs, "zzz-novel", 7, *_DFA_EDGE_STRS)


def _num_domain(pool: LiteralPool) -> tuple:
    vals: set = {0, 1}
    for v in pool.nums[:3]:
        vals.update({v - 1, v, v + 1})
    vals.update({F32_EDGE - 1, F32_EDGE + 1})
    return (ABSENT, *sorted(vals))


def _val_domain(pool: LiteralPool) -> tuple:
    return (ABSENT, *pool.strs[:3], *pool.nums[:2], False,
            {"httpGet": {}}, *_DFA_EDGE_STRS)


_MODE_DOMAIN = {
    "present": lambda pool: (ABSENT, "x"),
    "truthy": lambda pool: (ABSENT, False, "x"),
    "len": lambda pool: (ABSENT, [], [{"a": 1}], [1, 2, 3]),
    "str": _str_domain,
    "num": _num_domain,
    "val": _val_domain,
}

# default-value index per mode: prefer a literal (maximizes the number
# of conjuncts that fire under the default world, so each-choice flips
# explore deep program states rather than bouncing off the first
# undefined leaf)
_MODE_DEFAULT = {"present": 1, "truthy": 2, "len": 3,
                 "str": 1, "num": 1, "val": 1}


def _mode_slot(kind: str, path: tuple, mode: str, pool: LiteralPool,
               axis: str | None = None) -> Slot:
    domain = _MODE_DOMAIN[mode](pool)
    default = min(_MODE_DEFAULT[mode], len(domain) - 1)
    return Slot(kind=kind, path=path, domain=domain, default=default,
                axis=axis)


def _merge_domains(a: Slot, b: Slot) -> Slot:
    seen: list = []
    for v in (*a.domain, *b.domain):
        if not any(type(v) is type(x) and v == x for x in seen):
            seen.append(v)
    return dataclasses.replace(a, domain=tuple(seen))


def _eval_quiet(fn, *args):
    try:
        return fn(*args)
    except Exception:   # noqa: BLE001 — undefined under this constraint
        return None


def derive_plan(lowered, constraints: list[dict],
                module=None) -> ModelPlan:
    """ModelPlan for one template: one slot per distinct input path the
    PrepSpec extracts, with a finite abstract domain each."""
    spec = lowered.spec
    pool = mine_literals(module, constraints)
    axes = sorted(spec.axes, key=lambda ab: (len(ab[1]), ab[0]))
    axis_bases = {base for _k, base in axes}

    slots: dict[tuple, Slot] = {}

    def add(slot: Slot) -> None:
        key = (slot.kind, slot.path, slot.axis)
        prev = slots.get(key)
        slots[key] = _merge_domains(prev, slot) if prev else slot

    for rc in spec.r_cols:
        if rc.path and rc.path[0] == "$meta":
            tail = rc.path[1:]
            if tail in (("name",), ("operation",)):
                continue   # names are unique world keys; op is CREATE
            add(Slot(kind="meta", path=tail,
                     domain=(None,), default=0))
            continue
        if rc.path in axis_bases:
            continue       # the axis-length choice owns this path
        add(_mode_slot("scalar", rc.path, rc.mode, pool))
    for ec in spec.e_cols:
        add(_mode_slot("elem", ec.rel, ec.mode, pool, axis=ec.axis))

    # constraint-derived key sets
    cset_keys: dict[str, tuple[str, ...]] = {}
    for cs in spec.csets:
        keys: set = set()
        for c in constraints:
            got = _eval_quiet(cs.fn, c)
            if isinstance(got, (list, tuple, set, frozenset)):
                keys.update(k for k in got if isinstance(k, str))
        cset_keys[cs.name] = tuple(sorted(keys))
    for mb in spec.membs:
        keys = cset_keys.get(mb.cset, ())
        variants: list = [ABSENT, {}]
        if keys:
            variants.append({keys[0]: "v"})
            variants.append({k: "v" for k in keys})
        variants.append({**{k: "v" for k in keys}, "zzz-extra": "v"})
        add(Slot(kind="memb", path=mb.keys_path, domain=tuple(variants),
                 default=len(variants) - 1))
    for kv in spec.keyed_vals:
        keys = tuple(sorted({k for c in constraints
                             if isinstance(k := _eval_quiet(kv.key_fn, c),
                                           str)}))
        variants = [ABSENT, {}]
        for k in keys[:2]:
            for v in (*pool.strs[:2], False, 7):
                variants.append({k: v})
        add(Slot(kind="keyedval", path=kv.path, domain=tuple(variants),
                 default=min(2, len(variants) - 1)))
    for ek in spec.elem_keys:
        keys = cset_keys.get(ek.cset, ())
        variants = [{}]
        if keys:
            variants.append({keys[0]: {"t": 1}})
            variants.append({keys[0]: False})
            variants.append({k: {"t": 1} for k in keys})
        add(Slot(kind="elemkeys", path=(), domain=tuple(variants),
                 default=0, axis=ek.axis))

    ordered = [slots[k] for k in sorted(slots, key=repr)]
    return ModelPlan(slots=ordered, axes=axes,
                     inv_joins=list(spec.inv_joins), pool=pool)


# ---------------------------------------------------------------------------
# world construction


def _assign_path(obj: dict, path: tuple[str, ...], value) -> None:
    if value is ABSENT or not path:
        return
    cur = obj
    for seg in path[:-1]:
        nxt = cur.get(seg)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[seg] = nxt
        cur = nxt
    cur[path[-1]] = value


def _place_axis(obj: dict, base: tuple[str, ...], elems: list) -> None:
    """Install the element list at `base`; a ``"*"`` segment descends
    into the first element of the (already-built) outer axis list."""
    cur: Any = obj
    for i, seg in enumerate(base):
        last = i == len(base) - 1
        if seg == "*":
            if not (isinstance(cur, list) and cur):
                return           # outer axis empty: nested list nowhere
            cur = cur[0]
            continue
        if not isinstance(cur, dict):
            return
        if last:
            cur[seg] = elems
            return
        nxt = cur.get(seg)
        if not isinstance(nxt, (dict, list)):
            nxt = {}
            cur[seg] = nxt
        cur = nxt


@dataclasses.dataclass
class Model:
    """One concrete world: a list of resource objects (usually one;
    inventory-join models carry a partner row) plus the index of the
    row whose verdict this model is "about"."""

    resources: list
    focus: int = 0
    note: str = ""


def _meta_value(slot: Slot, pool: LiteralPool) -> tuple:
    """(domain, default index) for a review-identity slot."""
    if slot.path == ("kind", "kind"):
        return (("Pod", *pool.strs[:3]), 0)
    if slot.path == ("kind", "group"):
        return (("", *pool.strs[:2]), 0)
    if slot.path == ("kind", "version"):
        return (("v1", *pool.strs[:1]), 0)
    if slot.path == ("namespace",):
        return (("default", None, *pool.strs[:1]), 0)
    return ((None,), 0)


def finalize_plan(plan: ModelPlan) -> ModelPlan:
    """Resolve meta-slot domains (they need the pool) in place."""
    for i, s in enumerate(plan.slots):
        if s.kind == "meta":
            domain, default = _meta_value(s, plan.pool)
            plan.slots[i] = dataclasses.replace(s, domain=domain,
                                                default=default)
    return plan


def _build_resource(plan: ModelPlan, choice: dict[int, int],
                    axis_len: dict[str, int], name: str) -> dict:
    """One resource object from a slot-index assignment.  `choice`
    maps slot index -> domain index (missing = default)."""
    api, kind, ns = "v1", "Pod", "default"
    group = version = None
    for si, s in enumerate(plan.slots):
        if s.kind != "meta":
            continue
        v = s.domain[choice.get(si, s.default)]
        if s.path == ("kind", "kind") and isinstance(v, str) and v:
            kind = v
        elif s.path == ("kind", "group"):
            group = v
        elif s.path == ("kind", "version"):
            version = v
        elif s.path == ("namespace",):
            ns = v
    if group or (version and version != "v1"):
        api = f"{group}/{version or 'v1'}" if group else (version or "v1")
    obj: dict = {"apiVersion": api, "kind": kind,
                 "metadata": {"name": name}}
    if ns is not None:
        obj["metadata"]["namespace"] = ns

    # dict-shaped slots first so scalar assignments can merge into them
    for order in ("memb", "keyedval"):
        for si, s in enumerate(plan.slots):
            if s.kind == order:
                _assign_path(obj, s.path,
                             s.domain[choice.get(si, s.default)])
    for si, s in enumerate(plan.slots):
        if s.kind == "scalar":
            _assign_path(obj, s.path, s.domain[choice.get(si, s.default)])

    # axes, outer first; element e rotates each elem-slot's value so a
    # 2-element list shows two distinct abstract states per pass
    for axis_key, base in plan.axes:
        n_e = axis_len.get(axis_key, 1)
        elems = []
        for e in range(n_e):
            elem: dict = {}
            for si, s in enumerate(plan.slots):
                if s.axis != axis_key:
                    continue
                idx = (choice.get(si, s.default) + e) % len(s.domain)
                v = s.domain[idx]
                if s.kind == "elemkeys":
                    if isinstance(v, dict):
                        elem.update(v)
                elif s.kind == "elem":
                    _assign_path(elem, s.path, v)
            elems.append(elem)
        _place_axis(obj, base, elems)

    # identity invariants: the API server guarantees non-empty string
    # apiVersion/kind/name on every admitted object, and world keys
    # (kind/ns/name) must never collide across co-resident models — so
    # slots may not leave these fields invalid or non-unique
    if not (isinstance(obj.get("apiVersion"), str) and obj["apiVersion"]):
        obj["apiVersion"] = api
    if not (isinstance(obj.get("kind"), str) and obj["kind"]):
        obj["kind"] = kind
    md = obj.get("metadata")
    if not isinstance(md, dict):
        md = {}
        obj["metadata"] = md
    md["name"] = name
    mns = md.get("namespace")
    if mns is not None and not (isinstance(mns, str) and mns):
        del md["namespace"]
    return obj


def enumerate_models(plan: ModelPlan, budget: int = 96) -> list[Model]:
    """The bounded universe: the default world, every each-choice flip
    (one slot/axis varied at a time), inventory-join pairs, then
    deterministic mixed-radix combinations up to `budget` total."""
    finalize_plan(plan)
    counter = itertools.count()

    def name() -> str:
        return f"m{next(counter):03d}"

    models: list[Model] = []

    def emit(choice: dict, axis_len: dict, note: str) -> bool:
        if len(models) >= budget:
            plan.truncated = True
            return False
        models.append(Model(
            resources=[_build_resource(plan, choice, axis_len, name())],
            note=note))
        return True

    emit({}, {}, "default")
    for si, s in enumerate(plan.slots):
        for di in range(len(s.domain)):
            if di == s.default:
                continue
            if not emit({si: di}, {}, f"slot{si}={di}"):
                break
    for axis_key, _base in plan.axes:
        for n_e in (0, 2):
            emit({}, {axis_key: n_e}, f"axis:{axis_key}={n_e}")

    # inventory-join pairs: partner rows co-resident in the same world
    for ij in plan.inv_joins:
        for variant in ("dup", "nodup"):
            if len(models) >= budget:
                plan.truncated = True
                break
            focus = _build_resource(plan, {}, {}, name())
            focus["kind"] = ij.kind
            _assign_path(focus, ij.src_path, "joined-value")
            partner = _build_resource(plan, {}, {}, name())
            partner["kind"] = ij.kind
            _assign_path(partner, ij.inv_path,
                         "joined-value" if variant == "dup" else "other")
            models.append(Model(resources=[focus, partner], focus=0,
                                note=f"invjoin:{ij.name}:{variant}"))

    # deterministic mixed worlds fill the remaining budget
    k = 0
    while len(models) < budget and plan.slots and k < budget:
        choice = {si: (k * (si + 2) + (k >> 2) + 1) % len(s.domain)
                  for si, s in enumerate(plan.slots)}
        axis_len = {ax: (k + i) % 3
                    for i, (ax, _b) in enumerate(plan.axes)}
        emit(choice, axis_len, f"mix{k}")
        k += 1
    return models
