"""Stage 3, part 1: the static IR cost model.

An abstract interpreter over the lowered device IR (ir/program.py) that
prices a program BEFORE it ever jits: each node is classified by the
axes it varies over (constraints / resources / elements), the padded
cell count it materializes follows from the same shape buckets the
device uses (ir/prep.audit_pads), and op classes accumulate into a
:class:`CostVector` — gathers, compares, logical ops, arithmetic,
masked reductions, MXU matmul flops, gather volume, host-table and
provider-table bytes, H2D footprint, and bucket/padding waste.

The idea follows "A Learned Performance Model for Tensor Processing
Units" (PAPERS.md): static graph features predict TPU kernel cost well
enough to gate scheduling decisions.  Here the decision gated is
*admission of a policy template*: the reconciler prices every template
at install time against ``GATEKEEPER_COST_BUDGET_UNITS`` and either
warns or rejects (``GATEKEEPER_COST_BUDGET=warn|strict|off``) —
upstream Gatekeeper has no analogue; its audit cost is unbounded.

``units()`` collapses the vector through fixed op-class weights into a
scalar abstract cost; :func:`calibrate` fits the single seconds-per-
unit scale against measured ``device_s`` samples from the bench (least
squares through the origin), which is what lets ``probe --cost`` report
predicted-vs-measured.

Static unknowns are priced as documented upper bounds: element-axis
width and per-constraint set length default to the minimum shape
bucket (8), host-table cardinality to the padded row count (every row
distinct).  The model prices *work*, not constants: ``const``/``input``
nodes are free compute-wise and contribute only H2D bytes.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading

from gatekeeper_tpu.ir.prep import audit_pads

DEFAULT_E_PAD = 8
"""Assumed element-axis bucket when the real element width is unknown
at install time (the minimum bucket ir/prep.bucket hands out)."""

DEFAULT_SET_LEN = 8
"""Assumed per-constraint id-set / key-set padded length."""

REF_ROWS = 100_000
"""Reference inventory scale for install-time pricing: templates are
budgeted at the cost they would add to a 100k-resource sweep."""

# op-class weights for the scalar abstract cost.  Relative magnitudes
# reflect the device: a gather costs several vector lanes' worth of
# work, fused elementwise logic is nearly free, matmul flops ride the
# MXU at high throughput.
WEIGHTS = {
    "gathers": 4.0,
    "compares": 1.0,
    "logicals": 0.25,
    "arith": 1.0,
    "reductions": 1.0,
    "matmul_flops": 0.05,
}


@dataclasses.dataclass
class CostVector:
    """Per-program static cost, in padded-cell op counts by class."""

    gathers: int = 0            # table/ptable/in_cset/keyed_val cells
    compares: int = 0           # cmp cells
    logicals: int = 0           # and/or/not + rule-conjunct AND cells
    arith: int = 0              # arith cells
    reductions: int = 0         # cells consumed by any_e/all_e/count_e
    matmul_flops: int = 0       # cset_*_memb / elem_keys_missing MXU flops
    gather_volume_bytes: int = 0  # bytes moved by gathers (4B lanes)
    table_bytes: int = 0        # host lookup-table bytes shipped
    provider_tables: int = 0    # tables backed by external-data providers
    provider_table_bytes: int = 0
    h2d_bytes: int = 0          # estimated cold upload footprint
    live_cells: int = 0         # n_constraints * n_rows
    padded_cells: int = 0       # c_pad * r_pad

    def units(self) -> float:
        """Weighted scalar abstract cost (calibrate() maps it to
        seconds)."""
        return (WEIGHTS["gathers"] * self.gathers
                + WEIGHTS["compares"] * self.compares
                + WEIGHTS["logicals"] * self.logicals
                + WEIGHTS["arith"] * self.arith
                + WEIGHTS["reductions"] * self.reductions
                + WEIGHTS["matmul_flops"] * self.matmul_flops)

    def padding_waste(self) -> float:
        """Fraction of the padded [C, R] matrix that is bucket slack."""
        if not self.padded_cells:
            return 0.0
        return (self.padded_cells - self.live_cells) / self.padded_cells

    def __add__(self, other: "CostVector") -> "CostVector":
        return CostVector(**{
            f.name: getattr(self, f.name) + getattr(other, f.name)
            for f in dataclasses.fields(CostVector)})

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["units"] = round(self.units(), 1)
        d["padding_waste"] = round(self.padding_waste(), 4)
        return d


def node_axes(program) -> list[tuple[bool, bool, bool]]:
    """Per-node (c, r, e) axis dependence — which axes the node's value
    varies over.  Mirrors the broadcast semantics of engine/veval._to3:
    a node's lattice cell count is the product of the padded axes it
    depends on."""
    out: list[tuple[bool, bool, bool]] = []
    for n in program.nodes:
        arg = [out[a] for a in n.args]
        c = any(a[0] for a in arg)
        r = any(a[1] for a in arg)
        e = any(a[2] for a in arg)
        op = n.op
        if op == "const":
            ax = (False, False, False)
        elif op == "input":
            kind = n.meta[1]
            ax = {"c": (True, False, False),
                  "r": (False, True, False),
                  "e": (False, True, True)}[kind[0]]
        elif op in ("ptable_any", "ptable_all", "in_cset"):
            ax = (True, r, e)
        elif op == "keyed_val":
            ax = (True, True, False)
        elif op in ("cset_not_subset_memb", "cset_subset_memb"):
            ax = (True, True, False)
        elif op == "elem_keys_missing":
            ax = (True, True, True)
        elif op in ("any_e", "all_e", "count_e"):
            ax = (c, r, False)          # the element axis is reduced
        else:   # table / dfa_match / cmp / and / or / not / arith:
            ax = (c, r, e)          # broadcast of args
        out.append(ax)
    return out


def reachable_nodes(program) -> set[int]:
    """Node indices actually evaluated: the evaluator caches lazily, so
    only nodes reachable from rule conjuncts ever run (dead subtrees —
    e.g. those orphaned by a dedup rewrite — are free)."""
    seen: set[int] = set()
    stack = [ci for rule in program.rules for ci in rule.conjuncts]
    while stack:
        i = stack.pop()
        if i in seen or not (0 <= i < len(program.nodes)):
            continue
        seen.add(i)
        stack.extend(program.nodes[i].args)
    return seen


def _spec_h2d_bytes(spec, r_pad: int, c_pad: int, e_pad: int,
                    set_len: int) -> tuple[int, int, int, int]:
    """(h2d, table_bytes, provider_tables, provider_bytes) estimated
    from the PrepSpec request families.  Upper bounds: unary tables
    priced at one row per distinct value = r_pad."""
    h2d = r_pad * 1 + c_pad * 1            # __alive__ + __cvalid__
    h2d += c_pad * r_pad                   # __match__ gate (worst case)
    for ax, _base in spec.axes:
        h2d += r_pad * e_pad               # __elem__ presence
    for rc in spec.r_cols:
        h2d += r_pad * (5 if rc.mode in ("num", "len") else
                        4 if rc.mode in ("str", "val") else 1)
    for ec in spec.e_cols:
        h2d += r_pad * e_pad * (5 if ec.mode in ("num", "len") else
                                4 if ec.mode in ("str", "val") else 1)
    table_bytes = 0
    provider_tables = 0
    provider_bytes = 0
    for t in spec.tables:
        tb = r_pad * 5                     # .ok [T] + .v [T] at T <= r_pad
        table_bytes += tb
        if t.ext_providers:
            provider_tables += 1
            provider_bytes += tb
    dfas = getattr(spec, "dfas", ())
    if dfas:
        from gatekeeper_tpu.ops.regex_dfa import MAX_DFA_STATES, cached_dfa
        h2d += r_pad * (128 + 1)      # __strbytes__ [T, W] + __strdfaok__ [T]
        for d in dfas:
            dfa = cached_dfa(d.pattern)
            n_states = len(dfa.accept) if dfa is not None else MAX_DFA_STATES
            # .trans [S, 256] int32 + .accept [S] + .xv [T <= r_pad]:
            # priced as table bytes so the install-time budget sees a
            # state-count blowup the same way it sees a huge host table
            tb = n_states * 256 * 4 + n_states + r_pad
            table_bytes += tb
    h2d += table_bytes
    for _pt in spec.ptables:
        h2d += r_pad * 4 + c_pad * (set_len + 1)
    for _cs in spec.csets:
        h2d += r_pad * 4 + c_pad * set_len
    for _cv in spec.cvals:
        h2d += c_pad * 5
    for _mb in spec.membs:
        h2d += set_len * r_pad + c_pad * set_len
    for _ek in spec.elem_keys:
        h2d += set_len * r_pad * e_pad + c_pad * set_len
    for _kv in spec.keyed_vals:
        h2d += set_len * r_pad * 4 + c_pad * 4
    for _ij in spec.inv_joins:
        h2d += r_pad
    return h2d, table_bytes, provider_tables, provider_bytes


def estimate(lowered, n_rows: int, n_constraints: int,
             e_pad: int = DEFAULT_E_PAD,
             set_len: int = DEFAULT_SET_LEN) -> CostVector:
    """Abstractly interpret one LoweredProgram at the given workload
    scale.  Shapes follow the device's own padding (audit_pads), so the
    vector prices the padded work the kernels actually do."""
    program = lowered.program
    r_pad, c_pad = audit_pads(n_rows, n_constraints)
    axes = node_axes(program)
    live = reachable_nodes(program)

    def cells(ax: tuple[bool, bool, bool]) -> int:
        c, r, e = ax
        return ((c_pad if c else 1) * (r_pad if r else 1)
                * (e_pad if e else 1))

    cv = CostVector(live_cells=n_rows * n_constraints,
                    padded_cells=r_pad * c_pad)
    for i in sorted(live):
        n = program.nodes[i]
        op = n.op
        sz = cells(axes[i])
        if op in ("table", "dfa_match", "ptable_any", "ptable_all",
                  "in_cset", "keyed_val"):
            cv.gathers += sz
            cv.gather_volume_bytes += 4 * sz
        elif op == "cmp":
            cv.compares += sz
        elif op in ("and", "or", "not"):
            cv.logicals += sz
        elif op == "arith":
            cv.arith += sz
        elif op in ("any_e", "all_e", "count_e"):
            cv.reductions += cells(axes[n.args[0]]) if n.args else sz
        elif op in ("cset_not_subset_memb", "cset_subset_memb"):
            cv.matmul_flops += 2 * c_pad * set_len * r_pad
        elif op == "elem_keys_missing":
            cv.matmul_flops += 2 * c_pad * set_len * r_pad * e_pad
    # the in-program DFA scan: each distinct dfa_match table is computed
    # once per evaluation as max_str_len transition gathers over the
    # whole interner (t_pad priced at r_pad, the same one-distinct-value
    # -per-row upper bound unary tables use)
    for _d in getattr(lowered.spec, "dfas", ()):
        cv.gathers += r_pad * 128
        cv.gather_volume_bytes += 4 * r_pad * 128
    for rule in program.rules:
        row = c_pad * r_pad * (e_pad if rule.elem_axis is not None else 1)
        cv.logicals += len(rule.conjuncts) * row   # conjunct AND chain
        cv.reductions += row                       # rule any-reduce
    (cv.h2d_bytes, cv.table_bytes, cv.provider_tables,
     cv.provider_table_bytes) = _spec_h2d_bytes(
        lowered.spec, r_pad, c_pad, e_pad, set_len)
    return cv


def calibrate(samples) -> float:
    """Least-squares-through-origin seconds-per-unit scale from
    (units, measured_seconds) samples — the one free parameter the
    learned-cost-model idea needs per deployment/transport."""
    num = 0.0
    den = 0.0
    for units, seconds in samples:
        num += units * seconds
        den += units * units
    return num / den if den else 0.0


def predict_seconds(units: float, scale: float) -> float:
    return units * scale


def scatter_worthwhile(n_changed: int, n_total: int,
                       row_bytes: int = 4,
                       dispatch_rows: int = 64) -> bool:
    """Price a row-sized scatter against a full re-upload for one
    device-resident column (the devpages churn seam).

    A scatter moves ``n_changed * row_bytes`` over H2D plus a fixed
    per-dispatch cost (index staging + scatter kernel launch, priced in
    row-equivalents); a re-upload moves ``n_total * row_bytes`` in one
    transfer.  The scatter wins while the churned fraction stays under
    ~50% after the dispatch overhead — at higher churn the dense copy's
    bandwidth beats the gather/scatter addressing."""
    if n_changed <= 0:
        return True
    if n_total <= 0:
        return False
    return (n_changed + dispatch_rows) * 2 <= n_total


# ---------------------------------------------------------------------------
# running calibration store
#
# Every full sweep's per-template attribution (obs/attribution.py)
# feeds (units, measured_device_seconds) samples back here, closing
# the predict→measure→recalibrate loop the Learned-Performance-Model
# paper describes.  Bounded window so the scale tracks the current
# backend rather than averaging over a demotion.

_CAL_WINDOW = 256
_cal_lock = threading.Lock()
_cal_samples: collections.deque = collections.deque(maxlen=_CAL_WINDOW)


def record_sample(units: float, seconds: float) -> None:
    """Feed one measured (units, device_seconds) calibration sample."""
    if units > 0 and seconds > 0:
        with _cal_lock:
            _cal_samples.append((units, seconds))


def current_scale() -> float:
    """Seconds-per-unit fitted over the recent sample window (0.0
    while uncalibrated)."""
    with _cal_lock:
        samples = list(_cal_samples)
    return calibrate(samples)


def calibration_info() -> dict:
    with _cal_lock:
        n = len(_cal_samples)
    return {"samples": n, "scale": current_scale()}


def reset_calibration() -> None:
    """Drop the sample window (tests)."""
    with _cal_lock:
        _cal_samples.clear()


# Static prior for the uncalibrated window: weighted units the
# reference transport retires per second, anchored on the round-3
# live-device steady sweep (~674M row-evals/s; a unit is roughly one
# weighted op over one padded cell, so the same order of magnitude).
# Deliberately conservative (slow-side) — an over-predicting prior
# shrinks a deadline-pressed first batch, which is the safe direction;
# the first attribution sample replaces it entirely.
_PRIOR_UNITS_PER_SECOND = 2.5e8


def prior_scale() -> float:
    """Seconds-per-unit assumed before calibration
    (GATEKEEPER_COST_PRIOR_UPS overrides the units-per-second
    anchor; <=0 disables the prior)."""
    try:
        ups = float(os.environ.get("GATEKEEPER_COST_PRIOR_UPS",
                                   _PRIOR_UNITS_PER_SECOND))
    except ValueError:
        ups = _PRIOR_UNITS_PER_SECOND
    return 1.0 / ups if ups > 0 else 0.0


def effective_scale() -> float:
    """The scale predictions should actually use: the fitted
    seconds-per-unit once attribution samples exist, the static prior
    until then.  Before this, ``predict_review_batch_seconds``
    returned None for the whole uncalibrated window, so the
    micro-batcher's deadline shrinking silently no-opped on exactly
    the batches most likely to blow a deadline — the very first ones,
    compiling cold."""
    s = current_scale()
    return s if s > 0.0 else prior_scale()


# ---------------------------------------------------------------------------
# install-time budget gate


def budget_mode() -> str:
    """GATEKEEPER_COST_BUDGET: 'warn' (default) records a warning,
    'strict' rejects the template, 'off' disables the gate."""
    mode = os.environ.get("GATEKEEPER_COST_BUDGET", "warn")
    return mode if mode in ("warn", "strict", "off") else "warn"


def budget_units() -> float:
    """Per-template abstract-cost budget at REF_ROWS scale
    (GATEKEEPER_COST_BUDGET_UNITS).  The default admits every library
    template with ample headroom while still catching pathological
    blowups (quadratic element-axis products, runaway table fan-out)."""
    try:
        return float(os.environ.get("GATEKEEPER_COST_BUDGET_UNITS",
                                    "2e9"))
    except ValueError:
        return 2e9
