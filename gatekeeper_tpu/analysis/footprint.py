"""Stage-5 dependency analysis: column read-set footprints.

The analysis ladder so far proves a lowered program is well-formed
(verify), affordable (costmodel) and semantically faithful (transval).
This stage proves *what it depends on*: an abstract interpreter over
the lowered IR computes, per template,

  * the exact set of (source, column-path) reads — object columns,
    review ``$meta`` identity columns, and inventory columns of other
    kinds (inv-joins);
  * the external-data providers consulted by its tables;
  * a **row-locality certificate**: the verdict of row *i* depends only
    on row *i*'s columns.  Every IR op is elementwise along the
    resource axis except the inventory join, so a template is row-local
    iff no reachable node reads an inv-join column.  Row-local
    templates are eligible for future resource-axis shard_map
    (ROADMAP item 1); cross-row ones are surfaced as findings;
  * per-column sensitivity classes: ``equality`` (exact value
    matters), ``string-regex`` (value feeds a regex table), ``range``
    (only ordering matters) and ``existence`` (only presence matters).

Footprints are *validated, not trusted*: ``validate_footprint`` reuses
the smallmodel worlds to perturb columns OUTSIDE the claimed read-set
and asserts the device mask is bit-identical.  Any difference is a
bug in this analysis, reported as a FootprintViolation; under
``GATEKEEPER_FOOTPRINT=strict`` it fails template install.  Validated
footprints persist in the snapshot "fp" tier (alongside transval
certificates) so a warm restart re-runs zero analyses.

The engine consumes footprints for sweep-time selective invalidation:
a churn re-sweep intersects each kind's dirty column paths
(store.table.dirty_paths_since) with the installed templates'
read-sets and skips the unaffected ones entirely
(engine/jax_driver._selective_reuse); ``GATEKEEPER_FOOTPRINT=off``
disables both analysis and reuse and is the bit-identical oracle.
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import os

import numpy as np

from gatekeeper_tpu.utils.log import logger

log = logger("footprint")

FOOTPRINT_VERSION = "fp-1"

# fresh analyses this process (mirrors transval.validations_run): the
# restart smoke asserts a warm process re-analyzes nothing
analyses_run = 0

_memo: dict[str, "Footprint"] = {}

# kind -> human reason, for templates whose verdicts read other rows.
# Consumed by the reconciler (status.byPod[] finding) and the probe.
cross_row: dict[str, str] = {}

# kind -> violations from the most recent strict-mode validation
violations: dict[str, list["FootprintViolation"]] = {}

# sensitivity lattice: join = most value-sensitive class wins
_SENS_ORDER = {"existence": 0, "range": 1, "string-regex": 2, "equality": 3}

# constraint match criteria read these object paths (engine._kind_mask):
# kinds/groups from $meta, namespaces/name/labelSelector from metadata.
# The engine unions them into every template's effective read-set.
MATCH_PATHS: tuple[tuple[str, ...], ...] = (
    ("metadata", "labels"),
    ("metadata", "name"),
    ("metadata", "namespace"),
    ("$meta",),
)

# perturbing these changes world structure (row keys, review identity),
# not column values — never candidate perturbation targets
_IDENTITY_PATHS: tuple[tuple[str, ...], ...] = (
    ("apiVersion",), ("kind",),
    ("metadata", "name"), ("metadata", "namespace"),
)


def mode() -> str:
    """off | on | strict.  ``on`` (default) runs the static analysis at
    install and enables selective invalidation; ``strict`` additionally
    perturbation-validates every footprint at install and fails the
    install on any violation; ``off`` is the bit-identical oracle."""
    return os.environ.get("GATEKEEPER_FOOTPRINT", "on").strip().lower()


def validation_budget() -> int:
    return int(os.environ.get("GATEKEEPER_FOOTPRINT_MODELS", "16"))


# ---------------------------------------------------------------------------
# results


@dataclasses.dataclass(frozen=True)
class ColumnRead:
    """One column the template's verdict can depend on.

    source: "object" (the reviewed object), "meta" (review identity,
    path starts with "$meta"), or "inventory:<Kind>" (another kind's
    cached objects, via an inv-join).  Paths use "*" for list axes."""

    path: tuple[str, ...]
    source: str
    sensitivity: str

    def format(self) -> str:
        p = ".".join(self.path)
        src = "" if self.source == "object" else f" [{self.source}]"
        return f"{p}{src} ({self.sensitivity})"


@dataclasses.dataclass(frozen=True)
class FootprintViolation:
    """Perturbation validation found a column OUTSIDE the claimed
    read-set that changes the device verdict — an analysis bug."""

    kind: str
    path: tuple[str, ...]
    note: str = ""

    def format(self) -> str:
        return (f"{self.kind}: verdict changed when perturbing "
                f"unclaimed column {'.'.join(self.path)} ({self.note})")


@dataclasses.dataclass(frozen=True)
class Footprint:
    kind: str
    digest: str
    columns: tuple[ColumnRead, ...]
    providers: tuple[str, ...]
    row_local: bool
    cross_row_reasons: tuple[str, ...] = ()
    validated: bool = False
    version: str = FOOTPRINT_VERSION

    def object_paths(self) -> tuple[tuple[str, ...], ...]:
        """Object-column paths (including inventory columns: in the
        audit world the inventory IS the table), for dirty-path
        intersection."""
        return tuple(c.path for c in self.columns
                     if c.source != "meta")

    def reads_meta(self) -> bool:
        return any(c.source == "meta" for c in self.columns)


# ---------------------------------------------------------------------------
# digest (snapshot key)


def _spec_sig(spec) -> tuple:
    """Deterministic signature of every PrepSpec request the analysis
    reads (fn fields excluded — they are compare=False closures; the
    program cache_key pins the semantics that matter)."""
    return (
        tuple((r.name, r.path, r.mode) for r in spec.r_cols),
        tuple((e.name, e.axis, e.base, e.rel, e.mode) for e in spec.e_cols),
        tuple(spec.axes),
        tuple((t.name, t.src, t.out, t.src_val, t.regex, t.ext_providers)
              for t in spec.tables),
        tuple((p.name, p.src, p.src_val) for p in spec.ptables),
        tuple((d.name, d.src, d.pattern) for d in getattr(spec, "dfas", ())),
        tuple((m.name, m.cset, m.keys_path) for m in spec.membs),
        tuple((k.name, k.path) for k in spec.keyed_vals),
        tuple((e.name, e.cset, e.axis) for e in spec.elem_keys),
        tuple((j.name, j.kind, j.inv_path, j.src_path,
               j.exclude_same_name, j.namespaced_only)
              for j in spec.inv_joins),
    )


def footprint_digest(lowered) -> str:
    parts = (FOOTPRINT_VERSION, repr(lowered.program.cache_key()),
             repr(_spec_sig(lowered.spec)))
    return hashlib.sha256("\x1f".join(parts).encode()).hexdigest()


# ---------------------------------------------------------------------------
# the abstract interpreter


def paths_intersect(a: tuple, b: tuple) -> bool:
    """Does a write at path ``a`` affect a read at path ``b`` (or vice
    versa)?  True when one is a component-wise prefix of the other,
    with "*" matching any component — writes below a read subsume it
    and writes above it replace the whole subtree."""
    for x, y in zip(a, b):
        if x != y and x != "*" and y != "*":
            return False
    return True


class _Reads:
    def __init__(self):
        self.uses: dict[tuple[tuple, str], set[str]] = {}
        self.modes: dict[tuple[tuple, str], str] = {}

    def add(self, path: tuple, source: str, mode: str,
            use: str | None = None) -> None:
        key = (path, source)
        self.uses.setdefault(key, set())
        self.modes.setdefault(key, mode)
        if use is not None:
            self.uses[key].add(use)

    def columns(self) -> tuple[ColumnRead, ...]:
        out = []
        for (path, source), uses in self.uses.items():
            if not uses:
                # never consumed by a classifying op: the column's
                # extraction mode decides (a bare truthy/present
                # conjunct only observes existence)
                m = self.modes[(path, source)]
                uses = {"existence" if m in ("present", "truthy")
                        else "equality"}
            sens = max(uses, key=lambda u: _SENS_ORDER[u])
            out.append(ColumnRead(path=path, source=source,
                                  sensitivity=sens))
        return tuple(sorted(out, key=lambda c: (c.source, c.path)))


def _col_keys(name: str, spec, by_r, by_e) -> list[tuple[tuple, str, str]]:
    """(path, source, mode) for an r-/e-column binding name."""
    rc = by_r.get(name)
    if rc is not None:
        src = "meta" if rc.path[:1] == ("$meta",) else "object"
        return [(rc.path, src, rc.mode)]
    ec = by_e.get(name)
    if ec is not None:
        return [(ec.base + ("*",) + ec.rel, "object", ec.mode)]
    return []


def analyze(kind: str, lowered) -> Footprint:
    """Compute the footprint of one lowered template — the exact
    read-set of the nodes reachable from its rule conjuncts (dead
    subtrees, e.g. orphaned by a dedup rewrite, read nothing)."""
    from gatekeeper_tpu.analysis.costmodel import reachable_nodes

    spec = lowered.spec
    prog = lowered.program
    by_r = {r.name: r for r in spec.r_cols}
    by_e = {e.name: e for e in spec.e_cols}
    by_t = {t.name: t for t in spec.tables}
    by_pt = {p.name: p for p in spec.ptables}
    by_d = {d.name: d for d in getattr(spec, "dfas", ())}
    by_m = {m.name: m for m in spec.membs}
    by_kv = {k.name: k for k in spec.keyed_vals}
    by_ek = {e.name: e for e in spec.elem_keys}
    by_ij = {j.name: j for j in spec.inv_joins}
    axis_base = dict(spec.axes)

    reads = _Reads()
    providers: set[str] = set()
    reasons: list[str] = []
    reach = reachable_nodes(prog)
    # node index -> the (path, source, mode) keys its value carries
    carried: dict[int, list[tuple[tuple, str, str]]] = {}

    def record(keys, use):
        for path, source, m in keys:
            reads.add(path, source, m, use)

    for i in sorted(reach):
        n = prog.nodes[i]
        op = n.op
        keys: list[tuple[tuple, str, str]] = []
        if op == "input":
            name, _ikind = n.meta
            keys = _col_keys(name, spec, by_r, by_e)
            for path, source, m in keys:
                reads.add(path, source, m)
            ij = by_ij.get(name)
            if ij is not None:
                # the inv-join column is computed from OTHER rows of
                # `ij.kind`: cross-row by nature, and it reads the
                # inventory column plus this row's source/identity
                reads.add(ij.inv_path, f"inventory:{ij.kind}",
                          "val", "equality")
                reads.add(ij.src_path, "object", "val", "equality")
                if ij.exclude_same_name:
                    reads.add(("metadata", "name"), "object", "str",
                              "equality")
                if ij.namespaced_only:
                    reads.add(("metadata", "namespace"), "object", "str",
                              "equality")
                reasons.append(
                    f"inventory join {name}: ∃ other {ij.kind} with "
                    f"{'.'.join(ij.inv_path)} == this "
                    f"{'.'.join(ij.src_path)}")
        elif op in ("table", "ptable_any", "ptable_all"):
            tname = n.meta[0]
            t = by_t.get(tname) or by_pt.get(tname)
            if t is not None:
                use = "string-regex" if getattr(t, "regex", None) \
                    else "equality"
                src_keys = _col_keys(t.src, spec, by_r, by_e)
                record(src_keys, use)
                keys = src_keys
                providers.update(getattr(t, "ext_providers", ()))
        elif op == "dfa_match":
            # the in-program DFA reads the interned byte encoding of the
            # source column: any change to the string's bytes can flip
            # the verdict, so the claim is the column at string-regex
            # sensitivity — exactly what the host-table lowering of the
            # same pattern claims (parity keeps narrow-claim validation
            # applicable to both paths)
            d = by_d.get(n.meta[0])
            if d is not None:
                src_keys = _col_keys(d.src, spec, by_r, by_e)
                record(src_keys, "string-regex")
                keys = src_keys
        elif op == "keyed_val":
            (name,) = n.meta
            kv = by_kv.get(name)
            if kv is not None:
                # dict[param key]: any key under the path can be read
                reads.add(kv.path + ("*",), "object", "val", "equality")
                keys = [(kv.path + ("*",), "object", "val")]
        elif op in ("cset_not_subset_memb", "cset_subset_memb"):
            _cname, mname = n.meta
            m = by_m.get(mname)
            if m is not None:
                # the membership matrix observes the KEY SET of the
                # dict at keys_path — adding/removing keys matters,
                # values under them do not, but the whole subtree is
                # claimed (prefix semantics keep this sound)
                reads.add(m.keys_path, "object", "val", "equality")
        elif op == "elem_keys_missing":
            _cname, ekname = n.meta
            ek = by_ek.get(ekname)
            if ek is not None:
                base = axis_base.get(ek.axis, ())
                reads.add(tuple(base) + ("*",), "object", "val",
                          "existence")
        elif op == "cmp":
            (cop,) = n.meta
            arg_keys = [k for a in n.args for k in carried.get(a, [])]
            ordering = cop in ("<", "<=", ">", ">=")
            for path, source, m in arg_keys:
                use = "range" if ordering and m in ("num", "len") \
                    else "equality"
                reads.add(path, source, m, use)
            keys = arg_keys
        elif op == "in_cset":
            arg_keys = [k for a in n.args for k in carried.get(a, [])]
            record(arg_keys, "equality")
            keys = arg_keys
        else:
            # and/or/not/any_e/all_e/count_e/arith/const: columns flow
            # through unclassified
            keys = [k for a in n.args for k in carried.get(a, [])]
        carried[i] = keys

    row_local = not reasons
    if not row_local:
        cross_row[kind] = "; ".join(reasons)
    else:
        cross_row.pop(kind, None)
    return Footprint(kind=kind, digest=footprint_digest(lowered),
                     columns=reads.columns(),
                     providers=tuple(sorted(providers)),
                     row_local=row_local,
                     cross_row_reasons=tuple(reasons))


# ---------------------------------------------------------------------------
# perturbation validation (footprints are validated, not trusted)


def _leaf_paths(obj, prefix: tuple = (), depth: int = 6) -> set[tuple]:
    out: set[tuple] = set()
    if depth <= 0:
        return out
    if isinstance(obj, dict):
        for k, v in obj.items():
            if not isinstance(k, str):
                continue
            p = prefix + (k,)
            sub = _leaf_paths(v, p, depth - 1)
            out.update(sub if sub else {p})
    elif isinstance(obj, list):
        for v in obj:
            sub = _leaf_paths(v, prefix + ("*",), depth - 1)
            out.update(sub if sub else {prefix + ("*",)})
    return out


def _perturb(obj, path: tuple, token, delete: bool = False) -> None:
    """Set (or delete) the value at ``path`` in place; "*" fans out
    over list elements; missing intermediate dicts are created on the
    set path and end the walk on the delete path."""
    if not path:
        return
    head, rest = path[0], path[1:]
    if head == "*":
        if isinstance(obj, list):
            if rest:
                for el in obj:
                    _perturb(el, rest, token, delete)
            elif not delete:
                for j in range(len(obj)):
                    obj[j] = token
        return
    if not isinstance(obj, dict):
        return
    if not rest:
        if delete:
            obj.pop(head, None)
        else:
            obj[head] = token
        return
    nxt = obj.get(head)
    if nxt is None:
        if delete:
            return
        nxt = obj[head] = {}
    _perturb(nxt, rest, token, delete)


def validate_footprint(kind: str, compiled, lowered, fp: Footprint,
                       constraints: list[dict] | None = None,
                       budget: int | None = None,
                       max_candidates: int = 12
                       ) -> list[FootprintViolation]:
    """Perturb columns OUTSIDE the claimed read-set over smallmodel
    worlds and assert the device mask is bit-identical.  Candidate
    columns come from the model resources themselves plus synthetic
    probe paths; identity fields are excluded (changing them changes
    world structure, not a column value)."""
    from gatekeeper_tpu.analysis import transval
    from gatekeeper_tpu.analysis.smallmodel import (derive_plan,
                                                    enumerate_models)

    cons = transval.expand_constraints(kind, constraints)
    plan = derive_plan(lowered, cons, module=compiled.module)
    models = enumerate_models(plan, budget or validation_budget())
    all_res = [obj for m in models for obj in m.resources]
    if not all_res:
        return []
    st, _rows, _handler = transval._world_state(all_res)
    base_mask, _b = transval._device_mask(lowered, st, cons)

    claimed = set(fp.object_paths()) | set(_IDENTITY_PATHS) | {("$meta",)}
    candidates: set[tuple] = set()
    for obj in all_res:
        candidates.update(_leaf_paths(obj))
    candidates.add(("metadata", "annotations", "gatekeeper-fp-probe"))
    candidates.add(("spec", "gatekeeperFpProbe"))
    open_paths = sorted(
        p for p in candidates
        if not any(paths_intersect(p, c) for c in claimed))[:max_candidates]

    out: list[FootprintViolation] = []
    for pi, path in enumerate(open_paths):
        for variant, delete in (("mutate", False), ("delete", True)):
            perturbed = copy.deepcopy(all_res)
            for obj in perturbed:
                _perturb(obj, path, f"fp-perturbed-{pi}", delete=delete)
            st2, _r2, _h2 = transval._world_state(perturbed)
            mask2, _b2 = transval._device_mask(lowered, st2, cons)
            if mask2.shape != base_mask.shape \
                    or not np.array_equal(mask2, base_mask):
                out.append(FootprintViolation(
                    kind=kind, path=path,
                    note=f"{variant} over {len(models)} model world(s)"))
                break
    return out


# ---------------------------------------------------------------------------
# fault seam + memoized entry point


def _narrow_kinds() -> set[str]:
    raw = os.environ.get("GATEKEEPER_FOOTPRINT_TEST_NARROW", "")
    return {t.strip() for t in raw.split(",") if t.strip()}


def maybe_narrowed(kind: str, fp: Footprint) -> Footprint:
    """Fault-injection seam: deliberately drop one claimed object
    column for the named kinds, proving end-to-end that perturbation
    validation catches a footprint that under-claims its reads."""
    if kind not in _narrow_kinds():
        return fp
    return narrow(fp)


def narrow(fp: Footprint) -> Footprint:
    """Drop one object column — prefer a spec-side read so the dropped
    path survives the validator's identity/match exclusions."""
    keep, dropped = [], None
    for c in fp.columns:
        if dropped is None and c.source == "object" \
                and c.path[:1] not in (("metadata",), ("$meta",)):
            dropped = c
            continue
        keep.append(c)
    if dropped is None:
        for c in list(keep):
            if c.source == "object":
                dropped = c
                keep.remove(c)
                break
    if dropped is None:
        return fp
    # drop ALL claims of that path (an inventory-source twin would
    # otherwise keep it out of the validator's candidate set)
    keep = [c for c in keep if c.path != dropped.path]
    log.warning("footprint deliberately narrowed (test seam)",
                kind=fp.kind, dropped=".".join(dropped.path))
    return dataclasses.replace(fp, columns=tuple(keep), validated=False)


def certify(kind: str, compiled, lowered,
            constraints: list[dict] | None = None) -> Footprint:
    """Memoized/snapshot-backed entry point the engine and probe use.

    The static analysis always runs (mode "on"); under "strict" the
    footprint is additionally perturbation-validated and any violation
    is recorded in ``violations[kind]`` (the engine then fails the
    install).  Validated footprints persist in the snapshot "fp" tier,
    so a warm restart re-runs zero analyses.  The NARROW seam bypasses
    both memo and snapshot — a narrowed footprint must reach the
    validator, not a cached honest one."""
    global analyses_run
    digest = footprint_digest(lowered)
    seam = kind in _narrow_kinds()
    if not seam:
        cached = _memo.get(digest)
        if cached is not None:
            _publish(kind, cached)
            return cached
        from gatekeeper_tpu.resilience import snapshot as _snap
        hit = _snap.load_footprint(digest)     # 1-tuple or None (miss)
        if hit is not None and isinstance(hit[0], Footprint) \
                and hit[0].version == FOOTPRINT_VERSION:
            _memo[digest] = hit[0]
            _publish(kind, hit[0])
            return hit[0]

    fp = analyze(kind, lowered)
    analyses_run += 1
    fp = maybe_narrowed(kind, fp)
    found: list[FootprintViolation] = []
    if mode() == "strict":
        found = validate_footprint(kind, compiled, lowered, fp,
                                   constraints=constraints)
        fp = dataclasses.replace(fp, validated=not found)
    if found:
        violations[kind] = found
        for v in found:
            log.warning("footprint violation", kind=kind,
                        column=".".join(v.path), note=v.note)
    else:
        violations.pop(kind, None)
    if not seam and not found:
        _memo[digest] = fp
        from gatekeeper_tpu.resilience import snapshot as _snap
        _snap.save_footprint(digest, fp)
    _publish(kind, fp)
    return fp


def _publish(kind: str, fp: Footprint) -> None:
    if fp.row_local:
        cross_row.pop(kind, None)
    else:
        cross_row[kind] = "; ".join(fp.cross_row_reasons) or "cross-row"


def locality_for(kind: str) -> str | None:
    """The cross-row reason for a kind, or None when row-local (or not
    yet analyzed)."""
    return cross_row.get(kind)


def violations_for(kind: str) -> list[FootprintViolation]:
    return violations.get(kind, [])
