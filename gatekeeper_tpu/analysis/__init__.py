"""Install-time static analysis: the two-stage template vetter.

Stage 1 (:mod:`.vetter`) walks the parsed Rego AST; Stage 2
(:mod:`.ir_verifier`) validates lowered device programs against their
PrepSpec.  Both emit :class:`.diagnostics.Diagnostic` records whose
codes follow the reference gatekeeper's ``status.byPod[].errors``
shape.  :mod:`.purity` is the single impure-builtin gate shared with
the shareable-review escape analysis; :mod:`.selflint` is the CI
host-sync + lock-discipline lint over host/kernel code.

Stage 3 (:mod:`.costmodel` + :mod:`.policyset`) analyzes the *set* of
installed policies: static per-program cost vectors with budget
admission, cross-template predicate dedup feeding the audit sweep, and
match shadowing/unreachability — ``cost_*`` / ``set_*`` findings.

Stage 4 (:mod:`.transval` + :mod:`.smallmodel`) is translation
validation: a bounded-model equivalence check of every lowered program
against the interpreter semantics, emitting a Certificate (persisted
through the warm-restart snapshot) or a minimal Counterexample that
joins the ``tests/corpus/transval/`` regression corpus.

Stage 5 (:mod:`.footprint`) is dependency analysis over the lowered
IR: per-template (kind, column) read-set footprints with sensitivity
classes, row-locality certificates gating shard_map eligibility, and
perturbation validation of the claimed read-set — footprints persist
in the snapshot ``fp`` tier and drive the engine's sweep-time
selective invalidation against the store's dirty-path log.

Stage 6 (:mod:`.shardplan`) is the sharding certifier: an abstract
interpreter propagates a row-sharded/replicated state through every
SSA value under a resource-axis partition and emits per-template
PartitionPlan certificates (required collectives, padding constraints,
per-shard H2D layout), validated on a 2-shard simulated mesh and
persisted in the snapshot ``sp`` tier — the engine's plan-driven
sweep behind ``GATEKEEPER_SHARDS=N`` consumes them.
"""

from gatekeeper_tpu.analysis.diagnostics import (   # noqa: F401
    ERROR, WARNING, Diagnostic, errors, format_all, has_errors,
)
from gatekeeper_tpu.analysis.purity import (        # noqa: F401
    is_impure_builtin, is_impure_call,
)
from gatekeeper_tpu.analysis.vetter import vet_module        # noqa: F401
from gatekeeper_tpu.analysis.ir_verifier import verify_program  # noqa: F401
from gatekeeper_tpu.analysis.costmodel import (   # noqa: F401
    CostVector, calibrate, estimate,
)
from gatekeeper_tpu.analysis.policyset import (   # noqa: F401
    analyze_policy_set, build_dedup_plan, constraint_set_warnings,
    duplicate_predicate_warnings, eval_shared_host, vet_template_cost,
)
from gatekeeper_tpu.analysis.transval import (    # noqa: F401
    Certificate, Counterexample, certify, replay_case, validate_template,
)
