"""Install-time static analysis: the two-stage template vetter.

Stage 1 (:mod:`.vetter`) walks the parsed Rego AST; Stage 2
(:mod:`.ir_verifier`) validates lowered device programs against their
PrepSpec.  Both emit :class:`.diagnostics.Diagnostic` records whose
codes follow the reference gatekeeper's ``status.byPod[].errors``
shape.  :mod:`.purity` is the single impure-builtin gate shared with
the shareable-review escape analysis; :mod:`.selflint` is the CI
host-sync lint over kernel-side code.
"""

from gatekeeper_tpu.analysis.diagnostics import (   # noqa: F401
    ERROR, WARNING, Diagnostic, errors, format_all, has_errors,
)
from gatekeeper_tpu.analysis.purity import (        # noqa: F401
    is_impure_builtin, is_impure_call,
)
from gatekeeper_tpu.analysis.vetter import vet_module        # noqa: F401
from gatekeeper_tpu.analysis.ir_verifier import verify_program  # noqa: F401
