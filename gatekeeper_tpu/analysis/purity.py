"""The single impure-builtin gate.

Both the shareable-review escape analysis (rego/closures.py) and the
Stage-1 vetter need the same judgment: "does this call name reach a
builtin whose result can vary between evaluations or leak information
out of the evaluation?".  The membership set lives in
rego/builtins.py (IMPURE_BUILTINS); this helper is the one place that
interprets it, so the two call sites can't drift.
"""

from __future__ import annotations


def is_impure_builtin(name: tuple[str, ...]) -> bool:
    """True iff ``name`` is a registered impure builtin (trace,
    time.now_ns, io.jwt.decode_verify, external_data)."""
    from gatekeeper_tpu.rego import builtins as bi
    return name in bi.IMPURE_BUILTINS


def is_impure_call(name: tuple[str, ...], rule_names) -> bool:
    """The closures.py judgment: a call is impurity-tainted when it
    names an impure builtin OR a user-defined rule/function (whose own
    body may be impure — the escape analysis doesn't chase the call
    graph, it over-approximates)."""
    return (is_impure_builtin(name)
            or (len(name) == 1 and name[0] in rule_names))
