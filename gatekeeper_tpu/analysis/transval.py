"""Stage 4: install-time translation validation (Rego ↔ lowered IR).

The lowering contract (ir/lower.py) allows the device mask to
*over*-approximate — fired (constraint, resource) pairs are re-evaluated
on host by the scalar oracle before any message is emitted — but an
*under*-approximation (oracle says violation, device mask silent) is a
silent enforcement hole.  Today that direction is only checked
dynamically (tests/test_fuzz_parity.py).  This module checks it at
install time: enumerate the template's bounded small-model universe
(:mod:`.smallmodel`), evaluate every world through both semantics, and
emit either a :class:`Certificate` (persisted as the fifth snapshot tier
in resilience/snapshot.py, keyed by IR digest, so warm restarts skip
re-validation) or a concrete :class:`Counterexample` (minimal world +
constraint + expected/actual verdicts) that serializes into
``tests/corpus/transval/`` and replays forever as a regression test.

Known, excused deviation: worlds whose numeric bindings are not exactly
float32-representable (``Bindings.f32_unsafe``) — the driver already
routes those kinds to the scalar oracle at serve time, so a
disagreement there is unreachable in production and is counted as
``excused_f32`` rather than refuting the translation.

Modes (``GATEKEEPER_TRANSVAL``): ``off`` (default), ``warn`` (validate,
log, serve on device regardless), ``strict`` (a counterexample pins the
template to the scalar fallback exactly as if it had never lowered, and
the reconciler writes ``translation_unvalidated`` into
``status.byPod[].errors``).
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import json
import os
from typing import Any

import numpy as np

from gatekeeper_tpu.analysis import smallmodel
from gatekeeper_tpu.analysis.smallmodel import Model, derive_plan, enumerate_models
from gatekeeper_tpu.utils.log import logger

log = logger("analysis.transval")

# bump whenever the model universe or checking semantics change: stale
# certificates must not excuse a re-lowered program from re-validation
TRANSVAL_VERSION = "transval-v1"

DEFAULT_BUDGET = 96

# kind -> counterexample summary, for the reconciler's status writer
failures: dict[str, "Counterexample"] = {}

# process-lifetime count of full validations actually executed (memo /
# snapshot hits do not count) — resilience/smoke.py asserts this is 0
# on a warm restart
validations_run = 0

_memo: dict[str, Any] = {}


def mode() -> str:
    return os.environ.get("GATEKEEPER_TRANSVAL", "off").strip().lower()


def model_budget() -> int:
    try:
        return max(4, int(os.environ.get("GATEKEEPER_TRANSVAL_MODELS",
                                         str(DEFAULT_BUDGET))))
    except ValueError:
        return DEFAULT_BUDGET


# ---------------------------------------------------------------------------
# results


@dataclasses.dataclass
class Certificate:
    """Proof token: the lowered program agreed with the interpreter on
    every world of the bounded universe (minus excused f32 worlds)."""

    kind: str
    digest: str
    models_checked: int
    constraints_checked: int
    fp_models: int          # device over-approximations (allowed)
    excused_f32: int
    excused_mixed: int      # mixed-type ordering (lower.py known dev.)
    truncated: bool
    budget: int
    version: str = TRANSVAL_VERSION


@dataclasses.dataclass
class Counterexample:
    """One concrete world refuting the translation: the oracle derives
    a violation the device mask misses (or, in replay, any parity
    break on the recorded world)."""

    kind: str
    target: str
    rego: str
    constraint: dict
    resources: list
    focus: int
    expected: bool
    actual: bool
    note: str = ""

    def to_json(self) -> dict:
        return {"version": TRANSVAL_VERSION, "kind": self.kind,
                "target": self.target, "rego": self.rego,
                "constraint": self.constraint, "resources": self.resources,
                "focus": self.focus, "expected": self.expected,
                "actual": self.actual, "note": self.note}

    @staticmethod
    def from_json(doc: dict) -> "Counterexample":
        return Counterexample(
            kind=doc["kind"], target=doc["target"], rego=doc["rego"],
            constraint=doc["constraint"], resources=doc["resources"],
            focus=doc.get("focus", 0), expected=doc["expected"],
            actual=doc["actual"], note=doc.get("note", ""))


def certificate_digest(lowered, constraints: list[dict],
                       budget: int) -> str:
    """Key of one validation run: the exact program (Program.cache_key
    reprs deterministically — tuples of scalars only, no sets/dicts),
    the constraint docs checked against, and the universe bound."""
    parts = (TRANSVAL_VERSION, repr(lowered.program.cache_key()),
             json.dumps(constraints, sort_keys=True, default=repr),
             str(budget))
    return hashlib.sha256("\x1f".join(parts).encode()).hexdigest()


# ---------------------------------------------------------------------------
# world evaluation: both semantics over one shared world


def _world_state(resources: list):
    """(TargetState, [(row, resource_index)]) with every resource
    upserted — both semantics must see the identical world."""
    from gatekeeper_tpu.client.local_driver import TargetState
    from gatekeeper_tpu.target.k8s import K8sValidationTarget

    handler = K8sValidationTarget()
    st = TargetState()
    rows: list[tuple[int, int]] = []
    for ri, obj in enumerate(resources):
        key, meta, obj2 = handler.process_data(obj)
        rows.append((st.table.upsert(key, obj2, meta), ri))
    return st, rows, handler


def _device_mask(lowered, st, constraints: list[dict]):
    """Eager (un-jitted) evaluation of the lowered program — one
    dispatch chain per batch instead of 49 XLA compiles.  Returns the
    bool mask trimmed to [n_constraints, n_resources] plus the
    Bindings (for the f32_unsafe flag)."""
    from gatekeeper_tpu.engine.veval import _eval_program
    from gatekeeper_tpu.ir.prep import build_bindings

    bindings = build_bindings(lowered.spec, st.table, constraints)
    mask = np.asarray(_eval_program(lowered.program, bindings.arrays))
    return mask[:len(constraints), :len(st.table._objs)], bindings


def _interp_fires(compiled, handler, st, row: int, frozen_c,
                  inv) -> bool:
    """Reference semantics for one (constraint, row): does the oracle
    derive at least one violation Obj carrying a msg?  (regolib
    filters results without msg — local_driver._eval_pair.)"""
    from gatekeeper_tpu.rego.values import Obj, freeze

    meta = st.table.meta_at(row)
    obj = st.table.object_at(row)
    if meta is None:
        return False
    review = handler.make_review(meta, obj)
    input_doc = Obj({"review": freeze(review), "constraint": frozen_c})
    try:
        results = compiled.interp.query_set("violation", input_doc, inv)
    except Exception as e:   # noqa: BLE001 — oracle error == undefined
        log.warning("transval oracle error", kind=compiled.kind, err=str(e))
        return False
    return any(isinstance(v, Obj) and "msg" in v for v in results)


def _has_ordering_cmp(program) -> bool:
    return any(nd.op == "cmp" and nd.meta
               and nd.meta[0] in ("<", "<=", ">", ">=")
               for nd in program.nodes)


def _elements_at(obj, base: tuple) -> list:
    """Element dicts of one axis base path ('*' descends every element
    of the outer list)."""
    cur = [obj]
    for seg in base:
        nxt: list = []
        for c in cur:
            if seg == "*":
                if isinstance(c, list):
                    nxt.extend(c)
            elif isinstance(c, dict) and seg in c:
                nxt.append(c[seg])
        cur = nxt
    out: list = []
    for c in cur:
        if isinstance(c, list):
            out.extend(e for e in c if isinstance(e, dict))
    return out


def _mixed_numeric_world(spec, resources: list) -> bool:
    """Does some num-mode column read a present non-numeric raw value
    (string/null/bool/compound where a number is expected)?  Ordering
    over such values follows OPA's cross-type total order on the oracle
    but is undefined on device — the second documented lowering
    deviation (ir/lower.py:32-34), excused like f32."""
    def mismatched(v) -> bool:
        return v is not smallmodel.ABSENT and (
            isinstance(v, bool) or not isinstance(v, (int, float)))

    for obj in resources:
        for rc in spec.r_cols:
            if rc.mode != "num" or (rc.path and rc.path[0] == "$meta"):
                continue
            cur = obj
            for seg in rc.path:
                cur = (cur.get(seg, smallmodel.ABSENT)
                       if isinstance(cur, dict) else smallmodel.ABSENT)
            if mismatched(cur):
                return True
        for ec in spec.e_cols:
            if ec.mode != "num":
                continue
            for elem in _elements_at(obj, ec.base):
                cur = elem
                for seg in ec.rel:
                    cur = (cur.get(seg, smallmodel.ABSENT)
                           if isinstance(cur, dict) else smallmodel.ABSENT)
                if mismatched(cur):
                    return True
    return False


def _check_world(compiled, lowered, constraints: list[dict],
                 resources: list):
    """Evaluate one isolated world through both semantics.

    Returns (status, detail): status 'excused_f32' | 'excused_mixed' |
    'agree' | 'disagree'; detail on disagreement is (constraint_index,
    resource_index, expected, actual) for the first under-approximated
    pair.  Over-approximation is NOT a disagreement (the lowering
    contract allows it; fired pairs re-evaluate on host)."""
    from gatekeeper_tpu.rego.values import freeze

    st, rows, handler = _world_state(resources)
    mask, bindings = _device_mask(lowered, st, constraints)
    if bindings.f32_unsafe:
        return "excused_f32", None
    inv = st.inventory_doc() if compiled.uses_inventory else None
    for ci, c in enumerate(constraints):
        fc = freeze(c)
        for row, ri in rows:
            expected = _interp_fires(compiled, handler, st, row, fc, inv)
            actual = bool(mask[ci, row])
            if expected and not actual:
                if (_has_ordering_cmp(lowered.program)
                        and _mixed_numeric_world(lowered.spec, resources)):
                    return "excused_mixed", None
                return "disagree", (ci, ri, expected, actual)
    return "agree", None


# ---------------------------------------------------------------------------
# counterexample minimization


_PROTECTED = {("apiVersion",), ("kind",), ("metadata",),
              ("metadata", "name")}


def _delete_path(obj: dict, path: tuple) -> bool:
    cur = obj
    for seg in path[:-1]:
        cur = cur.get(seg) if isinstance(cur, dict) else None
        if cur is None:
            return False
    if isinstance(cur, dict) and path[-1] in cur:
        del cur[path[-1]]
        return True
    return False


def _all_paths(obj, prefix=()):
    if isinstance(obj, dict):
        for k in sorted(obj, key=repr):
            yield prefix + (k,)
            yield from _all_paths(obj[k], prefix + (k,))


def _get_path(obj, path):
    cur = obj
    for seg in path:
        cur = cur.get(seg) if isinstance(cur, dict) else None
    return cur


def _minimize(compiled, lowered, constraint: dict, resources: list,
              focus: int, steps: int = 40) -> list:
    """Greedy shrink: drop object subtrees / truncate lists of the
    focus resource while the under-approximation still reproduces in
    isolation.  Deepest paths first so leaves go before containers."""
    world = copy.deepcopy(resources)

    def still_fails(candidate: list) -> bool:
        status, _ = _check_world(compiled, lowered, [constraint], candidate)
        return status == "disagree"

    for _ in range(steps):
        shrunk = False
        paths = sorted(_all_paths(world[focus]),
                       key=lambda p: (-len(p), p))
        for path in paths:
            if path in _PROTECTED or (path and path[0] == "metadata"
                                      and len(path) == 1):
                continue
            trial = copy.deepcopy(world)
            if not _delete_path(trial[focus], path):
                continue
            if still_fails(trial):
                world = trial
                shrunk = True
                break
        if not shrunk:
            # second pass: shorten lists instead of deleting them
            for path in sorted(_all_paths(world[focus]),
                               key=lambda p: (-len(p), p)):
                v = _get_path(world[focus], path)
                if isinstance(v, list) and len(v) > 1:
                    trial = copy.deepcopy(world)
                    tv = _get_path(trial[focus], path)
                    del tv[1:]
                    if still_fails(trial):
                        world = trial
                        shrunk = True
                        break
            if not shrunk:
                break
    return world


# ---------------------------------------------------------------------------
# the validator


def _bump_numbers(doc):
    if isinstance(doc, bool):
        return doc
    if isinstance(doc, (int, float)):
        return doc + 1
    if isinstance(doc, dict):
        return {k: _bump_numbers(v) for k, v in doc.items()}
    if isinstance(doc, list):
        return [_bump_numbers(v) for v in doc]
    return doc


def expand_constraints(kind: str, constraints: list[dict] | None) -> list[dict]:
    """The constraint axis of the universe.  Install-time validation
    (reconcile order: templates before constraints) uses the empty
    parameter document — the same stand-in policyset.template_digests
    uses; callers with real sample docs (probe --certify, tests) get a
    numeric-bumped variant appended so param-folded tables/csets are
    exercised at two operating points."""
    if not constraints:
        return [{"kind": kind, "metadata": {"name": "tv-default"},
                 "spec": {"parameters": {}}}]
    out = [copy.deepcopy(c) for c in constraints[:2]]
    for c in list(out):
        params = ((c.get("spec") or {}).get("parameters")) or {}
        if params and len(out) < 3:
            bumped = copy.deepcopy(c)
            bumped.setdefault("metadata", {})
            bumped["metadata"] = dict(bumped["metadata"],
                                      name=(bumped["metadata"].get("name", "c")
                                            + "-bumped"))
            bumped["spec"]["parameters"] = _bump_numbers(params)
            out.append(bumped)
    return out


def validate_template(kind: str, compiled, lowered=None,
                      constraints: list[dict] | None = None,
                      budget: int | None = None
                      ) -> "Certificate | Counterexample":
    """Run the bounded-model equivalence check for one template.

    `lowered` defaults to compiled.vectorized (tests pass a corrupted
    program explicitly); `constraints` are raw constraint docs (the
    sample axis) — see expand_constraints for the default."""
    global validations_run
    lowered = lowered if lowered is not None else compiled.vectorized
    if lowered is None:
        raise ValueError(f"{kind}: nothing to validate (not lowered)")
    budget = budget or model_budget()
    cons = expand_constraints(kind, constraints)
    digest = certificate_digest(lowered, cons, budget)
    validations_run += 1

    plan = derive_plan(lowered, cons, module=compiled.module)
    models = enumerate_models(plan, budget)

    # one shared world: every model's resources co-resident in one
    # table, ONE build_bindings + ONE eager program evaluation.  Sound
    # because both semantics see the identical world — co-residency can
    # only perturb which abstract states get visited, never the
    # per-(constraint, row) comparison itself.
    from gatekeeper_tpu.rego.values import freeze

    all_res: list = []
    owner: list[tuple[int, int]] = []     # flat index -> (model, res idx)
    for mi, m in enumerate(models):
        for ri, obj in enumerate(m.resources):
            all_res.append(obj)
            owner.append((mi, ri))
    st, rows, handler = _world_state(all_res)
    mask, bindings = _device_mask(lowered, st, cons)
    batch_f32_unsafe = bindings.f32_unsafe
    inv = st.inventory_doc() if compiled.uses_inventory else None

    fp_models = 0
    excused = 0
    excused_mixed = 0
    frozen = [freeze(c) for c in cons]
    for ci, c in enumerate(cons):
        for flat, (row, _ri) in enumerate(rows):
            expected = _interp_fires(compiled, handler, st, row,
                                     frozen[ci], inv)
            actual = bool(mask[ci, row])
            if expected == actual:
                continue
            if actual and not expected:
                fp_models += 1          # over-approximation: allowed
                continue
            # under-approximation: re-check the owning model isolated —
            # the big-batch table may carry f32-unsafe numerics from
            # *other* models that a production table for this world
            # would not
            mi, _ = owner[flat]
            model = models[mi]
            status, _detail = _check_world(compiled, lowered, [c],
                                           model.resources)
            if status == "excused_f32":
                excused += 1
                continue
            if status == "excused_mixed":
                excused_mixed += 1
                continue
            if status == "agree":
                if batch_f32_unsafe:
                    excused += 1        # artifact of co-resident numerics
                    continue
                # cross-world-dependent disagreement (e.g. interner or
                # join effects): report the full world, unminimized
                ce = Counterexample(
                    kind=kind, target=compiled.target, rego=compiled.source,
                    constraint=c, resources=copy.deepcopy(all_res),
                    focus=flat, expected=expected, actual=actual,
                    note=f"batch-context dependent ({model.note})")
                failures[kind] = ce
                return ce
            minimal = _minimize(compiled, lowered, c, model.resources,
                                model.focus if len(model.resources) > 1
                                else 0)
            ce = Counterexample(
                kind=kind, target=compiled.target, rego=compiled.source,
                constraint=c, resources=minimal,
                focus=model.focus, expected=expected, actual=actual,
                note=f"model {model.note}")
            failures[kind] = ce
            return ce

    failures.pop(kind, None)
    return Certificate(kind=kind, digest=digest,
                       models_checked=len(models),
                       constraints_checked=len(cons),
                       fp_models=fp_models, excused_f32=excused,
                       excused_mixed=excused_mixed,
                       truncated=plan.truncated, budget=budget)


def certify(kind: str, compiled, lowered,
            constraints: list[dict] | None = None
            ) -> "Certificate | Counterexample":
    """Memoized/snapshot-backed entry point the engine and probe use.

    Certificates persist through the cert snapshot tier so a warm
    restart skips validation entirely (validations_run stays 0);
    counterexamples are memoized in-process only — a cold process
    re-derives them so a fixed lowering is immediately re-admitted."""
    budget = model_budget()
    cons = expand_constraints(kind, constraints)
    digest = certificate_digest(lowered, cons, budget)
    cached = _memo.get(digest)
    if cached is not None:
        if isinstance(cached, Counterexample):
            failures[kind] = cached
        return cached
    from gatekeeper_tpu.resilience import snapshot as _snap

    hit = _snap.load_cert(digest)
    if hit is not None:
        _memo[digest] = hit[0]
        failures.pop(kind, None)
        return hit[0]
    result = validate_template(kind, compiled, lowered=lowered,
                               constraints=cons, budget=budget)
    _memo[digest] = result
    if isinstance(result, Certificate):
        _snap.save_cert(digest, result)
    return result


def failure_for(kind: str) -> "Counterexample | None":
    return failures.get(kind)


def maybe_miscompiled(kind: str, lowered):
    """Fault-injection seam (GATEKEEPER_TRANSVAL_TEST_MISCOMPILE=<Kind>,
    comma-separable): hand the validator a deliberately corrupted
    program for the named kinds, proving end-to-end that a real
    miscompile would be caught, pinned, and surfaced in status."""
    target = os.environ.get("GATEKEEPER_TRANSVAL_TEST_MISCOMPILE", "")
    if not target:
        return lowered
    if kind in {t.strip() for t in target.split(",") if t.strip()}:
        return miscompile(lowered)
    return lowered


def miscompile(lowered):
    """A minimal deliberate translation bug: flip the first comparison
    (fallback: swap the first and/or).  Used by the fixture tests and
    the GATEKEEPER_TRANSVAL_TEST_MISCOMPILE hook."""
    import dataclasses as dc

    from gatekeeper_tpu.ir.program import Program

    flip_cmp = {"==": "!=", "!=": "==", "<": ">=", "<=": ">",
                ">": "<=", ">=": "<"}
    nodes = list(lowered.program.nodes)
    for i, nd in enumerate(nodes):
        if nd.op == "cmp":
            nodes[i] = dc.replace(nd, meta=(flip_cmp[nd.meta[0]],))
            break
        if nd.op in ("and", "or"):
            nodes[i] = dc.replace(nd, op="or" if nd.op == "and" else "and")
            break
    else:
        raise ValueError("no miscompilable node in program")
    program = Program(nodes=tuple(nodes), rules=lowered.program.rules)
    return dc.replace(lowered, program=program)


# ---------------------------------------------------------------------------
# counterexample corpus (tests/corpus/transval/)


def save_counterexample(dirpath: str, ce: Counterexample) -> str:
    os.makedirs(dirpath, exist_ok=True)
    doc = ce.to_json()
    tag = hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:10]
    path = os.path.join(dirpath, f"{ce.kind.lower()}-{tag}.json")
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_corpus(dirpath: str) -> list[tuple[str, dict]]:
    if not os.path.isdir(dirpath):
        return []
    out = []
    for name in sorted(os.listdir(dirpath)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(dirpath, name)) as f:
            out.append((name, json.load(f)))
    return out


def replay_case(case: dict, lowered=None) -> str | None:
    """Replay one corpus case against the CURRENT compiler.  Returns
    None when parity holds on the recorded world (the historical bug
    stays fixed), else a description of the surviving violation.
    `lowered` overrides the freshly-lowered program (fixture tests
    replay against a known-corrupted program to prove the case bites)."""
    from gatekeeper_tpu.api.templates import compile_target_rego
    from gatekeeper_tpu.ir.lower import CannotLower, lower_template

    compiled = compile_target_rego(case["kind"], case["target"],
                                   case["rego"])
    if lowered is None:
        try:
            lowered = lower_template(compiled.module, compiled.interp)
        except CannotLower:
            return None   # no device program: nothing to miscompile
    status, detail = _check_world(compiled, lowered, [case["constraint"]],
                                  case["resources"])
    if status == "disagree":
        ci, ri, expected, actual = detail
        return (f"{case['kind']}: under-approximation replayed on "
                f"resource {ri} (expected={expected} actual={actual})")
    return None
