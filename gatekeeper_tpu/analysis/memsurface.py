"""Stage-8 memory-surface certifier: static peak-HBM accounting.

Stages 4-7 certify verdicts, read-sets, sharding, and the compile
surface.  None of them bounds *device memory*: a policy set that fits
at install can OOM mid-sweep once bound constants (DFA tables, the
interner byte matrix), per-kind binding arrays, devpages page state,
and SSA intermediates stack up across the whole installed set.  An
OOM discovered at sweep time is the worst possible failure mode — the
engine is already serving traffic.

This stage closes the hole statically.  An abstract interpreter over
the lowered spec and SSA program computes one :class:`MemorySurface`
certificate per template:

  * **bound arrays** — every binding the prep layer can materialize
    (the same static enumeration the Stage-7 certifier composes over),
    as byte polynomials over the pad-geometry axis classes of
    :func:`ir.prep.binding_dim_classes` ('r'/'c'/'t'/'e') with
    install-time static dims resolved where statically known (DFA
    state counts via ``ops/regex_dfa``, the interner byte width) and
    conservatively defaulted otherwise;
  * **SSA intermediates** — per-node value+defined pairs with
    op-class liveness (a node's buffer lives from its definition to
    its last use; rule conjuncts pin their nodes to the final reduce),
    the per-program-point live sums kept symbolically so the peak is
    evaluated at any geometry;
  * **devpages residency** — the resident mask (old + new during the
    delta swap), the on-device page table, and the bounded
    ``(idx, signs)`` delta staging stream;
  * **per-shard totals** — resource-axis terms divide across the
    PR-11 PartitionPlan shard count (``bytes_at(..., n_shards=N)``).

``peak = resident + max-over-points(intermediates) + devpages`` is an
*over-approximation contract*: the certificate must never claim less
than the measured live-buffer high-water (validated on CPU against
``jax.live_arrays`` in tests, and against the actually-built binding
arrays by ``probe --memsurface``).  The worst-signature headline
evaluates the polynomial at the Stage-8 deployment caps
(``GATEKEEPER_MS_MAX_*`` — deliberately smaller than the Stage-7
compile-surface caps: those bound what may ever be *compiled*, these
bound what the fleet is *sized* to hold resident at once).

The install gate: ``GATEKEEPER_HBM_BUDGET=off|warn|strict`` with
``GATEKEEPER_HBM_BUDGET_BYTES`` (default 16 GiB).  A template whose
worst-signature peak exceeds the budget raises
``hbm_budget_exceeded`` (strict rejects the install into
``status.byPod[].errors``; warn counts and serves).  Certificates
persist as the eleventh snapshot tier ``ms`` so a warm restart
re-runs zero analyses.

Three consumers make the certificate load-bearing: the devpages
residency planner sizes its LRU resident set from the certified page
bytes (``enforce/devpages.ResidencyPlanner``), the webhook
micro-batcher caps batch formation at the largest certified rung
whose signature fits the remaining budget, and the audit sweep orders
kind dispatch so concurrent in-flight footprints stay under budget.

``GATEKEEPER_MEMSURFACE_TEST_UNDER=<Kind>`` is the deterministic test
seam: the analyzer deliberately under-claims for that kind (bypassing
memo and snapshot), proving end-to-end that the validation harness
catches an unsound certificate.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

from gatekeeper_tpu.utils.log import logger

log = logger("memsurface")

MS_VERSION = "ms-1"

# fresh analyses this process (mirrors compilesurface.analyses_run):
# the restart smoke asserts a warm process re-analyzes nothing
analyses_run = 0

_memo: dict[str, "MemorySurface"] = {}

# kind -> most recently published certificate
surfaces: dict[str, "MemorySurface"] = {}

# kind -> human reason, for templates whose worst-signature peak
# exceeds the installed budget
over_budget: dict[str, str] = {}


def mode() -> str:
    """off | warn | strict.  ``warn`` (default) certifies at install
    and *counts* budget breaches but serves anyway; ``strict``
    additionally rejects any install whose worst-signature peak
    exceeds ``GATEKEEPER_HBM_BUDGET_BYTES`` (``hbm_budget_exceeded``
    into ``status.byPod[].errors``); ``off`` disables the stage."""
    return os.environ.get("GATEKEEPER_HBM_BUDGET", "warn").strip().lower()


DEFAULT_BUDGET_BYTES = 16 << 30         # one v5e chip's HBM


def budget_bytes() -> int:
    try:
        return int(os.environ.get("GATEKEEPER_HBM_BUDGET_BYTES",
                                  DEFAULT_BUDGET_BYTES))
    except ValueError:
        return DEFAULT_BUDGET_BYTES


# Stage-8 deployment-geometry caps: the *resident* geometry the fleet
# is sized for, deliberately far below the Stage-7 compile-surface
# caps (GATEKEEPER_CS_MAX_ROWS=1<<22 bounds what may ever be compiled;
# a [c, r] mask at that geometry alone is 16 GiB — certifying "the
# worst compilable signature fits" would reject every budget).  The
# worst-signature headline and the install gate evaluate here.
_CAP_DEFAULTS = {
    "r": ("GATEKEEPER_MS_MAX_ROWS", 1 << 16),
    "c": ("GATEKEEPER_MS_MAX_CONSTRAINTS", 1 << 6),
    "t": ("GATEKEEPER_MS_MAX_TABLE", 1 << 14),
    "e": ("GATEKEEPER_MS_MAX_ELEMS", 1 << 4),
}

# conservative default for a static dim whose install-time size is not
# statically derivable from the template alone (constraint-set pad
# lengths, parametric-table value counts: they depend on the installed
# constraint parameters) — resolved exactly when the caller passes the
# built shapes
DEFAULT_STATIC_DIM = 64


def _cap(cls: str) -> int:
    name, dflt = _CAP_DEFAULTS[cls]
    try:
        return int(os.environ.get(name, dflt))
    except ValueError:
        return dflt


def _caps_sig() -> tuple:
    return tuple((cls, _cap(cls)) for cls in sorted(_CAP_DEFAULTS))


def cap_dims() -> dict:
    """The worst-signature evaluation point: every pad axis at its
    Stage-8 deployment cap."""
    return {cls: _cap(cls) for cls in _CAP_DEFAULTS}


@dataclasses.dataclass(frozen=True)
class MemorySurface:
    """One template's certified memory surface.

    ``bindings`` is the bound-array byte model: one entry per
    materializable array as ``(name, dims, itemsize)`` where each dim
    is an axis-class char ('r'/'c'/'t'/'e') or a resolved static int.
    ``points`` is the intermediate-liveness model: per program point,
    the live SSA terms as ``((axes, per_elem_bytes), ...)`` with axes
    a subset-tuple of ('c','r','e').  ``has_r`` marks a resource axis
    (the devpages residency terms apply).  All byte queries go through
    :meth:`bytes_at`; nothing here is pre-evaluated, so one
    certificate serves every geometry, shard count, and budget."""

    kind: str
    digest: str
    bounded: bool
    reason: str | None
    bindings: tuple          # ((name, (dim, ...), itemsize), ...)
    points: tuple            # (((axes, per_elem_bytes), ...), ...)
    has_r: bool
    scalar_pin: bool = False
    version: str = MS_VERSION

    # -- evaluation -------------------------------------------------

    def _dim(self, d, dims: dict) -> int:
        if isinstance(d, str):
            return int(dims.get(d, _cap(d)))
        return int(d) if d else DEFAULT_STATIC_DIM

    def resident_bytes(self, dims: dict, shapes: dict | None = None,
                       n_shards: int = 1) -> int:
        """Bound-array bytes at a geometry.  ``shapes`` (name -> shape
        tuple of the actually-built arrays) overrides the model where
        present — exact static dims, exact pads.  Resource-axis arrays
        divide across ``n_shards`` (ceil: padding replicates)."""
        total = 0
        for name, dcls, itemsize in self.bindings:
            if shapes is not None and name in shapes:
                n = 1
                for v in shapes[name]:
                    n *= int(v)
                nbytes = n * itemsize
                sharded = any(isinstance(d, str) and d == "r"
                              for d in dcls)
            else:
                n = 1
                sharded = False
                for d in dcls:
                    n *= self._dim(d, dims)
                    sharded = sharded or d == "r"
                nbytes = n * itemsize
            if sharded and n_shards > 1:
                nbytes = -(-nbytes // n_shards)
            total += nbytes
        return total

    def transient_bytes(self, dims: dict, n_shards: int = 1) -> int:
        """Peak live SSA-intermediate bytes: the max over program
        points of the live value+defined pairs.  Every intermediate
        carries the full evaluation lattice, so all terms shard along
        the resource axis when present."""
        peak = 0
        for terms in self.points:
            live = 0
            for axes, per_elem in terms:
                n = per_elem
                for ax in axes:
                    n *= self._dim(ax, dims)
                if n_shards > 1 and "r" in axes:
                    n = -(-n // n_shards)
                live += n
            peak = max(peak, live)
        return peak

    def devpages_bytes(self, dims: dict, delta_k: int | None = None,
                       n_shards: int = 1) -> int:
        """Devpages residency terms: the resident mask twice (old and
        new coexist across the delta swap), the on-device page table,
        and the compact (idx, signs) delta staging stream at width
        ``delta_k`` (its ladder cap when unspecified)."""
        if not self.has_r:
            return 0
        c = self._dim("c", dims)
        r = self._dim("r", dims)
        masks = 2 * c * r * 1                    # old + new bool masks
        pt = r * 4                               # int32 page table
        if n_shards > 1:
            masks = -(-masks // n_shards)
            pt = -(-pt // n_shards)
        if delta_k is None:
            delta_k = c * r                      # the overflow cap
        return masks + pt + delta_k * 5          # idx int32 + signs bool

    def peak_bytes(self, dims: dict | None = None,
                   shapes: dict | None = None,
                   delta_k: int | None = None,
                   n_shards: int = 1,
                   devpages: bool = True) -> int:
        """The certificate's bottom line: conservative peak live bytes
        for one sweep of this template at a geometry.  ``dims``
        defaults to the Stage-8 caps (the worst certified signature);
        pass the actual pads (and ``shapes``) to evaluate a live
        deployment."""
        if self.scalar_pin:
            return 0
        dims = dims if dims is not None else cap_dims()
        total = self.resident_bytes(dims, shapes=shapes,
                                    n_shards=n_shards)
        total += self.transient_bytes(dims, n_shards=n_shards)
        if devpages:
            total += self.devpages_bytes(dims, delta_k=delta_k,
                                         n_shards=n_shards)
        return total


def surface_digest(lowered) -> str:
    """Certificate key: program cache_key + pad-geometry version +
    Stage-8 caps.  Any geometry or model change invalidates persisted
    certificates by key mismatch."""
    from gatekeeper_tpu.analysis import footprint
    from gatekeeper_tpu.ir import prep as _prep
    return hashlib.sha256(repr((
        MS_VERSION, _prep.PAD_GEOMETRY_VERSION, _caps_sig(),
        repr(lowered.program.cache_key()),
        repr(footprint._spec_sig(lowered.spec)),
    )).encode()).hexdigest()


# ---------------------------------------------------------------------------
# the byte model: bound arrays


# per-row bytes by column mode (matches the dtypes build_bindings
# materializes: num/len -> .v float32 + .p bool; str/val -> int32 ids,
# counted at full width even when the narrow-transfer path ships them
# smaller — over-approximation is the contract; present/truthy -> bool)
_MODE_BYTES = {"num": 5, "len": 5, "str": 4, "val": 4,
               "present": 1, "truthy": 1}


def _dfa_states(pattern: str) -> int:
    """Exact DFA state count for a constant pattern — the one static
    dim that is fully derivable from the template alone."""
    from gatekeeper_tpu.ops import regex_dfa
    dfa = regex_dfa.compile_dfa(pattern)
    if dfa is None:
        return 0
    return int(len(dfa.accept))


def _spec_bindings(spec) -> list[tuple]:
    """The bound-array byte model: every array build_bindings can
    materialize for this spec, as (name, dims, itemsize) with dims the
    axis-class chars of ir/prep.binding_dim_classes and static dims
    resolved where the template alone determines them (0 = unknown,
    defaulted conservatively at evaluation)."""
    out: list[tuple] = [
        ("__alive__", ("r",), 1),
        ("__match__", ("c", "r"), 1),
        ("__rank__", ("r",), 4),
        ("__pagetable__", ("r",), 4),
        # build_bindings materializes the constraint-validity column
        # unconditionally (all-valid when no cvalid_fns)
        ("__cvalid__", ("c",), 1),
    ]
    for ax, _base in getattr(spec, "axes", ()):
        out.append((f"__elem__:{ax}", ("r", "e"), 1))
    for r in getattr(spec, "r_cols", ()):
        out.append((r.name, ("r",), _MODE_BYTES.get(r.mode, 5)))
    for r in getattr(spec, "e_cols", ()):
        out.append((r.name, ("r", "e"), _MODE_BYTES.get(r.mode, 5)))
    for r in getattr(spec, "tables", ()):
        out.append((f"{r.name}.ok", ("t",), 1))
        out.append((f"{r.name}.v", ("t",), 4))
    for r in getattr(spec, "ptables", ()):
        out.append((f"{r.name}.any", ("c", 0), 1))
        out.append((f"{r.name}.all", ("c", 0), 1))
        out.append((f"{r.name}.vmap", ("t",), 4))
    for r in getattr(spec, "csets", ()):
        out.append((r.name, ("c", 0), 1))
        out.append((f"{r.name}.vmap", ("t",), 4))
    for r in getattr(spec, "cvals", ()):
        out.append((r.name, ("c",), 5))
    for r in getattr(spec, "membs", ()):
        out.append((r.name, (0, "r"), 1))
    for r in getattr(spec, "elem_keys", ()):
        out.append((r.name, (0, "r", "e"), 1))
    for r in getattr(spec, "keyed_vals", ()):
        out.append((f"{r.name}.kv", (0, "r"), 4))
        out.append((f"{r.name}.sel", ("c",), 4))
    for r in getattr(spec, "inv_joins", ()):
        # the host-built r_bool column plus the in-jit join input
        # records the devpages path stages (src/inv/sel/names, int32)
        out.append((r.name, ("r",), 1))
        for part in ("src", "inv", "sel", "names"):
            out.append((f"r:ij.{r.name}.{part}", ("r",), 4))
    for r in getattr(spec, "dfas", ()):
        s = _dfa_states(r.pattern)
        out.append((f"{r.name}.trans", (s, 256), 4))
        out.append((f"{r.name}.accept", (s,), 1))
        out.append((f"{r.name}.xv", ("t",), 1))
    if getattr(spec, "dfas", ()):
        from gatekeeper_tpu.store.interner import Interner
        width = Interner().max_str_len
        out.append(("__strbytes__", ("t", width), 1))
        out.append(("__strdfaok__", ("t",), 1))
    return out


# ---------------------------------------------------------------------------
# the byte model: SSA intermediates via op-class liveness


# ops whose value array is wider than a bool mask (float32/int32);
# everything else evaluates to a bool value.  The defined mask is a
# bool beside either.
_WIDE_OPS = frozenset({"const", "input", "table", "keyed_val",
                       "arith", "count_e"})


def _node_points(program) -> list[tuple]:
    """Per-program-point live intermediate terms under last-use
    liveness.  A node's (defined, value) pair materializes at its
    definition point and frees after its last consumer; rule conjuncts
    stay live through the final reduce, which also carries the output
    violation mask.  Dead (unreachable) nodes never allocate."""
    from gatekeeper_tpu.analysis.costmodel import (node_axes,
                                                   reachable_nodes)
    axes = node_axes(program)
    reach = reachable_nodes(program)
    n = len(program.nodes)
    last_use = {}
    for i in sorted(reach):
        last_use[i] = i
        for a in program.nodes[i].args:
            if a in last_use:
                last_use[a] = max(last_use[a], i)
    for rule in program.rules:
        for ci in rule.conjuncts:
            if ci in last_use:
                last_use[ci] = n                # live through the reduce
    points: list[tuple] = []
    live: dict[int, tuple] = {}
    for i in sorted(reach):
        c, r, e = axes[i]
        ax = tuple(s for s, on in (("c", c), ("r", r), ("e", e)) if on)
        per_elem = (4 if program.nodes[i].op in _WIDE_OPS else 1) + 1
        live[i] = (ax, per_elem)
        points.append(tuple(t for j, t in sorted(live.items())
                            if last_use[j] >= i))
        live = {j: t for j, t in live.items() if last_use[j] > i}
    # the final reduce: every conjunct mask AND the [c, r] output
    final = [t for j, t in sorted(live.items())]
    final.append((("c", "r"), 1))
    points.append(tuple(final))
    return points


def _test_under_kinds() -> frozenset:
    raw = os.environ.get("GATEKEEPER_MEMSURFACE_TEST_UNDER", "")
    return frozenset(k for k in raw.split(",") if k)


def analyze(kind: str, lowered) -> MemorySurface:
    """The Stage-8 abstract interpretation: compose the bound-array
    byte model with the liveness-based intermediate model into one
    symbolic certificate.  The TEST_UNDER seam deliberately drops the
    intermediates and scales every binding down 64x — an unsound
    under-claim the validation harness must catch."""
    digest = surface_digest(lowered)
    bindings = tuple(_spec_bindings(lowered.spec))
    if kind in _test_under_kinds():
        # itemsize 0: the seeded certificate claims (nearly) nothing
        shrunk = tuple((name, dcls, 0) for name, dcls, _it in bindings)
        return MemorySurface(
            kind=kind, digest=digest, bounded=True,
            reason="deliberately under-claimed (test seam)",
            bindings=shrunk, points=(), has_r=True)
    points = tuple(_node_points(lowered.program))
    has_r = any("r" in [d for d in dcls if isinstance(d, str)]
                for _nm, dcls, _it in bindings)
    return MemorySurface(
        kind=kind, digest=digest, bounded=True, reason=None,
        bindings=bindings, points=points, has_r=has_r)


def scalar_surface(kind: str) -> MemorySurface:
    """The trivial certificate of a scalar-pinned template: no device
    program, no device bytes — vacuously within any budget."""
    return MemorySurface(
        kind=kind, digest=f"scalar:{kind}", bounded=True, reason=None,
        bindings=(), points=(), has_r=False, scalar_pin=True)


# ---------------------------------------------------------------------------
# memoized entry point + the budget verdict


def certify(kind: str, compiled, lowered) -> MemorySurface:
    """Memoized/snapshot-backed entry point the engine and probe use.
    Certificates persist in the snapshot "ms" tier, so a warm restart
    re-runs zero analyses.  The TEST_UNDER seam bypasses memo and
    snapshot — the deliberately unsound certificate must reach the
    caller, not a cached honest one."""
    global analyses_run
    digest = surface_digest(lowered)
    seam = kind in _test_under_kinds()
    if not seam:
        cached = _memo.get(digest)
        if cached is not None:
            _publish(kind, cached)
            return cached
        from gatekeeper_tpu.resilience import snapshot as _snap
        hit = _snap.load_memsurface(digest)     # 1-tuple or None
        if hit is not None and isinstance(hit[0], MemorySurface) \
                and hit[0].version == MS_VERSION:
            _memo[digest] = hit[0]
            _publish(kind, hit[0])
            return hit[0]

    cert = analyze(kind, lowered)
    analyses_run += 1
    if not seam:
        _memo[digest] = cert
        from gatekeeper_tpu.resilience import snapshot as _snap
        _snap.save_memsurface(digest, cert)
    _publish(kind, cert)
    return cert


def _publish(kind: str, cert: MemorySurface) -> None:
    surfaces[kind] = cert
    reason = budget_reason(cert)
    if reason is None:
        over_budget.pop(kind, None)
    else:
        over_budget[kind] = reason


def budget_reason(cert: MemorySurface) -> str | None:
    """The ``hbm_budget_exceeded`` verdict: non-None when the
    certificate's worst-signature peak exceeds the installed budget."""
    if cert.scalar_pin:
        return None
    peak = cert.peak_bytes()
    budget = budget_bytes()
    if peak <= budget:
        return None
    return (f"hbm_budget_exceeded: worst-signature peak "
            f"{peak / (1 << 20):.0f} MiB exceeds the "
            f"{budget / (1 << 20):.0f} MiB budget "
            f"(GATEKEEPER_HBM_BUDGET_BYTES)")


def surface_for(kind: str) -> MemorySurface | None:
    """The most recently published certificate for a kind, or None
    when not yet analyzed."""
    return surfaces.get(kind)


def policy_set_bytes(dims: dict | None = None,
                     certs: dict | None = None) -> int:
    """Roll the per-template peaks up to the whole installed set: the
    sum of every certificate's peak at a geometry — templates coexist
    on device (the identity-keyed binding caches keep every kind's
    arrays resident across a sweep), so the set-level claim is the
    sum, not the max."""
    certs = certs if certs is not None else surfaces
    return sum(c.peak_bytes(dims) for c in certs.values()
               if isinstance(c, MemorySurface))
