"""Diagnostic records for the static-analysis pass.

A :class:`Diagnostic` is the unit finding emitted by both analysis
stages (the Rego front-end vetter and the lowered-IR verifier).  Codes
follow the reference gatekeeper's ``status.byPod[].errors`` shape
(``rego_parse_error``, ``rego_type_error``, ...): a short snake_case
string keyed by family prefix — ``rego_*`` for Stage-1 AST findings,
``ir_*`` for Stage-2 device-program findings — so a controller can
forward a finding into status unchanged (see
controllers/constrainttemplate.py).

Severity is two-valued: ``error`` findings reject the template at
install time; ``warning`` findings are recorded but admit.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from gatekeeper_tpu.errors import Location

ERROR = "error"
WARNING = "warning"


@dataclass(frozen=True)
class Diagnostic:
    code: str
    severity: str           # "error" | "warning"
    message: str
    location: Location = field(default_factory=Location)

    def format(self) -> str:
        """``file:row:col severity code: message`` — the probe --lint
        output line (file part dropped when unset)."""
        loc = self.location
        pos = f"{loc.row}:{loc.col}"
        if loc.file:
            pos = f"{loc.file}:{pos}"
        return f"{pos} {self.severity} {self.code}: {self.message}"


def errors(diags: list[Diagnostic]) -> list[Diagnostic]:
    return [d for d in diags if d.severity == ERROR]


def has_errors(diags: list[Diagnostic]) -> bool:
    return any(d.severity == ERROR for d in diags)


def format_all(diags: list[Diagnostic]) -> str:
    return "\n".join(d.format() for d in diags)
