"""Stage-6 sharding certifier: static partition plans for the sweep.

Stage 5 (:mod:`.footprint`) proves *which* templates are row-local —
shard-eligible in principle.  This stage proves *how* each lowered
program partitions under a resource-axis split: an abstract
interpreter propagates a sharding state (``row-sharded`` |
``replicated``) through every SSA value of the lowered IR and emits a
per-template :class:`PartitionPlan` certificate naming

  * the per-node sharding states (elementwise/compare ops stay
    sharded; gathers into replicated param/provider tables stay
    sharded because only the *index* is row-partitioned; element-axis
    reductions stay per-row);
  * the named collectives the serving reduction needs — the per-shard
    violation counts are a partial-reduce closed by one
    ``all_reduce`` over ``r``, and the capped top-k rows/scores need
    an ``all_gather`` each (exactly the psum + two all_gathers in
    ``parallel.sharding._topk_local_step``);
  * the pad-to-multiple-of-shard-count constraints and the per-shard
    H2D layout: each binding's partition axes per
    ``ir.prep.binding_axes``.

Anything consuming a CROSS-ROW footprint (the inventory join) is
certified *ineligible* with the footprint's reason — its verdict
reads other rows, so a row split changes semantics.

Plans are *validated, not trusted*: ``validate_plan`` executes the
plan on a 2-shard simulated mesh (``shard_map`` over CPU devices)
across the Stage-4 small-model worlds and demands a bit-identical
violation mask plus count/top-k parity vs the unsharded oracle.  Any
difference is a ShardPlanViolation; under ``GATEKEEPER_SHARDPLAN=
strict`` the engine pins the template to the replicated path (install
never fails on this stage).  Validated plans persist in the snapshot
"sp" tier — the seventh — so a warm restart re-runs zero analyses.

The engine consumes plans for the plan-driven simulated sweep behind
``GATEKEEPER_SHARDS=N``: eligible kinds run sharded, ineligible ones
pin to the replicated (single-device) path, bit-identically.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

import numpy as np

from gatekeeper_tpu.utils.log import logger

log = logger("shardplan")

SHARDPLAN_VERSION = "sp-1"

# fresh analyses this process (mirrors footprint.analyses_run): the
# restart smoke asserts a warm process re-analyzes nothing
analyses_run = 0

_memo: dict[str, "PartitionPlan"] = {}

# kind -> most recently published plan (memoized or not)
plans: dict[str, "PartitionPlan"] = {}

# kind -> human reason, for templates whose plans are shard-ineligible.
# Consumed by the reconciler (status.byPod[] finding) and the probe.
ineligible: dict[str, str] = {}

# kind -> violations from the most recent strict-mode validation
violations: dict[str, list["ShardPlanViolation"]] = {}

SHARDED = "row-sharded"
REPLICATED = "replicated"

# the serving reduction over a row-sharded verdict matrix: per-shard
# counts are a partial-reduce closed by one all_reduce; the capped
# top-k needs its rows and scores gathered (see _topk_local_step)
_SERVING_COLLECTIVES: tuple[tuple[str, str, str], ...] = (
    ("all_reduce", "r", "violation_counts"),
    ("all_gather", "r", "topk_rows"),
    ("all_gather", "r", "topk_scores"),
)

# pad_bindings_for_mesh's contract, stated as certificate constraints
_PAD_CONSTRAINTS: tuple[str, ...] = (
    "r_pad % r_shards == 0",
    "c_pad % c_shards == 0",
    "fill:int32=-1",
    "fill:other=0",
)

# framework bindings the prepped arrays always carry alongside the
# spec-derived ones (engine/veval gating + rank order)
_FRAMEWORK_BINDINGS: tuple[str, ...] = (
    "__match__", "__alive__", "__rank__", "__cvalid__",
)


def mode() -> str:
    """off | warn | strict.  ``warn`` (default) runs the static
    analysis at install and lets the sharded sweep consume plans;
    ``strict`` additionally executes every eligible plan on a 2-shard
    simulated mesh at install and pins any invalid plan to the
    replicated path; ``off`` disables analysis and plan gating (the
    oracle: everything shards exactly as before this stage)."""
    return os.environ.get("GATEKEEPER_SHARDPLAN", "warn").strip().lower()


def validation_budget() -> int:
    return int(os.environ.get("GATEKEEPER_SHARDPLAN_MODELS", "16"))


# ---------------------------------------------------------------------------
# results


@dataclasses.dataclass(frozen=True)
class ShardPlanViolation:
    """Simulated-mesh validation found a divergence between the plan's
    sharded execution and the unsharded oracle — an analysis bug (or a
    deliberately broken plan via the TEST_BREAK seam)."""

    kind: str
    note: str = ""

    def format(self) -> str:
        return f"{self.kind}: sharded execution diverged ({self.note})"


@dataclasses.dataclass(frozen=True)
class PartitionPlan:
    """Per-template sharding certificate under a resource-axis split.

    ``node_shardings`` records the abstract state of every reachable
    IR node; ``collectives`` the (op, axis, operand) reductions the
    serving step needs; ``padding`` the divisibility/fill constraints
    each shard's H2D layout must satisfy; ``layout`` the per-binding
    partition axes (None = replicated dim)."""

    kind: str
    digest: str
    eligible: bool
    reason: str = ""
    node_shardings: tuple[tuple[int, str], ...] = ()
    collectives: tuple[tuple[str, str, str], ...] = ()
    padding: tuple[str, ...] = ()
    layout: tuple[tuple[str, tuple], ...] = ()
    validated: bool = False
    shards_validated: int = 0
    version: str = SHARDPLAN_VERSION


# ---------------------------------------------------------------------------
# digest (snapshot key)


def shardplan_digest(lowered) -> str:
    from gatekeeper_tpu.analysis.footprint import _spec_sig
    parts = (SHARDPLAN_VERSION, repr(lowered.program.cache_key()),
             repr(_spec_sig(lowered.spec)))
    return hashlib.sha256("\x1f".join(parts).encode()).hexdigest()


# ---------------------------------------------------------------------------
# the abstract interpreter


def _cross_row_reason(kind: str, name: str, ij) -> str:
    """Prefer the footprint's published reason (put_template runs
    Stage 5 first); self-derive the identical text otherwise."""
    from gatekeeper_tpu.analysis import footprint
    got = footprint.locality_for(kind)
    if got:
        return got
    return (f"inventory join {name}: ∃ other {ij.kind} with "
            f"{'.'.join(ij.inv_path)} == this {'.'.join(ij.src_path)}")


def analyze(kind: str, lowered) -> PartitionPlan:
    """Propagate the sharding lattice through the nodes reachable from
    the rule conjuncts and derive the partition plan.  The analysis is
    per-op: inputs take their binding's partition axes (``r`` present
    → row-sharded); table/ptable gathers follow their index operand
    (the table itself is replicated); element-axis reductions stay
    per-row; everything else joins its args (any sharded operand makes
    the result sharded).  No IR op reduces over ``r`` — the only
    cross-shard dependency inside a program is the inventory join,
    which makes the whole template ineligible."""
    from gatekeeper_tpu.analysis.costmodel import reachable_nodes
    from gatekeeper_tpu.ir.prep import binding_axes

    spec = lowered.spec
    prog = lowered.program
    by_ij = {j.name: j for j in spec.inv_joins}
    digest = shardplan_digest(lowered)

    def ineligible_plan(reason: str) -> PartitionPlan:
        return PartitionPlan(kind=kind, digest=digest, eligible=False,
                             reason=reason, padding=_PAD_CONSTRAINTS)

    # every H2D binding must resolve to partition axes, or the shard
    # layout is undefined for it
    binding_names = set(_FRAMEWORK_BINDINGS)
    for group in (spec.r_cols, spec.e_cols, spec.tables, spec.ptables,
                  spec.membs, spec.keyed_vals, spec.elem_keys,
                  spec.inv_joins, getattr(spec, "dfas", ())):
        binding_names.update(x.name for x in group)
    if getattr(spec, "dfas", ()):
        # in-program DFA framework arrays: the packed interner bytes and
        # the device-eligibility mask ride along with every dfa table
        binding_names.update(("__strbytes__", "__strdfaok__"))
    layout: list[tuple[str, tuple]] = []
    for name in sorted(binding_names):
        try:
            layout.append((name, tuple(binding_axes(name))))
        except ValueError:
            return ineligible_plan(f"unpartitionable binding {name}: "
                                   f"no known shard layout")

    sharded_by_name = {nm: "r" in axes for nm, axes in layout}
    states: dict[int, str] = {}
    shardings: list[tuple[int, str]] = []
    for i in sorted(reachable_nodes(prog)):
        n = prog.nodes[i]
        op = n.op
        if op == "const":
            st = REPLICATED
        elif op == "input":
            name, _ikind = n.meta
            ij = by_ij.get(name)
            if ij is not None:
                # the inv-join column is computed from OTHER rows: a
                # row split would hide matches living on other shards
                return ineligible_plan(_cross_row_reason(kind, name, ij))
            st = SHARDED if sharded_by_name.get(name, True) else REPLICATED
        elif op in ("keyed_val", "elem_keys_missing",
                    "cset_not_subset_memb", "cset_subset_memb"):
            # per-(constraint, row) lookups/matrices: row-partitioned
            st = SHARDED
        else:
            # table/ptable gathers follow their (row-sharded) index;
            # any_e/all_e/count_e reduce the ELEMENT axis, not r;
            # cmp/in_cset/and/or/not/arith are elementwise — all join
            st = REPLICATED
            for a in n.args:
                if states.get(a) == SHARDED:
                    st = SHARDED
                    break
        states[i] = st
        shardings.append((i, st))

    return PartitionPlan(kind=kind, digest=digest, eligible=True,
                         node_shardings=tuple(shardings),
                         collectives=_SERVING_COLLECTIVES,
                         padding=_PAD_CONSTRAINTS,
                         layout=tuple(layout))


# ---------------------------------------------------------------------------
# simulated-mesh validation (plans are validated, not trusted)


def make_sim_mesh(n_shards: int):
    """Row-only (1, n) simulated mesh — a pure resource-axis partition
    matching the plan semantics — over the first ``n_shards`` local
    devices.  Lives in parallel.sharding; re-exported here for the
    probe/tests."""
    from gatekeeper_tpu.parallel.sharding import make_sim_mesh as _m
    return _m(n_shards)


def _break_kinds() -> set[str]:
    raw = os.environ.get("GATEKEEPER_SHARDPLAN_TEST_BREAK", "")
    return {t.strip() for t in raw.split(",") if t.strip()}


_skip_logged = False


def validate_plan(kind: str, compiled, lowered, plan: PartitionPlan,
                  constraints: list[dict] | None = None,
                  budget: int | None = None
                  ) -> tuple[PartitionPlan, list[ShardPlanViolation]]:
    """Execute the plan on a 2-shard simulated mesh over the smallmodel
    worlds and demand (a) a bit-identical violation mask and (b)
    count/top-k parity vs the unsharded oracle.  Returns the plan
    (stamped validated on success) plus any violations.  With fewer
    than 2 local devices the validation soft-skips: the plan stays
    unvalidated but is NOT a violation (a 1-device strict process must
    not pin the whole library)."""
    global _skip_logged
    import jax

    from gatekeeper_tpu.analysis import transval
    from gatekeeper_tpu.analysis.smallmodel import (derive_plan,
                                                    enumerate_models)

    if not plan.eligible:
        return plan, []
    if len(jax.devices()) < 2:
        if not _skip_logged:
            _skip_logged = True
            log.warning("shardplan validation skipped: fewer than 2 "
                        "devices (set jax_num_cpu_devices=2 for the "
                        "simulated mesh)")
        return plan, []

    from gatekeeper_tpu.parallel.sharding import (binding_spec,
                                                  make_sharded_mask_fn,
                                                  make_sim_mesh,
                                                  pad_bindings_for_mesh,
                                                  run_sharded_audit)

    cons = transval.expand_constraints(kind, constraints)
    plan_m = derive_plan(lowered, cons, module=compiled.module)
    models = enumerate_models(plan_m, budget or validation_budget())
    all_res = [obj for m in models for obj in m.resources]
    if not all_res:
        return plan, []
    st, _rows, _handler = transval._world_state(all_res)
    base_mask, bindings = transval._device_mask(lowered, st, cons)

    mesh = make_sim_mesh(2)
    b = pad_bindings_for_mesh(bindings, mesh.shape["c"], mesh.shape["r"])
    names = tuple(sorted(b.arrays))
    specs = {nm: binding_spec(nm, b.arrays[nm]) for nm in names}
    fn = make_sharded_mask_fn(lowered.program, names, specs, mesh)
    with mesh:
        m = fn(tuple(b.arrays[nm] for nm in names))
    mask2 = np.asarray(m)[:base_mask.shape[0], :base_mask.shape[1]]
    if kind in _break_kinds() and mask2.size:
        # fault-injection seam: flip one cell of the sharded mask so
        # the validator provably catches a divergent plan end-to-end
        mask2 = mask2.copy()
        mask2.flat[0] = ~mask2.flat[0]
        log.warning("shardplan deliberately broken (test seam)",
                    kind=kind)

    out: list[ShardPlanViolation] = []
    if mask2.shape != base_mask.shape \
            or not np.array_equal(mask2, base_mask):
        diff = int(np.sum(mask2 != base_mask)) \
            if mask2.shape == base_mask.shape else -1
        out.append(ShardPlanViolation(
            kind=kind,
            note=f"2-shard mask mismatch vs oracle over "
                 f"{len(models)} model world(s), {diff} cell(s)"))
    else:
        counts, rows, valid = run_sharded_audit(
            lowered.program, bindings, mesh, k=20)
        for ci in range(base_mask.shape[0]):
            want = int(base_mask[ci].sum())
            got_rows = {int(r) for r, v in zip(rows[ci], valid[ci]) if v}
            viol_rows = set(np.nonzero(base_mask[ci])[0].tolist())
            if int(counts[ci]) != want or not got_rows <= viol_rows:
                out.append(ShardPlanViolation(
                    kind=kind,
                    note=f"top-k parity: constraint {ci} counts "
                         f"{int(counts[ci])} vs {want}"))
                break
    if out:
        return dataclasses.replace(plan, validated=False), out
    return dataclasses.replace(plan, validated=True,
                               shards_validated=2), []


# ---------------------------------------------------------------------------
# memoized entry point


def certify(kind: str, compiled, lowered,
            constraints: list[dict] | None = None) -> PartitionPlan:
    """Memoized/snapshot-backed entry point the engine and probe use.

    The static analysis always runs (mode "warn"); under "strict" the
    plan is additionally executed on the 2-shard simulated mesh and
    any violation is recorded in ``violations[kind]`` (the engine then
    pins the kind to the replicated path — install never fails on this
    stage).  Validated plans persist in the snapshot "sp" tier, so a
    warm restart re-runs zero analyses.  The TEST_BREAK seam bypasses
    both memo and snapshot — a broken plan must reach the validator,
    not a cached honest one."""
    global analyses_run
    digest = shardplan_digest(lowered)
    seam = kind in _break_kinds()
    if not seam:
        cached = _memo.get(digest)
        if cached is not None:
            _publish(kind, cached)
            return cached
        from gatekeeper_tpu.resilience import snapshot as _snap
        hit = _snap.load_shardplan(digest)     # 1-tuple or None (miss)
        if hit is not None and isinstance(hit[0], PartitionPlan) \
                and hit[0].version == SHARDPLAN_VERSION:
            _memo[digest] = hit[0]
            _publish(kind, hit[0])
            return hit[0]

    plan = analyze(kind, lowered)
    analyses_run += 1
    found: list[ShardPlanViolation] = []
    if mode() == "strict":
        plan, found = validate_plan(kind, compiled, lowered, plan,
                                    constraints=constraints)
    if found:
        violations[kind] = found
        for v in found:
            log.warning("shardplan violation", kind=kind, note=v.note)
    else:
        violations.pop(kind, None)
    if not seam and not found:
        _memo[digest] = plan
        from gatekeeper_tpu.resilience import snapshot as _snap
        _snap.save_shardplan(digest, plan)
    _publish(kind, plan)
    return plan


def _publish(kind: str, plan: PartitionPlan) -> None:
    plans[kind] = plan
    if plan.eligible:
        ineligible.pop(kind, None)
    else:
        ineligible[kind] = plan.reason or "shard-ineligible"


def plan_for(kind: str) -> PartitionPlan | None:
    """The most recently published plan for a kind, or None when not
    yet analyzed."""
    return plans.get(kind)


def ineligible_for(kind: str) -> str | None:
    """The shard-ineligibility reason for a kind, or None when
    eligible (or not yet analyzed)."""
    return ineligible.get(kind)


def violations_for(kind: str) -> list[ShardPlanViolation]:
    return violations.get(kind, [])
