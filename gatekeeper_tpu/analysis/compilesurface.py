"""Stage-7 compile-surface certifier: the finite set of jit signatures.

Stages 4-6 certify that a lowered program computes the right verdicts,
which columns it reads, and how it shards.  None of them bounds the
*compile surface*: the set of static shape signatures the jitted
programs can ever be entered with.  Every distinct signature is one
XLA trace + compile — and a signature arriving mid-traffic (shape
drift past a pad bucket, an oversized review batch) is a retrace storm
that blows the p99 budget (the jax_driver "recompile at the next
bucket, then re-dispatch" path).

This stage closes that hole statically.  An abstract interpreter over
the lowered spec's binding requests maps every bound array dim to a
pad-geometry *generator* via :func:`ir.prep.binding_dim_classes`:

  * ``r`` / ``c`` — the ``bucket()`` power-of-two ladders of
    ``audit_pads`` (resource and constraint axes);
  * ``t`` — the ``interner_bucket()`` headroom ladder (distinct
    strings);
  * ``e`` — the element-axis ``bucket(·, minimum=2)`` ladder;
  * ``static`` — install-time constants (constraint key counts, DFA
    ``[n_states, 256]`` transition tables, the interner byte width):
    exactly one value per installed policy set.

Each input-driven axis is a finite ladder only because deployment caps
bound it (``GATEKEEPER_CS_MAX_*``); the composition of the ladders is
the :class:`CompileSurface` certificate — the complete signature set,
with ``n_signatures`` = the product of the ladder lengths (times the
devpages delta-width rungs for kinds with a resource axis).  A binding
whose dims cannot be mapped to a generator makes the surface
*unbounded*: the certificate is rejected with a
``compile_surface_unbounded`` diagnostic and the kind is excluded from
AOT precompilation and retrace gating.

Certificates are consumed in three places:

  * ``JaxDriver.precompile()`` AOT-compiles the certified signatures
    of the current geometry at install/warm-restart (the ``cs``
    snapshot tier records both the certificates and the precompiled
    geometry stamp, so a warm restart issues zero AOT compiles);
  * the webhook micro-batcher shrinks deadline-pressed batches along
    the certified r-ladder rungs instead of halving blindly (halving
    50 -> 25 keeps the same padded signature; stepping 50 -> 32 -> 16
    actually changes the executable the cost model priced);
  * a runtime retrace sentinel at the executor's jit cache-miss seam
    counts any dispatch whose signature falls outside the certificate
    (``retrace_uncertified_total``), flight-records it, and under
    ``GATEKEEPER_COMPILE_SURFACE=strict`` refuses the dispatch with
    :class:`UncertifiedRetrace` instead of compiling mid-traffic.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os

from gatekeeper_tpu.errors import EvalError
from gatekeeper_tpu.utils.log import logger

log = logger("compilesurface")

CS_VERSION = "cs-1"

# fresh analyses this process (mirrors shardplan.analyses_run): the
# restart smoke asserts a warm process re-analyzes nothing
analyses_run = 0

# AOT executable compiles issued by JaxDriver.precompile() this
# process: a warm restart whose geometry stamp is in the cs tier must
# issue zero (the executables come back through the persistent compile
# cache on first dispatch instead of a startup compile storm)
precompiles_run = 0

# dispatches whose signature fell outside the installed certificates
# (module-wide twin of the driver's retrace_uncertified_total metric)
uncertified_total = 0

_memo: dict[str, "CompileSurface"] = {}

# kind -> most recently published certificate
surfaces: dict[str, "CompileSurface"] = {}

# kind -> human reason, for templates whose surface is unbounded.
unbounded: dict[str, str] = {}

# program cache_key -> bounded certificate, for the dispatch-time
# sentinel (only bounded surfaces are guardable: an unbounded one
# makes no membership claim)
_registry: dict = {}


def mode() -> str:
    """off | warn | strict.  ``warn`` (default) certifies at install,
    drives AOT precompilation, and *counts* uncertified dispatches but
    serves them via the lazy-recompile fallback; ``strict``
    additionally refuses any dispatch outside the certificate
    (:class:`UncertifiedRetrace`); ``off`` disables the stage."""
    return os.environ.get("GATEKEEPER_COMPILE_SURFACE",
                          "warn").strip().lower()


class UncertifiedRetrace(EvalError):
    """strict-mode refusal: a dispatch demanded a jit signature outside
    the installed CompileSurface certificate.  Serving it would compile
    a fresh executable mid-traffic — the exact retrace storm the
    certificate exists to rule out."""


# deployment caps that make the input-driven ladders finite.  A store,
# constraint set, interner, or element list past its cap would demand a
# signature outside every certificate — which is the point: the
# operator states the geometry the fleet is sized for, and anything
# beyond it is a certifiable capacity event, not a silent retrace.
_CAP_DEFAULTS = {
    "r": ("GATEKEEPER_CS_MAX_ROWS", 1 << 22),
    "c": ("GATEKEEPER_CS_MAX_CONSTRAINTS", 1 << 12),
    "t": ("GATEKEEPER_CS_MAX_TABLE", 1 << 22),
    "e": ("GATEKEEPER_CS_MAX_ELEMS", 1 << 16),
}

# canonical ladder minimums (ir/prep.py padding formulas: audit_pads
# bucket minimums, interner_bucket floor, element bucket minimum=2)
_LADDER_MIN = {"r": 8, "c": 4, "t": 8, "e": 2}


def _cap(cls: str) -> int:
    name, dflt = _CAP_DEFAULTS[cls]
    try:
        return int(os.environ.get(name, dflt))
    except ValueError:
        return dflt


def _caps_sig() -> tuple:
    return tuple((cls, _cap(cls)) for cls in sorted(_CAP_DEFAULTS))


@dataclasses.dataclass(frozen=True)
class CompileSurface:
    """One template's certified compile surface.

    ``bindings`` maps every statically enumerable bound-array name to
    its per-dim generator classes; ``axes`` lists the input-driven axis
    classes actually present with their (minimum, cap, rung-count)
    ladders; ``n_signatures`` is the full composed surface size
    (product of ladder lengths x the devpages delta-width rungs when a
    resource axis is present).  ``bounded=False`` certificates carry
    the ``compile_surface_unbounded`` reason and are never registered
    with the dispatch sentinel."""

    kind: str
    digest: str
    bounded: bool
    reason: str | None
    bindings: tuple          # ((name, (cls, ...)), ...)
    axes: tuple              # ((cls, minimum, cap, n_rungs), ...)
    n_signatures: int
    delta_rungs: int         # devpages delta-width pow2 rungs (>= 256)
    scalar_pin: bool = False
    version: str = CS_VERSION


def surface_digest(lowered) -> str:
    """Certificate key: program cache_key + pad-geometry version +
    ladder caps.  A geometry change (PAD_GEOMETRY_VERSION bump, a cap
    re-size) invalidates by key mismatch — persisted certificates are
    never consulted across a geometry change."""
    from gatekeeper_tpu.analysis import footprint
    from gatekeeper_tpu.ir import prep as _prep
    return hashlib.sha256(repr((
        CS_VERSION, _prep.PAD_GEOMETRY_VERSION, _caps_sig(),
        repr(lowered.program.cache_key()),
        repr(footprint._spec_sig(lowered.spec)),
    )).encode()).hexdigest()


def _spec_binding_names(spec) -> list[str]:
    """Every bound-array name the prepped bindings for this spec can
    carry, including the per-request derived variants and the framework
    gates — the static enumeration the per-dim generators compose
    over."""
    names: list[str] = ["__alive__"]
    if getattr(spec, "cvalid_fns", ()):
        names.append("__cvalid__")
    # match/rank gates are installed per constraint set at dispatch;
    # the certificate always accounts for them (their axes are the
    # same c/r ladders either way)
    names += ["__match__", "__rank__", "__pagetable__"]
    names += [r.name for r in getattr(spec, "r_cols", ())]
    names += [r.name for r in getattr(spec, "e_cols", ())]
    names += [r.name for r in getattr(spec, "tables", ())]
    for r in getattr(spec, "ptables", ()):
        names += [f"{r.name}.any", f"{r.name}.all", f"{r.name}.vmap"]
    for r in getattr(spec, "csets", ()):
        names += [r.name, f"{r.name}.vmap"]
    names += [r.name for r in getattr(spec, "cvals", ())]
    names += [r.name for r in getattr(spec, "membs", ())]
    names += [r.name for r in getattr(spec, "elem_keys", ())]
    for r in getattr(spec, "keyed_vals", ()):
        names += [f"{r.name}.kv", f"{r.name}.sel"]
    names += [r.name for r in getattr(spec, "inv_joins", ())]
    for r in getattr(spec, "dfas", ()):
        names += [f"{r.name}.trans", f"{r.name}.accept", f"{r.name}.xv"]
    if getattr(spec, "dfas", ()):
        names += ["__strbytes__", "__strdfaok__"]
    return names


def _delta_rung_count() -> int:
    """Power-of-two rungs of the devpages delta-width ladder
    (``delta_bucket(n) * DELTA_K_LADDER`` in enforce/devpages.py),
    bounded by the full mask size under the r/c caps."""
    from gatekeeper_tpu.enforce import devpages as _dvp
    k_cap = _cap("r") * _cap("c") * _dvp.DELTA_K_LADDER
    n = 0
    k = _dvp.DELTA_K_MIN
    while k <= k_cap:
        n += 1
        k <<= 1
    return n


def analyze(kind: str, lowered) -> CompileSurface:
    """The Stage-7 abstract interpretation: enumerate the spec's bound
    arrays, map every dim to a pad-geometry generator, and compose the
    finite signature ladder — or reject as unbounded."""
    from gatekeeper_tpu.ir import prep as _prep
    digest = surface_digest(lowered)
    if kind in _test_unbounded_kinds():
        return CompileSurface(
            kind=kind, digest=digest, bounded=False,
            reason="deliberately unbounded (test seam)",
            bindings=(), axes=(), n_signatures=0, delta_rungs=0)
    bindings: list[tuple] = []
    present: set[str] = set()
    for name in _spec_binding_names(lowered.spec):
        try:
            classes = _prep.binding_dim_classes(name)
        except ValueError as e:
            return CompileSurface(
                kind=kind, digest=digest, bounded=False,
                reason=f"compile_surface_unbounded: binding {name!r} "
                       f"has no pad-geometry generator ({e})",
                bindings=tuple(bindings), axes=(), n_signatures=0,
                delta_rungs=0)
        bindings.append((name, classes))
        present.update(c for c in classes if c != "static")
    axes = []
    n_signatures = 1
    for cls in sorted(present):
        ladder = _prep.bucket_ladder(_LADDER_MIN[cls], _cap(cls))
        if not ladder:
            return CompileSurface(
                kind=kind, digest=digest, bounded=False,
                reason=f"compile_surface_unbounded: axis {cls!r} cap "
                       f"{_cap(cls)} below its pad minimum",
                bindings=tuple(bindings), axes=(), n_signatures=0,
                delta_rungs=0)
        axes.append((cls, _LADDER_MIN[cls], _cap(cls), len(ladder)))
        n_signatures *= len(ladder)
    delta_rungs = _delta_rung_count() if "r" in present else 0
    # each certified geometry can be entered as a full-mask signature
    # or through one of the devpages delta-width variants
    n_signatures *= 1 + delta_rungs
    return CompileSurface(
        kind=kind, digest=digest, bounded=True, reason=None,
        bindings=tuple(bindings), axes=tuple(axes),
        n_signatures=n_signatures, delta_rungs=delta_rungs)


def scalar_surface(kind: str) -> CompileSurface:
    """The trivial certificate of a scalar-pinned template: no jitted
    program, an empty compile surface — vacuously finite."""
    return CompileSurface(
        kind=kind, digest=f"scalar:{kind}", bounded=True,
        reason=None, bindings=(), axes=(), n_signatures=0,
        delta_rungs=0, scalar_pin=True)


def _test_unbounded_kinds() -> frozenset:
    raw = os.environ.get("GATEKEEPER_CS_TEST_UNBOUNDED", "")
    return frozenset(k for k in raw.split(",") if k)


# ---------------------------------------------------------------------------
# memoized entry point


def certify(kind: str, compiled, lowered) -> CompileSurface:
    """Memoized/snapshot-backed entry point the engine and probe use.
    Certificates persist in the snapshot "cs" tier, so a warm restart
    re-runs zero analyses.  The TEST_UNBOUNDED seam bypasses memo and
    snapshot — a deliberately unbounded surface must reach the caller,
    not a cached honest one."""
    global analyses_run
    digest = surface_digest(lowered)
    seam = kind in _test_unbounded_kinds()
    if not seam:
        cached = _memo.get(digest)
        if cached is not None:
            _publish(kind, cached, lowered)
            return cached
        from gatekeeper_tpu.resilience import snapshot as _snap
        hit = _snap.load_compilesurface(digest)   # 1-tuple or None
        if hit is not None and isinstance(hit[0], CompileSurface) \
                and hit[0].version == CS_VERSION:
            _memo[digest] = hit[0]
            _publish(kind, hit[0], lowered)
            return hit[0]

    cert = analyze(kind, lowered)
    analyses_run += 1
    if not seam and cert.bounded:
        _memo[digest] = cert
        from gatekeeper_tpu.resilience import snapshot as _snap
        _snap.save_compilesurface(digest, cert)
    _publish(kind, cert, lowered)
    return cert


def _publish(kind: str, cert: CompileSurface, lowered) -> None:
    surfaces[kind] = cert
    if cert.bounded:
        unbounded.pop(kind, None)
        if lowered is not None and not cert.scalar_pin:
            _registry[lowered.program.cache_key()] = cert
    else:
        unbounded[kind] = cert.reason or "compile_surface_unbounded"


def surface_for(kind: str) -> CompileSurface | None:
    """The most recently published certificate for a kind, or None
    when not yet analyzed."""
    return surfaces.get(kind)


def unbounded_for(kind: str) -> str | None:
    """The unbounded-surface reason for a kind, or None when the
    surface is certified finite (or not yet analyzed)."""
    return unbounded.get(kind)


# ---------------------------------------------------------------------------
# dispatch-time sentinel


def _pow2_member(v: int, cap: int) -> bool:
    return 1 <= v <= cap and (v & (v - 1)) == 0


def dispatch_certified(program, arrays, delta_k: int | None = None) -> bool:
    """Membership of one dispatch's signature in the installed
    certificate.  Called by the executor ONLY on a jit cache miss (a
    compile), never on the steady path.  Programs without a bounded
    certificate (dedup-rewritten subprograms, shared-column twins, the
    reduce kernels) are not guarded — True.  Membership is checked
    against the *live* caps, permissively at the ladder floor: any
    power of two under the cap is a certified rung (smaller-than-
    minimum pads cannot demand more signatures than the ladder)."""
    cert = _registry.get(program.cache_key())
    if cert is None or not cert.bounded:
        return True
    from gatekeeper_tpu.ir import prep as _prep
    for name in sorted(arrays):
        try:
            classes = _prep.binding_dim_classes(name)
        except ValueError:
            return False
        shape = tuple(arrays[name].shape)
        if len(shape) != len(classes):
            return False
        for v, cls in zip(shape, classes):
            if cls == "static":
                continue
            if not _pow2_member(int(v), _cap(cls)):
                return False
    if delta_k is not None:
        from gatekeeper_tpu.enforce import devpages as _dvp
        if not _pow2_member(int(delta_k),
                            _cap("r") * _cap("c") * _dvp.DELTA_K_LADDER) \
                or delta_k < _dvp.DELTA_K_MIN:
            return False
    return True
