"""Host-sync self-lint for kernel-side code.

``python -m gatekeeper_tpu.analysis.selflint <dir>...`` parses every
``.py`` file under the given directories and flags host-synchronizing
calls — ``.block_until_ready(...)``, ``np.asarray(...)`` /
``numpy.asarray(...)``, ``time.time()`` — that appear INSIDE
kernel-side functions.  Any of these inside a traced/jitted function
either forces a device round-trip per dispatch or bakes a host value
into the compiled artifact; outside kernel code they are legitimate
(explain paths, host prep, timing harnesses), so the lint must scope
itself to the jit closure rather than grepping whole files.

Kernel-side functions are discovered statically:

* functions decorated with ``jax.jit`` / ``jit`` (bare or via
  ``partial(jax.jit, ...)``);
* functions passed by name to a ``jax.jit(...)`` call, including local
  defs (``raw`` in engine/veval.py);
* the transitive closure over plain-name calls from those roots: a
  module-level function (or every method of a module-level class) a
  kernel function calls is itself kernel-side.

Attribute calls (``self._raw``) cannot be resolved statically and are
skipped — the closure rule above covers the real call graph of the
engine, where jitted entry points reach helpers by name.

Exit status: number of findings (0 = clean).  Wired as the ci.sh lint
stage over ``gatekeeper_tpu/engine`` and ``gatekeeper_tpu/ir``.
"""

from __future__ import annotations

import ast
import os
import sys

_FORBIDDEN_ATTRS = {"block_until_ready"}
# (module alias, attr) pairs resolved from `alias.attr(...)` calls
_FORBIDDEN_QUALIFIED = {
    ("np", "asarray"), ("numpy", "asarray"),
    ("onp", "asarray"),
    ("time", "time"),
}


def _dotted(node: ast.AST) -> tuple[str, ...] | None:
    """`a.b.c` -> ('a','b','c'); None for anything non-trivial."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / jit, or partial(jax.jit, ...)."""
    d = _dotted(node)
    if d in (("jax", "jit"), ("jit",)):
        return True
    if isinstance(node, ast.Call):
        fd = _dotted(node.func)
        if fd in (("partial",), ("functools", "partial")) and node.args:
            return _is_jit_expr(node.args[0])
    return False


class _Scopes(ast.NodeVisitor):
    """Collect every function/class definition and every jax.jit call
    whose first argument is a plain name."""

    def __init__(self) -> None:
        self.funcs: dict[str, list[ast.AST]] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        self.jit_named: set[str] = set()
        self.decorated_roots: list[ast.AST] = []

    def _visit_func(self, node) -> None:
        self.funcs.setdefault(node.name, []).append(node)
        if any(_is_jit_expr(d) for d in node.decorator_list):
            self.decorated_roots.append(node)
        self.generic_visit(node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.classes[node.name] = node
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_expr(node.func) and node.args \
                and isinstance(node.args[0], ast.Name):
            self.jit_named.add(node.args[0].id)
        self.generic_visit(node)


def _called_names(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
            out.add(sub.func.id)
    return out


def _kernel_roots(tree: ast.Module) -> list[ast.AST]:
    sc = _Scopes()
    sc.visit(tree)
    roots: list[ast.AST] = list(sc.decorated_roots)
    seen: set[int] = {id(r) for r in roots}
    frontier: list[str] = sorted(sc.jit_named)
    resolved: set[str] = set()
    while frontier:
        name = frontier.pop()
        if name in resolved:
            continue
        resolved.add(name)
        members: list[ast.AST] = list(sc.funcs.get(name, ()))
        cls = sc.classes.get(name)
        if cls is not None:
            members.extend(n for n in cls.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)))
        for fn in members:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            roots.append(fn)
            frontier.extend(_called_names(fn) - resolved)
    # transitive closure over the decorated roots too
    for fn in list(roots):
        for name in sorted(_called_names(fn) - resolved):
            frontier.append(name)
    while frontier:
        name = frontier.pop()
        if name in resolved:
            continue
        resolved.add(name)
        for fn in sc.funcs.get(name, ()):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            roots.append(fn)
            frontier.extend(_called_names(fn) - resolved)
    return roots


def _lint_tree(tree: ast.Module, path: str) -> list[str]:
    findings: list[str] = []
    for root in _kernel_roots(tree):
        for sub in ast.walk(root):
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _FORBIDDEN_ATTRS:
                findings.append(
                    f"{path}:{sub.lineno}: .{sub.func.attr}() inside "
                    f"kernel-side function {root.name!r}")
                continue
            d = _dotted(sub.func)
            if d is not None and len(d) == 2 \
                    and (d[0], d[1]) in _FORBIDDEN_QUALIFIED:
                findings.append(
                    f"{path}:{sub.lineno}: {d[0]}.{d[1]}() inside "
                    f"kernel-side function {root.name!r}")
    return findings


def lint_paths(paths: list[str]) -> list[str]:
    findings: list[str] = []
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, names in os.walk(p):
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(names) if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    for f in sorted(files):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=f)
        except SyntaxError as e:
            findings.append(f"{f}:{e.lineno}: syntax error: {e.msg}")
            continue
        findings.extend(_lint_tree(tree, f))
    return findings


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m gatekeeper_tpu.analysis.selflint "
              "<dir-or-file>...", file=sys.stderr)
        return 2
    findings = lint_paths(argv)
    for line in findings:
        print(line)
    if findings:
        print(f"selflint: {len(findings)} host-sync call(s) in "
              "kernel-side code", file=sys.stderr)
    else:
        print("selflint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
