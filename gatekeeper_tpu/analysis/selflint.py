"""Host-sync self-lint for kernel-side code.

``python -m gatekeeper_tpu.analysis.selflint <dir>...`` parses every
``.py`` file under the given directories and flags host-synchronizing
calls — ``.block_until_ready(...)``, ``np.asarray(...)`` /
``numpy.asarray(...)``, ``time.time()`` — that appear INSIDE
kernel-side functions.  Any of these inside a traced/jitted function
either forces a device round-trip per dispatch or bakes a host value
into the compiled artifact; outside kernel code they are legitimate
(explain paths, host prep, timing harnesses), so the lint must scope
itself to the jit closure rather than grepping whole files.

Kernel-side functions are discovered statically:

* functions decorated with ``jax.jit`` / ``jit`` (bare or via
  ``partial(jax.jit, ...)``);
* functions passed by name to a ``jax.jit(...)`` call, including local
  defs (``raw`` in engine/veval.py);
* the transitive closure over plain-name calls from those roots: a
  module-level function (or every method of a module-level class) a
  kernel function calls is itself kernel-side.

Attribute calls (``self._raw``) cannot be resolved statically and are
skipped — the closure rule above covers the real call graph of the
engine, where jitted entry points reach helpers by name.

The same walk also enforces the NONDETERMINISM rule inside kernel-side
functions: clocks (``time.monotonic``/``perf_counter``), RNG calls
(``random.*``, ``np.random.*``), ``uuid.uuid4``, and ``for``-loops over
un-sorted set expressions (hash-order iteration) are flagged — any of
these makes the traced program, and every digest or certificate derived
from it, vary run to run.

Exit status: number of findings (0 = clean).  Wired as the ci.sh lint
stage over ``gatekeeper_tpu/engine`` and ``gatekeeper_tpu/ir``.

``--locks`` switches to the LOCK-DISCIPLINE checker for host control-
plane code: inside any ``with ...lock:`` block whose context expression
ends in ``_lock``, blocking calls — provider round-trips (``fetch``,
``fetch_keys``, ``urlopen``), future waits (``.result()``),
``time.sleep`` — are flagged.  This codifies the WatchManager
lock-split rule (compute deltas under ``_lock``, apply subscribe/
unsubscribe outside it) as a CI gate over ``watch/``, ``controllers/``
and ``externaldata/``: a blocking call under a lock serializes every
reader behind one slow provider.  Nested function definitions inside
the ``with`` body are skipped (they run later, not under the lock).

``--lockorder`` builds the lock-ACQUISITION-ORDER graph over the whole
fileset (an edge A -> B when some path acquires B while holding A,
lexically or through statically-resolvable calls) and fails on any
cycle — the deadlock-capable ordering two threads can interleave.  See
:func:`lint_lockorder_paths` for the over-approximation rules.

``--rebind`` switches to the REBIND-ONLY checker for engine code:
``Bindings.arrays`` and ``Bindings.base_dirty`` are shared between the
sweep cache, the per-kind bindings cache, and in-flight executor
futures, so they must be REBOUND to a fresh dict (``b.arrays = {**...}``)
and never mutated in place — an in-place write retroactively changes
arrays a cached sweep or a queued future already captured.  The rule
flags subscript stores/deletes (``b.arrays[k] = v``, ``del
b.arrays[k]``), mutating dict methods (``.update``/``.pop``/
``.setdefault``/``.clear``/``.popitem``), and ``|=`` augmented
assignment on any ``<expr>.arrays`` / ``<expr>.base_dirty`` attribute.
Reads stay legal; this codifies the invariant documented at
engine/jax_driver.py (previously enforced only by comment).

``--retrace`` switches to the RETRACE-HAZARD checker for kernel-side
code, the static twin of the Stage-7 compile-surface certificate
(analysis/compilesurface.py): inside kernel roots it flags (a)
``jax.jit(...)`` / ``jit(...)`` / ``partial(jax.jit, ...)`` calls — a
jit wrapper constructed inside a traced function is a fresh
unmemoized executable per call, invisible to the compile cache and
the AOT precompiler; (b) ``jnp.asarray(...)`` / ``jnp.array(...)``
over freshly CONSTRUCTED host data (a literal, comprehension, or call
result) — such a value is baked per-signature into the compiled
artifact, so every drifting input shape is a retrace; re-wrapping an
already-bound array (``jnp.asarray(arrays[name])``, a plain name) is
a no-op under trace and exempt; and (c) ``if``-tests on ``.shape`` /
``.ndim`` — shape-dependent Python branching specializes the trace
beyond the pad-bucket ladder the certificate enumerated; the numpy
broadcast-dimension probe (``x.shape[i] == 1``) is exempt, it selects
between layouts inside the same certified lattice.  All three are
legitimate at the host seams (the memoized ``_compiled`` cache,
binding prep) — the lint scopes to the jit closure, so those seams
are naturally exempt.
"""

from __future__ import annotations

import ast
import os
import sys

_FORBIDDEN_ATTRS = {"block_until_ready"}
# (module alias, attr) pairs resolved from `alias.attr(...)` calls
_FORBIDDEN_QUALIFIED = {
    ("np", "asarray"), ("numpy", "asarray"),
    ("onp", "asarray"),
    ("time", "time"),
}


# nondeterminism rule set: any call into these modules inside a
# kernel-side function bakes a per-trace value into the compiled
# artifact (clocks, RNG state) — recompiles stop being reproducible and
# cached executables/certificates stop being trustworthy.  time.time is
# already in _FORBIDDEN_QUALIFIED; these cover whole module surfaces
# (random.random, random.choice, np.random.uniform, ...).
_NONDET_MODULE_PREFIXES = (
    ("random",), ("np", "random"), ("numpy", "random"), ("onp", "random"),
)
_NONDET_QUALIFIED = {("time", "monotonic"), ("time", "perf_counter"),
                     ("uuid", "uuid4")}

# lock-discipline rule set (--locks): calls that block the calling
# thread on I/O, a timer, or another thread's completion
_LOCK_BLOCKING_ATTRS = {"fetch", "fetch_keys", "urlopen", "result"}
_LOCK_BLOCKING_QUALIFIED = {("time", "sleep")}

# rebind-only rule set (--rebind): attributes that alias shared state
# (sweep cache, bindings cache, in-flight futures, device-resident
# page state — KindPages.mask/page_table/ij_dev hold live device
# buffers the next delta sweep reads) and therefore must be rebound to
# a fresh object, never mutated in place
_REBIND_ATTRS = {"arrays", "base_dirty", "mask", "page_table", "ij_dev"}
_DICT_MUTATORS = {"update", "setdefault", "pop", "clear", "popitem"}

# alloc-discipline rule set (--allocs): array constructors that, at
# the HOST layer, materialize a fresh device buffer per call.  Inside
# a jit trace the same spellings are XLA ops fused into the compiled
# program (and the Stage-8 memory surface has already priced them), so
# kernel roots are exempt; build/rebuild seams construct buffers by
# design and are exempt by name; everything else — the steady-state
# serve paths — must reuse ping-pong/recycled buffers, or carry an
# explicit `# allocs-ok: <reason>` waiver on the line.
_ALLOC_CONSTRUCTORS = {"zeros", "ones", "full", "empty", "arange",
                       "zeros_like", "ones_like", "full_like"}
_ALLOC_MODULE_PREFIXES = (("jnp",), ("jax", "numpy"))
_ALLOC_DEVICE_PUT = (("jax", "device_put"), ("device_put",))
# a function whose name carries one of these substrings is a
# build/rebuild seam: constructing device state is its job
_ALLOC_SEAM_MARKERS = ("build", "init", "rebuild", "prewarm", "warm",
                       "prepare", "restore", "expand", "adopt",
                       "migrate", "scatter", "put", "upload", "stage",
                       "precompile", "compile")
_ALLOC_WAIVER = "allocs-ok:"

# retrace-hazard rule set (--retrace): host->device conversion calls
# that bake per-trace constants when they appear inside the trace
_RETRACE_CONVERT = {
    ("jnp", "asarray"), ("jnp", "array"),
    ("jax", "numpy", "asarray"), ("jax", "numpy", "array"),
}
# attributes whose appearance in an `if` test makes the branch
# shape-dependent (trace specialization past the pad-bucket ladder)
_RETRACE_SHAPE_ATTRS = {"shape", "ndim"}


def _dotted(node: ast.AST) -> tuple[str, ...] | None:
    """`a.b.c` -> ('a','b','c'); None for anything non-trivial."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / jit, or partial(jax.jit, ...)."""
    d = _dotted(node)
    if d in (("jax", "jit"), ("jit",)):
        return True
    if isinstance(node, ast.Call):
        fd = _dotted(node.func)
        if fd in (("partial",), ("functools", "partial")) and node.args:
            return _is_jit_expr(node.args[0])
    return False


class _Scopes(ast.NodeVisitor):
    """Collect every function/class definition and every jax.jit call
    whose first argument is a plain name."""

    def __init__(self) -> None:
        self.funcs: dict[str, list[ast.AST]] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        self.jit_named: set[str] = set()
        self.decorated_roots: list[ast.AST] = []

    def _visit_func(self, node) -> None:
        self.funcs.setdefault(node.name, []).append(node)
        if any(_is_jit_expr(d) for d in node.decorator_list):
            self.decorated_roots.append(node)
        self.generic_visit(node)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.classes[node.name] = node
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if _is_jit_expr(node.func) and node.args \
                and isinstance(node.args[0], ast.Name):
            self.jit_named.add(node.args[0].id)
        self.generic_visit(node)


def _called_names(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
            out.add(sub.func.id)
    return out


def _kernel_roots(tree: ast.Module) -> list[ast.AST]:
    sc = _Scopes()
    sc.visit(tree)
    roots: list[ast.AST] = list(sc.decorated_roots)
    seen: set[int] = {id(r) for r in roots}
    frontier: list[str] = sorted(sc.jit_named)
    resolved: set[str] = set()
    while frontier:
        name = frontier.pop()
        if name in resolved:
            continue
        resolved.add(name)
        members: list[ast.AST] = list(sc.funcs.get(name, ()))
        cls = sc.classes.get(name)
        if cls is not None:
            members.extend(n for n in cls.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)))
        for fn in members:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            roots.append(fn)
            frontier.extend(_called_names(fn) - resolved)
    # transitive closure over the decorated roots too
    for fn in list(roots):
        for name in sorted(_called_names(fn) - resolved):
            frontier.append(name)
    while frontier:
        name = frontier.pop()
        if name in resolved:
            continue
        resolved.add(name)
        for fn in sc.funcs.get(name, ()):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            roots.append(fn)
            frontier.extend(_called_names(fn) - resolved)
    return roots


def _is_unordered_set_expr(node: ast.AST) -> bool:
    """Set literal / comprehension / bare set()-frozenset() call — an
    expression whose iteration order follows the process hash seed."""
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        return d in (("set",), ("frozenset",))
    return False


def _lint_tree(tree: ast.Module, path: str) -> list[str]:
    findings: list[str] = []
    for root in _kernel_roots(tree):
        for sub in ast.walk(root):
            if isinstance(sub, (ast.For, ast.AsyncFor)):
                # hash-order iteration: the loop body's trace order (and
                # therefore the compiled program / any digest derived
                # from it) varies with PYTHONHASHSEED
                if _is_unordered_set_expr(sub.iter):
                    findings.append(
                        f"{path}:{sub.lineno}: iteration over un-sorted "
                        f"set inside kernel-side function {root.name!r} "
                        f"(wrap in sorted(...))")
                continue
            if not isinstance(sub, ast.Call):
                continue
            if isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _FORBIDDEN_ATTRS:
                findings.append(
                    f"{path}:{sub.lineno}: .{sub.func.attr}() inside "
                    f"kernel-side function {root.name!r}")
                continue
            d = _dotted(sub.func)
            if d is None:
                continue
            if len(d) == 2 and (d[0], d[1]) in _FORBIDDEN_QUALIFIED:
                findings.append(
                    f"{path}:{sub.lineno}: {d[0]}.{d[1]}() inside "
                    f"kernel-side function {root.name!r}")
                continue
            if d in _NONDET_QUALIFIED:
                findings.append(
                    f"{path}:{sub.lineno}: nondeterministic "
                    f"{'.'.join(d)}() inside kernel-side function "
                    f"{root.name!r}")
                continue
            for prefix in _NONDET_MODULE_PREFIXES:
                if len(d) > len(prefix) and d[:len(prefix)] == prefix:
                    findings.append(
                        f"{path}:{sub.lineno}: nondeterministic "
                        f"{'.'.join(d)}() inside kernel-side function "
                        f"{root.name!r}")
                    break
    return findings


def _is_broadcast_probe(test: ast.AST) -> bool:
    """``x.shape[i] == 1`` / ``!= 1`` — the numpy broadcast-dimension
    idiom.  Axis-1 vs axis-N layout selection is static per signature
    and stays inside the certified pad lattice, so it is exempt from
    the shape-branch rule."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.ops[0], (ast.Eq, ast.NotEq)):
        return any(isinstance(s, ast.Constant) and s.value == 1
                   for s in (test.left, test.comparators[0]))
    return False


def _bakes_host_value(call: ast.Call) -> bool:
    """True when an asarray/array call converts freshly constructed
    host data (literal, comprehension, call result) rather than
    re-wrapping an already-materialized array (Name / Attribute /
    Subscript — a no-op under trace)."""
    if not call.args:
        return False
    return not isinstance(call.args[0],
                          (ast.Name, ast.Attribute, ast.Subscript))


def _alloc_seam(name: str) -> bool:
    low = name.lower()
    return any(m in low for m in _ALLOC_SEAM_MARKERS)


def _is_alloc_call(call: ast.Call) -> str | None:
    """Dotted name of a fresh-device-buffer construction, or None."""
    d = _dotted(call.func)
    if d is None:
        return None
    if len(d) >= 2 and d[-1] in _ALLOC_CONSTRUCTORS \
            and d[:-1] in _ALLOC_MODULE_PREFIXES:
        return ".".join(d)
    if d in _ALLOC_DEVICE_PUT and _bakes_host_value(call):
        return ".".join(d)
    return None


def _own_nodes(fn: ast.AST):
    """Nodes lexically owned by *fn*, pruning nested function defs
    (each nested def is judged under its own name by the caller)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            stack.extend(ast.iter_child_nodes(n))


def _lint_allocs_tree(tree: ast.Module, path: str) -> list[str]:
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        lines = []
    # exempt kernel roots (traced: constructors are XLA ops, priced by
    # the Stage-8 memory surface) and build/rebuild seams, including
    # any helper defined lexically inside either
    exempt: set[int] = set()
    for root in _kernel_roots(tree):
        for sub in ast.walk(root):
            exempt.add(id(sub))
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _alloc_seam(fn.name):
            for sub in ast.walk(fn):
                exempt.add(id(sub))
    findings: list[str] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if id(fn) in exempt:
            continue
        for sub in _own_nodes(fn):
            if not isinstance(sub, ast.Call):
                continue
            desc = _is_alloc_call(sub)
            if desc is None:
                continue
            # waiver comment on the call line or the line above it
            span = lines[max(0, sub.lineno - 2):sub.lineno]
            if any(_ALLOC_WAIVER in ln for ln in span):
                continue
            findings.append(
                f"{path}:{sub.lineno}: fresh device buffer "
                f"{desc}() in serve-path function {fn.name!r} "
                f"(move to a build seam, reuse a recycled buffer, "
                f"or waive with '# allocs-ok: <reason>')")
    return findings


def _lint_retrace_tree(tree: ast.Module, path: str) -> list[str]:
    """Flag retrace hazards inside kernel-side functions: per-call jit
    construction, in-trace host->device conversion, shape-dependent
    Python branching.  Walks root bodies (not decorator lists — the
    root's own ``@jax.jit`` is the legitimate seam, not a finding)."""
    findings: list[str] = []
    for root in _kernel_roots(tree):
        for stmt in root.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.If, ast.IfExp)):
                    if _is_broadcast_probe(sub.test):
                        continue
                    for t in ast.walk(sub.test):
                        if isinstance(t, ast.Attribute) \
                                and t.attr in _RETRACE_SHAPE_ATTRS:
                            findings.append(
                                f"{path}:{sub.lineno}: shape-dependent "
                                f"branch on .{t.attr} inside kernel-side "
                                f"function {root.name!r} (trace "
                                f"specialization past the certified "
                                f"pad ladder)")
                            break
                    continue
                if not isinstance(sub, ast.Call):
                    continue
                if _is_jit_expr(sub.func) or _is_jit_expr(sub):
                    findings.append(
                        f"{path}:{sub.lineno}: jit construction inside "
                        f"kernel-side function {root.name!r} (per-call "
                        f"executable, invisible to the compile cache "
                        f"and AOT precompiler)")
                    continue
                d = _dotted(sub.func)
                if d in _RETRACE_CONVERT and _bakes_host_value(sub):
                    findings.append(
                        f"{path}:{sub.lineno}: {'.'.join(d)}() over "
                        f"freshly constructed host data inside "
                        f"kernel-side function {root.name!r} (baked "
                        f"per trace; convert at the binding seam)")
    return findings


def _lock_name(item: ast.withitem) -> str | None:
    """Name of the lock a with-item acquires, or None.

    Matches ``with self._lock:``, ``with mgr._prep_lock:`` and call
    wrappers like ``with self._lock.acquire_timeout(1):`` — any dotted
    context expression with a segment ending in ``_lock`` (or exactly
    ``lock``)."""
    expr = item.context_expr
    if isinstance(expr, ast.Call):
        expr = expr.func
    d = _dotted(expr)
    if d is None:
        return None
    for seg in d:
        if seg.endswith("_lock") or seg == "lock":
            return ".".join(d)
    return None


def _lint_lock_tree(tree: ast.Module, path: str) -> list[str]:
    """Flag blocking calls lexically inside ``with *_lock:`` bodies."""
    findings: list[str] = []

    def walk_pruned(node: ast.AST):
        """ast.walk, but don't descend into nested defs/lambdas — code
        inside them runs later, not under the lock."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            yield child
            yield from walk_pruned(child)

    def scan_body(body: list[ast.stmt], lockname: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in [stmt, *walk_pruned(stmt)]:
                if not isinstance(sub, ast.Call):
                    continue
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in _LOCK_BLOCKING_ATTRS:
                    findings.append(
                        f"{path}:{sub.lineno}: blocking .{sub.func.attr}() "
                        f"while holding {lockname}")
                    continue
                d = _dotted(sub.func)
                if d is not None and len(d) == 2 \
                        and (d[0], d[1]) in _LOCK_BLOCKING_QUALIFIED:
                    findings.append(
                        f"{path}:{sub.lineno}: blocking {d[0]}.{d[1]}() "
                        f"while holding {lockname}")

    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            lockname = _lock_name(item)
            if lockname is not None:
                scan_body(node.body, lockname)
                break
    return findings


def _callee_name(call: ast.Call) -> str | None:
    """Statically resolvable callee for the lock-order call graph:
    plain names (module functions) and ``self.<method>`` calls; other
    attribute calls cannot be resolved and are skipped."""
    if isinstance(call.func, ast.Name):
        return call.func.id
    if isinstance(call.func, ast.Attribute) \
            and isinstance(call.func.value, ast.Name) \
            and call.func.value.id == "self":
        return call.func.attr
    return None


def lint_lockorder_paths(paths: list[str]) -> list[str]:
    """``--lockorder``: whole-fileset lock-ACQUISITION-ORDER checker.

    Builds the acquisition graph from the AST: an edge ``A -> B``
    means some code path acquires lock ``B`` (by its final attribute
    name, e.g. ``_prep_lock``) while holding ``A`` — either lexically
    (a nested ``with``) or interprocedurally (a call made under ``A``
    into a function whose transitive closure acquires ``B``).  A cycle
    in that graph is a deadlock-capable ordering (thread 1 holds A
    wanting B, thread 2 holds B wanting A) and is reported as a
    finding.  Names merge per final segment and per bare callee name
    across the fileset — a deliberate over-approximation, like the
    rest of this lint; self-loops are skipped (same-name locks on
    distinct instances, and RLock re-entry, would drown the signal)."""
    fn_acquires: dict[str, set[str]] = {}
    fn_calls: dict[str, set[str]] = {}
    edges: dict[tuple[str, str], str] = {}   # (held, acquired) -> witness
    call_under: list[tuple[str, str, str]] = []   # (held, callee, site)

    def harvest(fn_node: ast.AST, path: str) -> None:
        acquires = fn_acquires.setdefault(fn_node.name, set())
        calls = fn_calls.setdefault(fn_node.name, set())

        def walk(node: ast.AST, held: tuple[str, ...]) -> None:
            if node is not fn_node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
                return      # runs later, not under the held locks
            if isinstance(node, (ast.With, ast.AsyncWith)):
                got: list[str] = []
                for item in node.items:
                    walk(item.context_expr, held)
                    ln = _lock_name(item)
                    if ln is None:
                        continue
                    lk = ln.rsplit(".", 1)[-1]
                    got.append(lk)
                    acquires.add(lk)
                    for h in held:
                        if h != lk:
                            edges.setdefault(
                                (h, lk), f"{path}:{node.lineno}")
                held2 = held + tuple(got)
                for stmt in node.body:
                    walk(stmt, held2)
                return
            if isinstance(node, ast.Call):
                cal = _callee_name(node)
                if cal is not None:
                    calls.add(cal)
                    for h in held:
                        call_under.append(
                            (h, cal, f"{path}:{node.lineno}"))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        walk(fn_node, ())

    for f in _iter_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=f)
        except SyntaxError as e:
            return [f"{f}:{e.lineno}: syntax error: {e.msg}"]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                harvest(node, f)

    # transitive lock closure per (bare) function name
    fn_locks: dict[str, set[str]] = {}

    def locks_of(name: str, seen: set[str]) -> set[str]:
        got = fn_locks.get(name)
        if got is not None:
            return got
        if name in seen:
            return set()
        seen.add(name)
        out = set(fn_acquires.get(name, ()))
        for cal in fn_calls.get(name, ()):
            if cal in fn_acquires:
                out |= locks_of(cal, seen)
        return out

    for name in fn_acquires:
        fn_locks[name] = locks_of(name, set())

    for held, cal, site in call_under:
        for lk in fn_locks.get(cal, ()):
            if lk != held:
                edges.setdefault((held, lk), f"{site} (via {cal})")

    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)

    findings: list[str] = []
    visited: set[str] = set()

    def dfs(n: str, stack: list[str], onstack: set[str]) -> None:
        visited.add(n)
        onstack.add(n)
        stack.append(n)
        for m in sorted(adj.get(n, ())):
            if m in onstack:
                i = stack.index(m)
                cyc = stack[i:] + [m]
                wit = "; ".join(
                    edges.get((cyc[j], cyc[j + 1]), "?")
                    for j in range(len(cyc) - 1))
                findings.append(
                    f"lock-order cycle: {' -> '.join(cyc)} ({wit})")
            elif m not in visited:
                dfs(m, stack, onstack)
        onstack.discard(n)
        stack.pop()

    for n in sorted(adj):
        if n not in visited:
            dfs(n, [], set())
    return findings


def _is_rebind_attr(node: ast.AST) -> bool:
    """`<anything>.arrays` / `<anything>.base_dirty` attribute access."""
    return isinstance(node, ast.Attribute) and node.attr in _REBIND_ATTRS


def _lint_rebind_tree(tree: ast.Module, path: str) -> list[str]:
    """Flag in-place mutation of Bindings.arrays / base_dirty."""
    findings: list[str] = []
    for sub in ast.walk(tree):
        if isinstance(sub, ast.Subscript) \
                and isinstance(sub.ctx, (ast.Store, ast.Del)) \
                and _is_rebind_attr(sub.value):
            verb = "del of" if isinstance(sub.ctx, ast.Del) else "store into"
            findings.append(
                f"{path}:{sub.lineno}: in-place {verb} "
                f".{sub.value.attr}[...] (rebind a fresh dict instead)")
        elif isinstance(sub, ast.AugAssign) \
                and _is_rebind_attr(sub.target):
            findings.append(
                f"{path}:{sub.lineno}: augmented assignment to "
                f".{sub.target.attr} (rebind a fresh dict instead)")
        elif isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr in _DICT_MUTATORS \
                and _is_rebind_attr(sub.func.value):
            findings.append(
                f"{path}:{sub.lineno}: mutating "
                f".{sub.func.value.attr}.{sub.func.attr}() "
                f"(rebind a fresh dict instead)")
    return findings


def _iter_files(paths: list[str]) -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, _dirs, names in os.walk(p):
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(names) if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    return sorted(files)


def _lint_files(paths: list[str], lint_fn) -> list[str]:
    findings: list[str] = []
    for f in _iter_files(paths):
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        try:
            tree = ast.parse(src, filename=f)
        except SyntaxError as e:
            findings.append(f"{f}:{e.lineno}: syntax error: {e.msg}")
            continue
        findings.extend(lint_fn(tree, f))
    return findings


def lint_paths(paths: list[str]) -> list[str]:
    return _lint_files(paths, _lint_tree)


def lint_lock_paths(paths: list[str]) -> list[str]:
    return _lint_files(paths, _lint_lock_tree)


def lint_rebind_paths(paths: list[str]) -> list[str]:
    return _lint_files(paths, _lint_rebind_tree)


def lint_retrace_paths(paths: list[str]) -> list[str]:
    return _lint_files(paths, _lint_retrace_tree)


def lint_allocs_paths(paths: list[str]) -> list[str]:
    return _lint_files(paths, _lint_allocs_tree)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    locks = "--locks" in argv
    lockorder = "--lockorder" in argv
    rebind = "--rebind" in argv
    retrace = "--retrace" in argv
    allocs = "--allocs" in argv
    argv = [a for a in argv if a not in ("--locks", "--lockorder",
                                         "--rebind", "--retrace",
                                         "--allocs")]
    if not argv:
        print("usage: python -m gatekeeper_tpu.analysis.selflint "
              "[--locks|--lockorder|--rebind|--retrace|--allocs] "
              "<dir-or-file>...",
              file=sys.stderr)
        return 2
    if allocs:
        findings = lint_allocs_paths(argv)
        kind_msg = "fresh device-buffer alloc(s) in serve paths"
    elif retrace:
        findings = lint_retrace_paths(argv)
        kind_msg = "retrace hazard(s) in kernel-side code"
    elif locks:
        findings = lint_lock_paths(argv)
        kind_msg = "blocking call(s) under _lock"
    elif lockorder:
        findings = lint_lockorder_paths(argv)
        kind_msg = "lock-acquisition-order cycle(s)"
    elif rebind:
        findings = lint_rebind_paths(argv)
        kind_msg = "in-place mutation(s) of rebind-only state"
    else:
        findings = lint_paths(argv)
        kind_msg = "host-sync call(s) in kernel-side code"
    for line in findings:
        print(line)
    if findings:
        print(f"selflint: {len(findings)} {kind_msg}", file=sys.stderr)
    else:
        print("selflint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    raise SystemExit(main())
