"""Stage 2: the lowered-IR verifier.

Validates a device program (ir/program.py) against its PrepSpec before
the engine jits it: a malformed node would otherwise surface as a
shape/KeyError deep inside a traced jax computation, or — worse —
silently gather garbage.  Checks, per node:

* **SSA form** — every ``args`` entry references an earlier node;
* **op universe / arity** — the op is one the evaluator
  (engine/veval.py) implements, with the argument count it expects;
* **binding resolution** — every meta name (input column, interned
  table, constraint set, membership matrix, keyed-val table, element
  axis) resolves to a request in the PrepSpec, with the input kind the
  spec's request implies (``ir_dangling_ref`` otherwise);
* **dtype classes** — operands carry the class (bool/num/id) the op
  consumes: comparisons never mix namespaces, ordering and arithmetic
  are numeric-only, masks are bool (``ir_type_mismatch``);
* **gather bounds** — a ``table``/``ptable_*`` gather's index operand
  must be the interned input column the table was built over
  (``TableReq.src``): the table's rows are indexed by exactly that
  column's intern ids, so the gather is in-bounds by construction.
  Any other index source cannot be proven in-bounds and is rejected
  (``ir_shape_mismatch``);
* **provider tags** — when a declared-provider set is given, every
  ``TableReq.ext_providers`` tag must resolve (``ir_bad_provider_ref``).

All findings are error severity: a device program is either
well-formed or it must not reach jit.  The engine treats findings as
"fall back to the scalar oracle" unless GATEKEEPER_IR_VERIFY=strict
(see engine/jax_driver.py); GATEKEEPER_IR_VERIFY=off skips the pass.

Module counters VERIFY_RUNS / VERIFY_VIOLATIONS let the test suite
assert the verifier actually ran over every program it lowered, with
zero violations.
"""

from __future__ import annotations

from gatekeeper_tpu.analysis.diagnostics import ERROR, Diagnostic
from gatekeeper_tpu.errors import Location
from gatekeeper_tpu.ir.program import CMP_OPS, NUM_OPS, Node, Program

VERIFY_RUNS = 0
VERIFY_VIOLATIONS = 0

# arg-count per op (None = checked specially)
_ARITY = {
    "const": 0, "input": 0, "table": 1, "dfa_match": 1,
    "ptable_any": 1, "ptable_all": 1,
    "keyed_val": 0, "cmp": 2, "and": 2, "or": 2, "not": 1, "in_cset": 1,
    "cset_not_subset_memb": 0, "cset_subset_memb": 0,
    "elem_keys_missing": 0, "any_e": 1, "all_e": 1, "count_e": 1,
    "arith": 2,
}

_INPUT_KINDS = frozenset({
    "r_id", "r_num", "r_bool", "e_id", "e_num", "e_bool",
    "c_id", "c_num", "c_bool",
})

# RColReq/EColReq mode -> the input-node kind suffix lowering emits
_MODE_SUFFIX = {"str": "id", "val": "id", "num": "num", "len": "num",
                "truthy": "bool", "present": "bool"}
# CValReq kind -> input-node kind
_CVAL_KIND = {"num": "c_num", "str": "c_id", "val": "c_id", "bool": "c_bool"}
# TableReq out -> node dtype class
_TABLE_CLASS = {"bool": "bool", "num": "num", "id_str": "id", "id_val": "id"}


def _spec_bindings(spec) -> dict[str, str]:
    """name -> expected input-node kind, over every request family that
    lowering materializes as an ``input`` node."""
    out: dict[str, str] = {}
    for r in spec.r_cols:
        out[r.name] = "r_" + _MODE_SUFFIX.get(r.mode, "?")
    for e in spec.e_cols:
        out[e.name] = "e_" + _MODE_SUFFIX.get(e.mode, "?")
    for cv in spec.cvals:
        out[cv.name] = _CVAL_KIND.get(cv.kind, "?")
    for ij in spec.inv_joins:
        out[ij.name] = "r_bool"
    return out


def verify_program(lowered, providers: "set[str] | None" = None,
                   file: str = "") -> list[Diagnostic]:
    """Verify one LoweredProgram (ir/lower.py).  Returns a (possibly
    empty) list of error-severity diagnostics and bumps the module
    counters.  ``providers=None`` skips the provider-tag check (the
    engine verifies structure only; install-time callers pass the
    declared set)."""
    global VERIFY_RUNS, VERIFY_VIOLATIONS
    program: Program = lowered.program
    spec = lowered.spec
    # `loc` is rebound per node / per rule below so every diagnostic
    # carries the offending position (Location.row = node or rule index
    # in the lowered program — the IR has no source text, so the index
    # IS the address a debugger needs)
    loc = Location(file=file)
    diags: list[Diagnostic] = []

    def err(code: str, msg: str) -> None:
        diags.append(Diagnostic(code, ERROR, msg, loc))

    bindings = _spec_bindings(spec)
    tables = {t.name: t for t in spec.tables}
    ptables = {t.name: t for t in spec.ptables}
    dfas = {d.name: d for d in getattr(spec, "dfas", ())}
    csets = {c.name for c in spec.csets}
    membs = {m.name for m in spec.membs}
    elem_keys = {ek.name for ek in spec.elem_keys}
    keyed_vals = {kv.name for kv in spec.keyed_vals}
    axes = {ax for ax, _base in spec.axes}

    classes: list[str] = []  # per-node dtype class: bool | num | id | ?

    def input_node_named(nid: int, want_src: str) -> bool:
        n = program.nodes[nid]
        return n.op == "input" and n.meta and n.meta[0] == want_src

    for i, n in enumerate(program.nodes):
        loc = Location(row=i, file=file)
        cls = "?"
        if not isinstance(n, Node) or _ARITY.get(n.op) is None:
            err("ir_unknown_op", f"node {i}: unknown op {n.op!r}")
            classes.append(cls)
            continue
        if len(n.args) != _ARITY[n.op]:
            err("ir_shape_mismatch",
                f"node {i} ({n.op}): expected {_ARITY[n.op]} args, "
                f"got {len(n.args)}")
            classes.append(cls)
            continue
        if any(a < 0 or a >= i for a in n.args):
            err("ir_dangling_ref",
                f"node {i} ({n.op}): args {n.args} reference a node at "
                f"or after position {i} (program is not in SSA order)")
            classes.append(cls)
            continue
        acls = [classes[a] for a in n.args]

        if n.op == "const":
            if len(n.meta) != 2 or n.meta[1] not in ("float32", "bool"):
                err("ir_type_mismatch",
                    f"node {i} (const): meta must be (value, "
                    f"'float32'|'bool'), got {n.meta!r}")
            else:
                cls = "num" if n.meta[1] == "float32" else "bool"
        elif n.op == "input":
            if len(n.meta) != 2 or n.meta[1] not in _INPUT_KINDS:
                err("ir_type_mismatch",
                    f"node {i} (input): bad kind in meta {n.meta!r}")
            else:
                name, kind = n.meta
                want = bindings.get(name)
                if want is None:
                    err("ir_dangling_ref",
                        f"node {i} (input): column {name!r} has no "
                        "request in the PrepSpec")
                elif want != kind:
                    err("ir_type_mismatch",
                        f"node {i} (input): column {name!r} is bound as "
                        f"{want} but the node declares {kind}")
                cls = {"id": "id", "num": "num", "bool": "bool"}[
                    kind.split("_")[1]]
        elif n.op == "table":
            if len(n.meta) != 1:
                err("ir_shape_mismatch",
                    f"node {i} (table): meta must be (tname,), "
                    f"got {n.meta!r}")
            else:
                req = tables.get(n.meta[0])
                if req is None:
                    err("ir_dangling_ref",
                        f"node {i} (table): table {n.meta[0]!r} has no "
                        "TableReq in the PrepSpec")
                else:
                    cls = _TABLE_CLASS.get(req.out, "?")
                    if not input_node_named(n.args[0], req.src):
                        err("ir_shape_mismatch",
                            f"node {i} (table {req.name}): gather index "
                            f"is not the interned source column "
                            f"{req.src!r}; in-bounds access cannot be "
                            "proven")
                    elif acls[0] != "id":
                        err("ir_type_mismatch",
                            f"node {i} (table {req.name}): index operand "
                            f"must be an interned id column, got "
                            f"{acls[0]}")
                    if providers is not None:
                        for p in req.ext_providers:
                            if p not in providers:
                                err("ir_bad_provider_ref",
                                    f"node {i} (table {req.name}): "
                                    f"external-data tag {p!r} does not "
                                    "resolve to a declared provider")
        elif n.op == "dfa_match":
            if len(n.meta) != 1:
                err("ir_shape_mismatch",
                    f"node {i} (dfa_match): meta must be (dfa_name,), "
                    f"got {n.meta!r}")
            else:
                req = dfas.get(n.meta[0])
                if req is None:
                    err("ir_dangling_ref",
                        f"node {i} (dfa_match): dfa {n.meta[0]!r} has no "
                        "DfaReq in the PrepSpec")
                else:
                    if not input_node_named(n.args[0], req.src):
                        err("ir_shape_mismatch",
                            f"node {i} (dfa_match {req.name}): gather "
                            f"index is not the interned source column "
                            f"{req.src!r}; in-bounds access cannot be "
                            "proven")
                    elif acls[0] != "id":
                        err("ir_type_mismatch",
                            f"node {i} (dfa_match {req.name}): index "
                            f"operand must be an interned id column, got "
                            f"{acls[0]}")
            cls = "bool"
        elif n.op in ("ptable_any", "ptable_all"):
            if len(n.meta) != 2 or n.meta[0] != n.meta[1]:
                err("ir_shape_mismatch",
                    f"node {i} ({n.op}): meta must be (tname, tname), "
                    f"got {n.meta!r}")
            else:
                req = ptables.get(n.meta[0])
                if req is None:
                    err("ir_dangling_ref",
                        f"node {i} ({n.op}): ptable {n.meta[0]!r} has no "
                        "PTableReq in the PrepSpec")
                elif not input_node_named(n.args[0], req.src):
                    err("ir_shape_mismatch",
                        f"node {i} ({n.op} {req.name}): gather index is "
                        f"not the interned source column {req.src!r}")
                cls = "bool"
        elif n.op == "keyed_val":
            if len(n.meta) != 1 or n.meta[0] not in keyed_vals:
                err("ir_dangling_ref",
                    f"node {i} (keyed_val): {n.meta!r} has no "
                    "KeyedValReq in the PrepSpec")
            cls = "id"
        elif n.op == "in_cset":
            if len(n.meta) != 1 or n.meta[0] not in csets:
                err("ir_dangling_ref",
                    f"node {i} (in_cset): {n.meta!r} has no CSetReq in "
                    "the PrepSpec")
            if acls[0] != "id":
                err("ir_type_mismatch",
                    f"node {i} (in_cset): member operand must be an "
                    f"interned id, got {acls[0]}")
            cls = "bool"
        elif n.op in ("cset_not_subset_memb", "cset_subset_memb"):
            if len(n.meta) != 2:
                err("ir_shape_mismatch",
                    f"node {i} ({n.op}): meta must be (cset, memb), "
                    f"got {n.meta!r}")
            else:
                if n.meta[0] not in csets:
                    err("ir_dangling_ref",
                        f"node {i} ({n.op}): cset {n.meta[0]!r} has no "
                        "CSetReq in the PrepSpec")
                if n.meta[1] not in membs:
                    err("ir_dangling_ref",
                        f"node {i} ({n.op}): membership {n.meta[1]!r} "
                        "has no MembReq in the PrepSpec")
            cls = "bool"
        elif n.op == "elem_keys_missing":
            if len(n.meta) != 2:
                err("ir_shape_mismatch",
                    f"node {i} ({n.op}): meta must be (cset, elem_keys),"
                    f" got {n.meta!r}")
            else:
                if n.meta[0] not in csets:
                    err("ir_dangling_ref",
                        f"node {i} ({n.op}): cset {n.meta[0]!r} has no "
                        "CSetReq in the PrepSpec")
                if n.meta[1] not in elem_keys:
                    err("ir_dangling_ref",
                        f"node {i} ({n.op}): elem-keys {n.meta[1]!r} "
                        "has no ElemKeysReq in the PrepSpec")
            cls = "bool"
        elif n.op == "cmp":
            if len(n.meta) != 1 or n.meta[0] not in CMP_OPS:
                err("ir_shape_mismatch",
                    f"node {i} (cmp): meta must name one of {CMP_OPS}, "
                    f"got {n.meta!r}")
            else:
                cop = n.meta[0]
                if cop in ("<", "<=", ">", ">="):
                    if acls != ["num", "num"]:
                        err("ir_type_mismatch",
                            f"node {i} (cmp {cop}): ordering is "
                            f"numeric-only, got {acls}")
                elif not (acls == ["num", "num"] or acls == ["id", "id"]):
                    err("ir_type_mismatch",
                        f"node {i} (cmp {cop}): operands must both be "
                        f"num or both interned ids, got {acls}")
            cls = "bool"
        elif n.op == "arith":
            if len(n.meta) != 1 or n.meta[0] not in NUM_OPS:
                err("ir_shape_mismatch",
                    f"node {i} (arith): meta must name one of "
                    f"{NUM_OPS}, got {n.meta!r}")
            elif acls != ["num", "num"]:
                err("ir_type_mismatch",
                    f"node {i} (arith {n.meta[0]}): operands must be "
                    f"numeric, got {acls}")
            cls = "num"
        elif n.op in ("and", "or", "not"):
            # operands of any class: the evaluator's _fires() coerces
            # non-bool values to their definedness mask
            cls = "bool"
        elif n.op in ("any_e", "all_e", "count_e"):
            if len(n.meta) != 1 or n.meta[0] not in axes:
                err("ir_dangling_ref",
                    f"node {i} ({n.op}): element axis {n.meta!r} is not "
                    "declared in the PrepSpec")
            cls = "num" if n.op == "count_e" else "bool"
        classes.append(cls)

    nn = len(program.nodes)
    for ri, rule in enumerate(program.rules):
        loc = Location(row=ri, file=file)
        for ci in rule.conjuncts:
            if ci < 0 or ci >= nn:
                err("ir_dangling_ref",
                    f"rule {ri}: conjunct {ci} is out of range "
                    f"(program has {nn} nodes)")
        if rule.elem_axis is not None and rule.elem_axis not in axes:
            err("ir_dangling_ref",
                f"rule {ri}: element axis {rule.elem_axis!r} is not "
                "declared in the PrepSpec")

    VERIFY_RUNS += 1
    VERIFY_VIOLATIONS += len(diags)
    return diags


def reset_counters() -> None:
    global VERIFY_RUNS, VERIFY_VIOLATIONS
    VERIFY_RUNS = 0
    VERIFY_VIOLATIONS = 0
