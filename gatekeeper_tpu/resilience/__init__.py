"""Runtime resilience: warm-restart persistence + backend supervision.

Two halves (ROADMAP item 5):

- ``snapshot``: versioned on-disk persistence of the expensive
  startup artifacts — each template's lowered IR, the whole-set dedup
  plan, and a columnar-store snapshot — keyed by host fingerprint +
  artifact digest, stored alongside the XLA compilation cache.  A
  restarted pod skips Rego lowering and cache replication and is
  serving in seconds (the compiler-first O(1)-caching discipline:
  persist the compiled artifact, not the source).
- ``supervisor``: a supervised state machine over the device backend
  (healthy -> degraded(cpu-fallback) -> recovering -> healthy) that
  replaces the old one-shot, one-way ``mark_unavailable`` demotion.
  Serving paths consult the supervisor per dispatch; bounded re-probes
  with exponential backoff bring a flapped backend home and re-jit the
  executables onto it.
- ``faults``: the fault-injection harness
  (``GATEKEEPER_FAULT=probe_hang|device_lost|snapshot_corrupt``)
  exercising both halves in tests and CI.
"""

from gatekeeper_tpu.resilience import faults  # noqa: F401
from gatekeeper_tpu.resilience.supervisor import (  # noqa: F401
    DEGRADED, HEALTHY, POISONED, RECOVERING, BackendSupervisor,
    get_supervisor)
