"""Fault-injection harness: ``GATEKEEPER_FAULT=<name>[,<name>...]``.

Tests and CI arm faults through the environment; the production code
consults this module at the exact seams a real failure would hit:

- ``probe_hang``       — the device probe (initial and supervisor
                         re-probes) parks forever, simulating a
                         blackholed PJRT tunnel (the round-4 failure).
- ``device_lost``      — fires ONCE, mid-sweep, demoting the backend
                         supervisor as if the device died under a
                         dispatched executable.
- ``snapshot_corrupt`` — fires ONCE per snapshot read, making the
                         loader treat the entry as corrupt; exercises
                         the delete-and-rebuild path.
- ``slow_provider``    — external-data provider fetches stall for
                         ``GATEKEEPER_FAULT_STALL_S`` while armed,
                         simulating a saturated/far-away provider
                         (drives deadline expiry + brownout, not
                         breaker-open errors).
- ``queue_storm``      — fires ONCE, stalling admission batch
                         formation so the bounded queue fills and the
                         overload ladder engages (a simulated consumer
                         stall: slow device, GC pause, noisy
                         neighbor).
- ``fleet_straggler``  — fires ONCE, inside the fleet graduator's
                         candidate-twin build (rollout/fleet.py),
                         failing exactly one cluster of the fleet; the
                         map-reduce isolation contract marks only that
                         cluster ``held``, never the fleet.

Watch-class faults (consumed at the reactor's ingest edge,
``enforce/reactor.py`` — each models one way a watch stream breaks):

- ``watch_stall``      — while armed, frames buffer unstamped (bytes
                         stuck in the socket); past the stall timeout
                         the reactor declares the connection dead and
                         degrades to sweep cadence, reconnecting under
                         exponential backoff (attempts while armed
                         fail, as against a still-sick API server).
- ``watch_gap``        — fires ONCE: a stamped frame is lost on the
                         wire; the gap detector confirms the missing
                         sequence after the grace window and takes a
                         rung-2 kind resync.
- ``watch_duplicate``  — fires ONCE: a frame is delivered twice with
                         the same sequence; classified ``duplicate``
                         and dropped (verdict application is
                         idempotent regardless).
- ``watch_reorder``    — fires ONCE: a frame arrives late, below the
                         high-water sequence; classified
                         ``out_of_order`` and HEALS the suspected gap
                         — no resync.
- ``watch_flood``      — while armed, every real frame is followed by
                         a replay storm of recent frames; coalescing
                         absorbs small storms, a storm past the queue
                         bound is an ``overflow`` pathology escalating
                         to a rung-2 resync.

``active`` faults apply every time they are consulted; ``take`` faults
are one-shot per process (the set of already-fired names is kept here)
so a single armed fault produces one discrete failure event rather
than a permanently broken subsystem.  The chaos soak
(``resilience/chaos.py``) re-arms one-shot faults between schedule
events via ``rearm``.
"""

from __future__ import annotations

import os
import threading

_fired: set[str] = set()
_lock = threading.Lock()


def _armed() -> set[str]:
    spec = os.environ.get("GATEKEEPER_FAULT", "")
    return {f.strip() for f in spec.split(",") if f.strip()}


def active(name: str) -> bool:
    """Is the fault armed right now?  (Re-reads the env every call so
    tests can arm/disarm without process restarts.)"""
    return name in _armed()


def take(name: str) -> bool:
    """One-shot: True exactly once per process while the fault is
    armed; later calls return False even if it stays armed."""
    if name not in _armed():
        return False
    with _lock:
        if name in _fired:
            return False
        _fired.add(name)
    # a fired fault is a synthetic failure event: put it on the flight
    # recorder and dump the ring (best-effort — the injection seam must
    # behave exactly like the real failure it simulates)
    try:
        from gatekeeper_tpu.obs.flightrecorder import get_flight_recorder
        rec = get_flight_recorder()
        rec.record("fault_trip", fault=name)
        rec.dump(f"fault:{name}")
    except Exception:   # noqa: BLE001
        pass
    return True


def rearm(name: str) -> None:
    """Forget that a one-shot fault fired, so the next ``take`` while
    armed fires again — the chaos scheduler injects the same fault
    class repeatedly across a soak."""
    with _lock:
        _fired.discard(name)


def reset_for_tests() -> None:
    with _lock:
        _fired.clear()
