"""Restart smoke: one cold-or-warm startup measurement, JSON verdict.

``python -m gatekeeper_tpu.resilience.smoke`` builds the full policy
library against a JaxDriver, ingests a deterministic mixed inventory
(or restores it from the store snapshot), runs one full audit sweep,
persists the store snapshot, and prints a single JSON line::

    {"serving_seconds": ..., "restart_persistent_cache_hits": ...,
     "lowerings": ..., "templates": ..., "store_restored": ...,
     "verdict_digest": ..., "n_results": ...}

Run it twice against the same ``GATEKEEPER_SNAPSHOT_DIR`` (fresh
directory for the cold run) and the warm process must show
``restart_persistent_cache_hits > 0``, ``lowerings == 0`` (no Rego
re-lowering, no re-verification), ``validations == 0`` (every
translation-validation Certificate restored from the cert snapshot
tier instead of re-derived), ``footprints == 0`` (every Stage-5
dependency footprint restored from the fp snapshot tier instead of
re-analyzed), ``shardplans == 0`` (every Stage-6 partition plan
restored from the sp snapshot tier), ``memsurfaces == 0`` (every
Stage-8 memory-surface certificate restored from the ms snapshot
tier), an identical ``verdict_digest``, and
a substantially smaller ``serving_seconds`` — ci.sh's restart-smoke
stage asserts exactly that.  The workload is deterministic
(seeded RNG), so cold and warm evaluate the same inventory whether it
was replayed or restored.

Knobs: ``GATEKEEPER_SMOKE_N`` (resources, default 300).  The snapshot
directory must not be shared across different ``GATEKEEPER_SMOKE_N``
values (the store snapshot is keyed by target, not by size).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import sys
import time


def _verdict_digest(results) -> str:
    items = sorted(
        ((r.constraint or {}).get("kind", ""),
         ((r.constraint or {}).get("metadata") or {}).get("name", ""),
         (r.resource or {}).get("kind", ""),
         str(((r.resource or {}).get("metadata") or {}).get("namespace")),
         ((r.resource or {}).get("metadata") or {}).get("name", ""),
         r.msg)
        for r in results)
    return hashlib.sha256(repr(items).encode()).hexdigest()[:16]


def main() -> int:
    n = int(os.environ.get("GATEKEEPER_SMOKE_N", "300"))
    # translation validation on by default here: the warm process must
    # load every Certificate from the cert snapshot tier instead of
    # re-running the small-model check ("validations" == 0 warm)
    os.environ.setdefault("GATEKEEPER_TRANSVAL", "warn")
    # same contract for the Stage-6 partition plans: the warm process
    # must load every plan from the sp snapshot tier ("shardplans" == 0)
    os.environ.setdefault("GATEKEEPER_SHARDPLAN", "warn")
    # and for the Stage-7 compile surfaces: the warm process must load
    # every certificate from the cs tier ("compile_surfaces" == 0) AND
    # skip the startup AOT compile storm via the cs-tier geometry stamp
    # ("aot_precompiles" == 0)
    os.environ.setdefault("GATEKEEPER_COMPILE_SURFACE", "warn")
    # and for the Stage-8 memory surfaces: the warm process must load
    # every certificate from the ms tier ("memsurfaces" == 0)
    os.environ.setdefault("GATEKEEPER_HBM_BUDGET", "warn")

    # imports before the clock starts: interpreter + jax import cost is
    # identical for cold and warm processes and would only dilute the
    # startup ratio the smoke stage asserts on
    from gatekeeper_tpu.analysis import (compilesurface, footprint,
                                         memsurface, shardplan, transval)
    from gatekeeper_tpu.ops import regex_dfa
    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.client.interface import QueryOpts
    from gatekeeper_tpu.engine import jax_driver as jd_mod
    from gatekeeper_tpu.library import all_docs, make_mixed
    from gatekeeper_tpu.resilience import snapshot as snap
    from gatekeeper_tpu.target.k8s import K8sValidationTarget, TARGET_NAME

    if not snap.enabled():
        print(json.dumps({"error": "GATEKEEPER_SNAPSHOT_DIR not set"}))
        return 2

    # count actual Rego lowerings: the warm path must never reach
    # lower_template (the acceptance criterion "no re-lowering")
    calls = {"lowerings": 0}
    orig_lower = jd_mod.lower_template

    def counting_lower(*a, **k):
        calls["lowerings"] += 1
        return orig_lower(*a, **k)
    jd_mod.lower_template = counting_lower

    t0 = time.perf_counter()
    jd = jd_mod.JaxDriver()
    client = Backend(jd).new_client([K8sValidationTarget()])
    for tdoc, cdoc in all_docs():
        client.add_template(tdoc)
        client.add_constraint(cdoc)
    restored = jd.restore_store_snapshot(TARGET_NAME)
    if not restored:
        client.add_data_batch(make_mixed(random.Random(5), n))
    jd.prepare_audit(TARGET_NAME)
    # startup = driver + template install + inventory + audit prep (the
    # whole-policy-set dedup plan): the window warm restart actually
    # accelerates (parse/vet/lower/verify/plan skipped, store restored
    # instead of replicated).  The sweep after this line is workload,
    # identical cold and warm by construction.
    startup_s = time.perf_counter() - t0
    results, _trace = jd.query_audit(TARGET_NAME, QueryOpts(full=True))
    serving_s = time.perf_counter() - t0

    jd.save_store_snapshot(TARGET_NAME)
    st = jd.state[TARGET_NAME]
    rep = snap.restart_report()
    out = {
        "startup_seconds": round(startup_s, 3),
        "serving_seconds": round(serving_s, 3),
        "restart_persistent_cache_hits":
            rep["restart_persistent_cache_hits"],
        "restart_persistent_cache_misses":
            rep["restart_persistent_cache_misses"],
        "lowerings": calls["lowerings"],
        "templates": len(st.templates),
        "store_restored": restored,
        "n_rows": len(st.table),
        "n_results": len(results),
        "verdict_digest": _verdict_digest(results),
        "validations": transval.validations_run,
        "footprints": footprint.analyses_run,
        "shardplans": shardplan.analyses_run,
        "dfa_compiles": regex_dfa.compiles_run,
        "compile_surfaces": compilesurface.analyses_run,
        "aot_precompiles": compilesurface.precompiles_run,
        "memsurfaces": memsurface.analyses_run,
    }
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
