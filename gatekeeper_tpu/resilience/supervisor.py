"""Backend supervisor: a state machine over the jax device backend.

Replaces the one-shot, one-way ``device_probe.mark_unavailable``
demotion with supervised transitions::

    healthy ──failure──> degraded ──re-probe──> recovering ──ok──> healthy
                             ^                       │
                             └───────fail────────────┘
    (any)  ──poisoned failure──> poisoned            (terminal)

Serving paths consult ``use_device()`` per dispatch (JaxDriver's
``scalar_only`` is a property over it), so a mid-sweep demotion routes
the *remaining* kinds through the scalar oracle while the sweep still
completes with correct verdicts — SURVEY §5's "device failure =>
recompile/retry on CPU fallback", but now with a road back.

Re-probes are *bounded* (a tiny device op on a daemon thread with a
join deadline — never an unbounded jax call from the supervisor) and
run with exponential backoff from a background thread.  ``poisoned``
is terminal: a probe that timed out may still hold jax's backend-init
lock, so re-entering jax from this process is never safe (this
preserves the old ``mark_unavailable`` contract, which now routes here
with ``poisoned=True``).

On the degraded->healthy edge, registered recovery listeners fire
(drivers drop compiled-fn caches and re-jit onto the recovered
backend; the audit manager re-warms; controllers re-reconcile
templates).  State, reason, and transition counts are exported through
``utils.metrics`` and surfaced by ``probe --health`` and the webhook's
``/healthz``.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Callable

from gatekeeper_tpu.utils.log import logger
from gatekeeper_tpu.utils.metrics import Metrics

_log = logger("supervisor")

HEALTHY = "healthy"
DEGRADED = "degraded"
RECOVERING = "recovering"
POISONED = "poisoned"

# stable numeric encoding for the state gauge (dashboards alert on >0)
STATE_CODE = {HEALTHY: 0, RECOVERING: 1, DEGRADED: 2, POISONED: 3}

DEFAULT_BACKOFF_S = 2.0
BACKOFF_FACTOR = 2.0
BACKOFF_CAP_S = 60.0
DEFAULT_REPROBE_TIMEOUT_S = 10.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class BackendSupervisor:
    """Process-wide singleton (``get_supervisor()``); the verdict is
    per-process by nature, like the probe result it supersedes."""

    def __init__(self, metrics: Metrics | None = None):
        self.metrics = metrics or Metrics()
        self._lock = threading.RLock()
        self._state = HEALTHY
        self._reason = ""
        self._since = time.time()
        self._last_probe_at: float | None = None
        self._last_ok_at: float | None = None
        self._reprobe_attempts = 0
        self._platform = ""
        self._n_devices = 0
        # recovery listeners: weakly-held (owner, method-name) pairs so
        # short-lived drivers don't accumulate in the singleton, plus
        # strong plain callables for process-lifetime hooks.
        self._weak_listeners: list[tuple[weakref.ref, str]] = []
        self._listeners: list[Callable[[], None]] = []
        self._reprobe_thread: threading.Thread | None = None
        self._stop_evt = threading.Event()
        self._seeded = False
        self._seed_lock = threading.Lock()
        self._gauge_state()

    # ------------------------------------------------------------------
    # seeding from the initial probe verdict

    def _ensure_seeded(self) -> None:
        if self._seeded:
            return
        with self._seed_lock:
            if self._seeded:
                return
            # probe outside self._lock (first contact can take ~45s);
            # concurrent readers block here, exactly as they blocked on
            # probe_devices() before the supervisor existed
            from gatekeeper_tpu.utils import device_probe
            res = device_probe.probe_devices()
            with self._lock:
                if res.ok:
                    self._state = HEALTHY
                    self._platform = res.platform
                    self._n_devices = res.n_devices
                    self._last_ok_at = time.time()
                else:
                    self._state = POISONED if res.poisoned else DEGRADED
                    self.metrics.counter("backend_degradations").inc()
                self._reason = res.reason
                self._last_probe_at = time.time()
                self._gauge_state()
            self._seeded = True
        if not res.ok and not res.poisoned:
            self._maybe_start_reprobe_loop()

    # ------------------------------------------------------------------
    # read side (hot path: lock-free state read)

    @property
    def state(self) -> str:
        self._ensure_seeded()
        return self._state

    @property
    def reason(self) -> str:
        self._ensure_seeded()
        return self._reason

    def use_device(self) -> bool:
        """May callers dispatch onto the jax device path right now?
        Consulted per dispatch (driver ``scalar_only`` property), so it
        must stay cheap: one attribute read after the first call."""
        if not self._seeded:
            self._ensure_seeded()
        return self._state == HEALTHY

    def status(self) -> dict:
        self._ensure_seeded()
        with self._lock:
            return {
                "state": self._state,
                "reason": self._reason,
                "since": self._since,
                "last_probe_at": self._last_probe_at,
                "last_ok_at": self._last_ok_at,
                "reprobe_attempts": self._reprobe_attempts,
                "platform": self._platform,
                "n_devices": self._n_devices,
                "backend": (self._platform if self._state == HEALTHY
                            else "cpu-fallback"),
            }

    # ------------------------------------------------------------------
    # transitions

    def report_failure(self, reason: str, poisoned: bool = False) -> None:
        """An execution (or the probe) discovered the backend is gone.
        ``poisoned=True`` is terminal — a hung jax op may hold the
        backend-init lock, so this process must never re-enter jax on
        the device path (the old ``mark_unavailable`` contract)."""
        self._ensure_seeded()
        with self._lock:
            if self._state == POISONED:
                return
            target = POISONED if poisoned else DEGRADED
            if self._state == target and not poisoned:
                self._reason = reason
                return
            prev = self._state
            self._state = target
            self._reason = reason
            self._since = time.time()
            self.metrics.counter("backend_degradations").inc()
            self._gauge_state()
        _log.warning("backend degraded", state=target, reason=reason)
        # flight recorder: the degradation edge is THE moment the last
        # minute of evidence matters — record the transition, then dump
        # the ring (outside the lock; dump does file I/O)
        self._flight_transition(prev, target, reason, dump=True)
        self._pin_children_to_cpu()
        if not poisoned:
            self._maybe_start_reprobe_loop()

    @staticmethod
    def _flight_transition(prev: str, new: str, reason: str,
                           dump: bool = False) -> None:
        """Record a supervisor transition in the flight recorder and
        optionally dump the ring.  Best-effort: observability must
        never alter supervisor behavior."""
        try:
            from gatekeeper_tpu.obs.flightrecorder import \
                get_flight_recorder
            rec = get_flight_recorder()
            rec.record("supervisor_transition", frm=prev, to=new,
                       reason=reason)
            if dump:
                rec.dump(f"supervisor:{new}")
        except Exception:   # noqa: BLE001
            pass

    def reprobe_now(self, timeout_s: float | None = None) -> bool:
        """Synchronous bounded re-probe; True iff the backend is (or
        becomes) healthy.  Poisoned processes never re-probe."""
        self._ensure_seeded()
        with self._lock:
            if self._state == POISONED:
                return False
            if self._state == HEALTHY:
                return True
            prev = self._state
            self._state = RECOVERING
            self._gauge_state()
        self._flight_transition(prev, RECOVERING, "re-probe")
        if timeout_s is None:
            timeout_s = _env_float("GATEKEEPER_SUPERVISOR_REPROBE_TIMEOUT_S",
                                   DEFAULT_REPROBE_TIMEOUT_S)
        ok, n, platform, err = self._device_check(timeout_s)
        now = time.time()
        with self._lock:
            self._last_probe_at = now
            self._reprobe_attempts += 1
            if ok:
                self._state = HEALTHY
                self._reason = f"recovered: {n} {platform} device(s)"
                self._since = now
                self._last_ok_at = now
                self._platform = platform
                self._n_devices = n
                self.metrics.counter("backend_recoveries").inc()
            else:
                self._state = DEGRADED
                self.metrics.counter("backend_reprobe_failures").inc()
                if err:
                    self._reason = f"{self._reason} (re-probe: {err})" \
                        if "(re-probe:" not in self._reason else self._reason
            self._gauge_state()
        self._flight_transition(
            RECOVERING, HEALTHY if ok else DEGRADED,
            self._reason if ok else "re-probe failed")
        if ok:
            _log.info("backend recovered", platform=platform, n_devices=n)
            self._install_probe_result(True, n, platform)
            self._fire_recovery()
        return ok

    def _device_check(self, timeout_s: float):
        """Run one tiny jax device op on a daemon thread with a join
        deadline.  Returns (ok, n_devices, platform, err)."""
        from gatekeeper_tpu.resilience import faults
        box: dict = {}

        def _check():
            try:
                if (faults.active("probe_hang")
                        or os.environ.get("GATEKEEPER_PROBE_TEST_HANG") == "1"):
                    time.sleep(3600)    # simulated dead tunnel
                import jax
                import jax.numpy as jnp
                devs = jax.devices()
                # an actual dispatch, not just device enumeration: a
                # half-dead backend can enumerate but not execute
                jnp.add(jnp.int32(1), jnp.int32(1)).block_until_ready()
                box["devs"] = (len(devs), devs[0].platform)
            except BaseException as e:   # noqa: BLE001 — report, don't die
                box["err"] = e

        t = threading.Thread(target=_check, name="backend-reprobe",
                             daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            return False, 0, "", f"re-probe hung past {timeout_s:.0f}s"
        if "err" in box:
            return False, 0, "", str(box["err"])
        n, platform = box["devs"]
        return True, n, platform, ""

    # ------------------------------------------------------------------
    # background re-probe loop (exponential backoff)

    def _maybe_start_reprobe_loop(self) -> None:
        if os.environ.get("GATEKEEPER_SUPERVISOR_REPROBE", "1") == "0":
            return
        with self._lock:
            if (self._reprobe_thread is not None
                    and self._reprobe_thread.is_alive()):
                return
            self._stop_evt.clear()
            self._reprobe_thread = threading.Thread(
                target=self._reprobe_loop, name="backend-reprobe-loop",
                daemon=True)
            self._reprobe_thread.start()

    def _reprobe_loop(self) -> None:
        delay = _env_float("GATEKEEPER_SUPERVISOR_BACKOFF_S",
                           DEFAULT_BACKOFF_S)
        while True:
            if self._stop_evt.wait(delay):
                return
            with self._lock:
                st = self._state
            if st in (HEALTHY, POISONED):
                return
            if self.reprobe_now():
                return
            delay = min(delay * BACKOFF_FACTOR, BACKOFF_CAP_S)

    # ------------------------------------------------------------------
    # recovery listeners

    def on_recovery(self, fn: Callable[[], None]) -> None:
        """Register a process-lifetime recovery hook (strong ref)."""
        with self._lock:
            self._listeners.append(fn)

    def add_recovery_listener(self, owner: object, method: str) -> None:
        """Register ``getattr(owner, method)()`` to run on recovery.
        The owner is held weakly: short-lived drivers (tests construct
        hundreds) don't leak into the process singleton."""
        with self._lock:
            self._weak_listeners.append((weakref.ref(owner), method))

    def _fire_recovery(self) -> None:
        with self._lock:
            weak = list(self._weak_listeners)
            strong = list(self._listeners)
        live: list[tuple[weakref.ref, str]] = []
        for ref, method in weak:
            owner = ref()
            if owner is None:
                continue
            live.append((ref, method))
            try:
                getattr(owner, method)()
            except Exception as e:   # noqa: BLE001 — a listener must not
                _log.warning("recovery listener failed",   # break recovery
                             listener=method, error=e)
        with self._lock:
            self._weak_listeners = live
        for fn in strong:
            try:
                fn()
            except Exception as e:   # noqa: BLE001
                _log.warning("recovery listener failed",
                             listener=getattr(fn, "__name__", "fn"), error=e)

    # ------------------------------------------------------------------
    # plumbing

    def _gauge_state(self) -> None:
        self.metrics.gauge("backend_supervisor_state").set(
            STATE_CODE.get(self._state, -1))

    def _pin_children_to_cpu(self) -> None:
        """Keep ``device_probe.child_env`` coherent with supervisor
        state: while degraded, children must not walk into the same
        dead plugin (and the probe verdict they'd inherit agrees)."""
        from gatekeeper_tpu.utils import device_probe
        with self._lock:
            poisoned = self._state == POISONED
            reason = self._reason
        device_probe._install_result(device_probe.ProbeResult(
            False, 0, "", poisoned, reason))
        os.environ["JAX_PLATFORMS"] = "cpu"

    def _install_probe_result(self, ok: bool, n: int, platform: str) -> None:
        from gatekeeper_tpu.utils import device_probe
        device_probe._install_result(device_probe.ProbeResult(
            ok, n, platform, False, self._reason))
        # drop our cpu pin only if the recovered platform is not cpu
        # (a cpu-pinned process that recovered cpu stays pinned)
        if ok and platform != "cpu" \
                and os.environ.get("JAX_PLATFORMS") == "cpu":
            os.environ.pop("JAX_PLATFORMS", None)

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._reprobe_thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)


    def peek_state(self) -> str:
        """State WITHOUT triggering the seed probe — for hot-path
        readers (the overload ladder runs on every admission request)
        that must never pay the ~45s first-contact device probe.  An
        unseeded supervisor reads as healthy: the ladder only wants
        degradation signals that some dispatch already discovered."""
        return self._state if self._seeded else HEALTHY


_SUP: BackendSupervisor | None = None
_SUP_LOCK = threading.Lock()


def get_supervisor() -> BackendSupervisor:
    global _SUP
    if _SUP is not None:
        return _SUP
    with _SUP_LOCK:
        if _SUP is None:
            _SUP = BackendSupervisor()
        return _SUP


def peek_state() -> str:
    """Module-level hot-path read: current supervisor state without
    creating the singleton or triggering its seed probe.  The overload
    ladder calls this per admission request."""
    sup = _SUP
    return sup.peek_state() if sup is not None else HEALTHY


def reset_for_tests() -> None:
    """Drop the singleton (tests only; pairs with
    ``device_probe.reset_for_tests``, which calls this)."""
    global _SUP
    with _SUP_LOCK:
        sup, _SUP = _SUP, None
    if sup is not None:
        sup.stop()
