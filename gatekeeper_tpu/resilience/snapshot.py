"""Warm-restart persistence: lowered IR, dedup plans, store snapshots.

The XLA compilation cache (utils/compile_cache.py) only persists the
*executable* tier, and only on non-cpu backends — which is exactly why
``restart_persistent_cache_hits`` sat at 0: on the cpu platform the
XLA tier is disabled by default (deserialized cpu AOT executables
crash under concurrent dispatch), and the artifacts that dominate cold
start — Rego lowering, IR verification, dedup planning, store
replication — were never persisted at all.  This module persists those
tiers, on every backend, alongside the XLA cache:

- **template IR**: the full ``LoweredProgram`` per template, keyed by
  a digest of (kind, target, Rego source).  A ``None`` payload is a
  negative certificate — the template is known scalar-only
  (CannotLower), so the restarted pod skips the lowering *attempt*
  too.
- **parsed module**: the template's parsed + vetted Rego AST, keyed
  like the IR — a warm client skips parse, hygiene checks, and the
  stage-1 vet (all deterministic in the source, which keys the entry).
- **dedup plan**: the whole-policy-set cross-template predicate dedup
  plan, keyed by the digest of the installed set.
- **store snapshot**: the columnar store's rows + interned string
  table as plain data (``ResourceTable.snapshot_state()``).

Activation is explicit: snapshots read/write only when
``GATEKEEPER_SNAPSHOT_DIR`` is set (bench, ci restart-smoke, and the
manager set it; unit tests stay hermetic by default).

**Why a custom pickler.**  A ``LoweredProgram``'s PrepSpec carries
*local* functions (TableReq.fn / PTableReq.fn / CSetReq.fn close over
the Lowerer and AST terms), which stdlib pickle rejects.  The pickler
below serializes such functions as (marshalled code object, defining
module, closure cell contents) and rebuilds them with
``types.FunctionType`` against the live module globals.  Marshalled
code is CPython-bytecode-version specific, so entries are keyed by
``host_fingerprint()`` + the exact Python version + a format version,
every file is length- and sha256-checked, and *any* load failure —
truncation, version skew, unpickle error — deletes the entry and falls
back to a cold rebuild.  Corruption can cost a re-lower; it can never
crash startup or poison a verdict.
"""

from __future__ import annotations

import hashlib
import importlib
import io
import json
import marshal
import os
import pickle
import sys
import threading
import types

from gatekeeper_tpu.utils.log import logger

_log = logger("snapshot")

MAGIC = "gatekeeper-tpu-snapshot"
VERSION = 1


# ----------------------------------------------------------------------
# stats (feeds restart_report and bench restart counters)

class SnapshotStats:
    _FIELDS = ("ir_hits", "ir_misses", "mod_hits", "mod_misses",
               "plan_hits", "plan_misses",
               "store_hits", "store_misses",
               "cert_hits", "cert_misses",
               "fp_hits", "fp_misses",
               "sp_hits", "sp_misses",
               "pg_hits", "pg_misses",
               "dfa_hits", "dfa_misses",
               "ro_hits", "ro_misses",
               "cs_hits", "cs_misses",
               "ms_hits", "ms_misses", "corrupt_discarded",
               "saves", "save_errors")

    def __init__(self):
        self._lock = threading.Lock()
        for f in self._FIELDS:
            setattr(self, f, 0)

    def bump(self, field: str, by: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + by)

    def snapshot(self) -> dict:
        with self._lock:
            return {f: getattr(self, f) for f in self._FIELDS}

    def delta_since(self, snap: dict) -> dict:
        cur = self.snapshot()
        return {f: cur[f] - snap.get(f, 0) for f in self._FIELDS}


stats = SnapshotStats()


# ----------------------------------------------------------------------
# closure-aware pickling

def _rebuild_fn(code_b: bytes, module: str, name: str, defaults,
                kwdefaults, cells):
    code = marshal.loads(code_b)
    g = importlib.import_module(module).__dict__
    closure = None
    if cells is not None:
        closure = tuple(types.CellType(v) for v in cells)
    fn = types.FunctionType(code, g, name, defaults, closure)
    if kwdefaults:
        fn.__kwdefaults__ = dict(kwdefaults)
    return fn


def _rebuild_lock(kind: str):
    if kind == "rlock":
        return threading.RLock()
    if kind == "event":
        return threading.Event()
    if kind == "condition":
        return threading.Condition()
    return threading.Lock()


_LOCK_T = type(threading.Lock())
_RLOCK_T = type(threading.RLock())


class _Pickler(pickle.Pickler):
    """stdlib pickle + reducers for the artifacts a LoweredProgram
    actually carries: local functions/lambdas (by marshalled code),
    synchronization primitives (rebuilt fresh), and modules (by
    name)."""

    def reducer_override(self, obj):
        if isinstance(obj, types.FunctionType):
            qual = getattr(obj, "__qualname__", "")
            if "<locals>" in qual or obj.__name__ == "<lambda>":
                cells = None
                if obj.__closure__:
                    # cell_contents raises ValueError on an empty cell
                    # (never observed in lowered IR; a PicklingError
                    # here just skips the save)
                    cells = tuple(c.cell_contents for c in obj.__closure__)
                return (_rebuild_fn,
                        (marshal.dumps(obj.__code__),
                         obj.__module__ or "builtins", obj.__name__,
                         obj.__defaults__, obj.__kwdefaults__, cells))
            return NotImplemented
        if isinstance(obj, _LOCK_T):
            return (_rebuild_lock, ("lock",))
        if isinstance(obj, _RLOCK_T):
            return (_rebuild_lock, ("rlock",))
        if isinstance(obj, threading.Event):
            return (_rebuild_lock, ("event",))
        if isinstance(obj, threading.Condition):
            return (_rebuild_lock, ("condition",))
        if isinstance(obj, types.ModuleType):
            return (importlib.import_module, (obj.__name__,))
        return NotImplemented


def dumps(obj) -> bytes:
    buf = io.BytesIO()
    _Pickler(buf, protocol=4).dump(obj)
    return buf.getvalue()


# ----------------------------------------------------------------------
# the on-disk store

def enabled() -> bool:
    return bool(os.environ.get("GATEKEEPER_SNAPSHOT_DIR"))


def _python_tag() -> str:
    return f"cpython-{sys.version_info[0]}.{sys.version_info[1]}"


def snapshot_dir(create: bool = False, root: str | None = None) -> str | None:
    """Per-(host, python, format-version) subdirectory — marshalled
    code never crosses an interpreter or format boundary.  ``root``
    overrides the env var (historical-snapshot reads, whatif/replay.py)."""
    root = root or os.environ.get("GATEKEEPER_SNAPSHOT_DIR")
    if not root:
        return None
    from gatekeeper_tpu.utils.compile_cache import host_fingerprint
    d = os.path.join(root,
                     f"{host_fingerprint()}-{_python_tag()}-v{VERSION}")
    if create:
        os.makedirs(d, exist_ok=True)
    return d


def _entry_path(category: str, key: str,
                root: str | None = None) -> str | None:
    d = snapshot_dir(root=root)
    if d is None:
        return None
    h = hashlib.sha256(key.encode()).hexdigest()[:24]
    return os.path.join(d, f"{category}-{h}.snap")


def _discard(path: str, why: str) -> None:
    stats.bump("corrupt_discarded")
    _log.warning("discarding snapshot entry; will rebuild",
                 path=os.path.basename(path), why=why)
    try:
        os.remove(path)
    except OSError:
        pass


def _write_entry(category: str, key: str, payload: bytes) -> bool:
    path = _entry_path(category, key)
    if path is None:
        return False
    try:
        snapshot_dir(create=True)
        header = json.dumps({
            "magic": MAGIC, "version": VERSION, "python": _python_tag(),
            "key": key, "sha256": hashlib.sha256(payload).hexdigest(),
            "len": len(payload),
        }).encode()
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(header + b"\n" + payload)
        os.replace(tmp, path)   # atomic: readers see old-or-new, never torn
        stats.bump("saves")
        return True
    except Exception as e:   # noqa: BLE001 — persistence is best-effort
        stats.bump("save_errors")
        _log.warning("snapshot save failed", category=category, error=e)
        return False


def _read_entry(category: str, key: str, root: str | None = None):
    """Returns the unpickled payload in a 1-tuple, or None on miss.
    Any validation or unpickle failure deletes the entry (rebuild on
    the cold path) — corruption must never crash startup."""
    path = _entry_path(category, key, root=root)
    if path is None or not os.path.exists(path):
        return None
    from gatekeeper_tpu.resilience import faults
    if faults.take("snapshot_corrupt"):
        _discard(path, "fault injection: snapshot_corrupt")
        return None
    try:
        with open(path, "rb") as f:
            raw = f.read()
        nl = raw.index(b"\n")
        hdr = json.loads(raw[:nl])
        payload = raw[nl + 1:]
        if hdr.get("magic") != MAGIC:
            _discard(path, "bad magic")
            return None
        if hdr.get("version") != VERSION or hdr.get("python") != _python_tag():
            _discard(path, "version mismatch")
            return None
        if hdr.get("key") != key:
            _discard(path, "key mismatch")
            return None
        if hdr.get("len") != len(payload):
            _discard(path, "truncated")
            return None
        if hdr.get("sha256") != hashlib.sha256(payload).hexdigest():
            _discard(path, "checksum mismatch")
            return None
        return (pickle.loads(payload),)
    except Exception as e:   # noqa: BLE001 — any failure => cold rebuild
        _discard(path, f"load error: {e}")
        return None


# ----------------------------------------------------------------------
# typed entry points

def template_digest(kind: str, target: str, source: str) -> str:
    # GATEKEEPER_DFA changes what lower() emits (dfa_match nodes vs host
    # lookup tables), so IR entries must never cross flag modes — fold
    # the mode into the digest rather than the VERSION so flipping the
    # flag back and forth reuses both snapshot populations.
    from gatekeeper_tpu.ops.regex_dfa import dfa_enabled
    mode = "dfa" if dfa_enabled() else "nodfa"
    h = hashlib.sha256(
        f"{kind}\x00{target}\x00{source}\x00v{VERSION}\x00{mode}".encode())
    return h.hexdigest()[:24]


def load_template_ir(kind: str, target: str, source: str):
    """None = miss.  A 1-tuple hit carries the LoweredProgram, or None
    when the saved outcome was CannotLower (skip the attempt too)."""
    if not enabled():
        return None
    key = f"ir:{template_digest(kind, target, source)}"
    got = _read_entry("ir", key)
    stats.bump("ir_hits" if got is not None else "ir_misses")
    return got


def save_template_ir(kind: str, target: str, source: str, lowered) -> bool:
    if not enabled():
        return False
    key = f"ir:{template_digest(kind, target, source)}"
    try:
        payload = dumps(lowered)
    except Exception as e:   # noqa: BLE001 — an unpicklable program
        stats.bump("save_errors")   # just stays cold-start-only
        _log.warning("lowered IR not snapshottable", kind=kind, error=e)
        return False
    return _write_entry("ir", key, payload)


def load_template_module(kind: str, target: str, source: str):
    """None = miss.  A 1-tuple hit carries ``(Module, uses_inventory)``
    — the parsed + hygiene-checked + vetted AST.  Entries are written
    only after the stage-1 vet passes, so a hit certifies the source as
    vetted; the Interpreter is rebuilt fresh from the Module (its side
    tables are id()-keyed and must never cross a process boundary)."""
    if not enabled():
        return None
    key = f"mod:{template_digest(kind, target, source)}"
    got = _read_entry("mod", key)
    stats.bump("mod_hits" if got is not None else "mod_misses")
    return got


def save_template_module(kind: str, target: str, source: str,
                         module_and_flags) -> bool:
    if not enabled():
        return False
    key = f"mod:{template_digest(kind, target, source)}"
    try:
        payload = dumps(module_and_flags)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("parsed module not snapshottable", kind=kind, error=e)
        return False
    return _write_entry("mod", key, payload)


def policyset_digest(parts: list[str]) -> str:
    h = hashlib.sha256()
    for p in sorted(parts):
        h.update(p.encode())
        h.update(b"\x00")
    return h.hexdigest()[:24]


def load_dedup_plan(digest: str):
    if not enabled():
        return None
    got = _read_entry("plan", f"plan:{digest}")
    stats.bump("plan_hits" if got is not None else "plan_misses")
    return got


def save_dedup_plan(digest: str, plan) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(plan)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("dedup plan not snapshottable", error=e)
        return False
    return _write_entry("plan", f"plan:{digest}", payload)


def load_cert(digest: str):
    """Fifth tier: translation-validation certificates, keyed by the
    transval certificate digest (program cache_key + constraint docs +
    budget + validator version).  A warm restart that reuses the
    snapshotted lowered IR also reuses its certificate, so it re-runs
    zero validations (analysis/transval.certify)."""
    if not enabled():
        return None
    got = _read_entry("cert", f"cert:{digest}")
    stats.bump("cert_hits" if got is not None else "cert_misses")
    return got


def save_cert(digest: str, cert) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(cert)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("certificate not snapshottable", error=e)
        return False
    return _write_entry("cert", f"cert:{digest}", payload)


def load_footprint(digest: str):
    """Sixth tier: Stage-5 dependency footprints, keyed by the
    footprint digest (program cache_key + prep-spec signature +
    analyzer version).  A warm restart that reuses the snapshotted
    lowered IR also reuses its footprint, so it re-runs zero
    dependency analyses (analysis/footprint.certify)."""
    if not enabled():
        return None
    got = _read_entry("fp", f"fp:{digest}")
    stats.bump("fp_hits" if got is not None else "fp_misses")
    return got


def save_footprint(digest: str, fp) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(fp)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("footprint not snapshottable", error=e)
        return False
    return _write_entry("fp", f"fp:{digest}", payload)


def load_shardplan(digest: str):
    """Seventh tier: Stage-6 partition plans, keyed by the shardplan
    digest (program cache_key + prep-spec signature + analyzer
    version).  A warm restart that reuses the snapshotted lowered IR
    also reuses its partition plan, so it re-runs zero sharding
    analyses (analysis/shardplan.certify)."""
    if not enabled():
        return None
    got = _read_entry("sp", f"sp:{digest}")
    stats.bump("sp_hits" if got is not None else "sp_misses")
    return got


def save_shardplan(digest: str, plan) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(plan)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("shardplan not snapshottable", error=e)
        return False
    return _write_entry("sp", f"sp:{digest}", payload)


def load_compilesurface(digest: str):
    """Tenth tier: Stage-7 compile-surface certificates
    (analysis/compilesurface.py), keyed by program cache_key +
    pad-geometry version + ladder caps — plus the AOT-precompile
    geometry stamps JaxDriver.precompile writes under ``aot:`` keys.
    A warm restart reuses both: zero surface analyses AND zero AOT
    executable compiles at startup (smoke's ``compile_surfaces`` /
    ``aot_precompiles`` == 0 warm)."""
    if not enabled():
        return None
    got = _read_entry("cs", f"cs:{digest}")
    stats.bump("cs_hits" if got is not None else "cs_misses")
    return got


def save_compilesurface(digest: str, cert) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(cert)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("compile surface not snapshottable", error=e)
        return False
    return _write_entry("cs", f"cs:{digest}", payload)


def load_memsurface(digest: str):
    """Eleventh tier: Stage-8 memory-surface certificates
    (analysis/memsurface.py), keyed by program cache_key +
    pad-geometry version + MS deployment caps.  A warm restart
    re-runs zero peak-HBM analyses (smoke's ``memsurfaces`` == 0
    warm); a caps or geometry change invalidates by key mismatch."""
    if not enabled():
        return None
    got = _read_entry("ms", f"ms:{digest}")
    stats.bump("ms_hits" if got is not None else "ms_misses")
    return got


def save_memsurface(digest: str, cert) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(cert)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("memory surface not snapshottable", error=e)
        return False
    return _write_entry("ms", f"ms:{digest}", payload)


def load_dfa(digest: str):
    """Eighth tier: compiled regex byte-DFA tables (ops/regex_dfa),
    keyed by the pattern + DFA_VERSION digest.  A warm restart that
    reuses the snapshotted lowered IR also reuses its DFA tables, so
    it compiles zero automata (smoke's ``dfa_compiles`` == 0 warm).
    A hit may carry None — a negative certificate for a pattern known
    to fall outside the supported subset (skip the compile attempt)."""
    if not enabled():
        return None
    got = _read_entry("dfa", f"dfa:{digest}")
    stats.bump("dfa_hits" if got is not None else "dfa_misses")
    return got


def save_dfa(digest: str, dfa) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(dfa)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("dfa table not snapshottable", error=e)
        return False
    return _write_entry("dfa", f"dfa:{digest}", payload)


def load_store(target: str, root: str | None = None):
    """Load the store tier.  With ``root``, read from that snapshot
    root explicitly (a *historical* snapshot directory, independent of
    GATEKEEPER_SNAPSHOT_DIR) — the replay path's time machine."""
    if root is None and not enabled():
        return None
    got = _read_entry("store", f"store:{target}", root=root)
    stats.bump("store_hits" if got is not None else "store_misses")
    return got


def save_store(target: str, state) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(state)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("store snapshot failed to serialize", error=e)
        return False
    return _write_entry("store", f"store:{target}", payload)


def load_pagemap(target: str, root: str | None = None):
    """Load the pagemap tier: the VerdictLedger's per-kind confirmed
    violation sets, saved alongside the store tier so a warm restart
    adopts its verdicts (revalidated per kind by constraint digest +
    row count) instead of paying a cold full build."""
    if root is None and not enabled():
        return None
    got = _read_entry("pg", f"pg:{target}", root=root)
    stats.bump("pg_hits" if got is not None else "pg_misses")
    return got


def save_pagemap(target: str, payload_obj) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(payload_obj)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("pagemap snapshot failed to serialize", error=e)
        return False
    return _write_entry("pg", f"pg:{target}", payload)


def load_rollout(name: str, root: str | None = None):
    """Ninth tier: promotion-rollout state (rollout/controller.py),
    keyed by rollout name.  A warm restart resumes an in-flight
    promotion at the same rung — state machine position, installed
    enforcement rung, and the prior-doc set a rollback would restore."""
    if root is None and not enabled():
        return None
    got = _read_entry("ro", f"ro:{name}", root=root)
    stats.bump("ro_hits" if got is not None else "ro_misses")
    return got


def save_rollout(name: str, payload_obj) -> bool:
    if not enabled():
        return False
    try:
        payload = dumps(payload_obj)
    except Exception as e:   # noqa: BLE001
        stats.bump("save_errors")
        _log.warning("rollout state not snapshottable", error=e)
        return False
    return _write_entry("ro", f"ro:{name}", payload)


# ----------------------------------------------------------------------
# the combined restart counter (the keying-bug fix)

def tier_counts(s: dict) -> tuple[int, int]:
    """(hits, misses) summed across every snapshot tier of a stats dict
    (works on both ``stats.snapshot()`` absolutes and ``delta_since``
    deltas)."""
    hits = (s["ir_hits"] + s["mod_hits"] + s["plan_hits"]
            + s["store_hits"] + s.get("cert_hits", 0)
            + s.get("fp_hits", 0) + s.get("sp_hits", 0)
            + s.get("pg_hits", 0) + s.get("dfa_hits", 0)
            + s.get("ro_hits", 0) + s.get("cs_hits", 0)
            + s.get("ms_hits", 0))
    misses = (s["ir_misses"] + s["mod_misses"] + s["plan_misses"]
              + s["store_misses"] + s.get("cert_misses", 0)
              + s.get("fp_misses", 0) + s.get("sp_misses", 0)
              + s.get("pg_misses", 0) + s.get("dfa_misses", 0)
              + s.get("ro_misses", 0) + s.get("cs_misses", 0)
              + s.get("ms_misses", 0))
    return hits, misses


def restart_report() -> dict:
    """One number that actually reflects warm-restart reuse.

    The old bench counter read only the XLA event listener — on the
    cpu platform that tier is off by default, so the counter was
    structurally 0.  The fixed counter sums every persistence tier:
    XLA executable hits (when that tier is on) + lowered-IR hits +
    dedup-plan hits + store-snapshot hits.
    """
    from gatekeeper_tpu.utils.compile_cache import persistent_cache_stats
    x = persistent_cache_stats().snapshot()
    s = stats.snapshot()
    t_hits, t_misses = tier_counts(s)
    hits = x.get("hits", 0) + t_hits
    misses = x.get("misses", 0) + t_misses
    return {
        "restart_persistent_cache_hits": hits,
        "restart_persistent_cache_misses": misses,
        "xla": x,
        "snapshot": s,
    }
