"""Seeded chaos soak: sustained admission + audit load under injected
faults, with invariants checked after every event.

PR-7 gave the stack fault seams (``probe_hang``, ``device_lost``,
``snapshot_corrupt``) and a supervisor that survives them one at a
time, under a test that injects exactly one fault into a quiet process.
This module is the adversarial version: a deterministic, seeded
schedule arms faults — including the overload-specific
``slow_provider`` and ``queue_storm`` — while concurrent admission
workers and an audit loop keep the engine busy, and a monitor enforces
the invariants that define "degrades, never lies":

1. **No deadlock** — a watchdog trips if admission completions stop
   progressing.
2. **Deny verdicts are bit-identical to the oracle or rejected** — an
   expected-deny request may come back 403 with exactly the oracle's
   messages, or be rejected outright (429 fail-closed / 500 / timeout);
   it is NEVER silently admitted.  Symmetrically, an expected-allow
   request is never spuriously denied 403.
3. **The bounded queue stays bounded** — sampled depth never exceeds
   capacity.
4. **p99 stays bounded during brownout** — requests either complete
   within a multiple of their deadline or are rejected; they don't
   hang.
5. **The supervisor recovers** — after the schedule disarms, a
   degraded backend returns to healthy (and the driver re-jits) within
   the backoff budget.

The reactor PR added the watch dimension: the soak runs with
``GATEKEEPER_PAGES=on``, a FakeCluster + event reactor
(``enforce/reactor.py``) driving store writes from watch events, a
namespace churn worker mutating the cluster throughout, and five
watch-class faults (``watch_stall``, ``watch_gap``,
``watch_duplicate``, ``watch_reorder``, ``watch_flood``) in the
schedule pool.  Three more invariants:

6. **The ledger event stream is exact** — a mirror violation multiset
   maintained purely from appear/clear events must equal the ledger's
   actual state AND the pages-off oracle's evaluation of the same
   store at every checkpoint (the stream is bit-identical to the diff
   of consecutive full sweeps, under every injected pathology).
7. **Resync never leaves phantoms** — a forced whole-ladder resync
   against the settled store emits zero events.
8. **The reactor recovers** — after the schedule disarms, the state
   machine returns to ``live`` within the recovery budget.

The rollout PR added ``promotion_storm``: the event runs a real
PromotionController ladder on a side client (its own policy set and
synthesized corpus, so the soak's live verdicts stay untouched) and
pins the brownout ladder ≥ SHED_WARN mid-rollout.  Two more
invariants:

9. **A rollout never ends above its evidence-supported rung** — the
   brownout must abort the in-flight promotion (``rolled_back``), and
   a rejected candidate never has a rung installed.
10. **Every rollback restores live enforcement exactly** — the
    post-rollback policy-set fingerprint equals the pre-rollout one.

Everything is seeded: ``build_schedule(seed, duration)`` is a pure
function of its arguments (the determinism test in
``tests/test_chaos.py`` pins this), so a failing soak replays with the
same fault timeline.  Chaos events are mirrored into the PR-9 flight
recorder; any invariant violation dumps the ring.

CLI::

    python -m gatekeeper_tpu.resilience.chaos --seed 7 --duration 30

rc 0 = clean, rc 1 = warnings only (e.g. brownout never engaged),
rc 2 = invariant violation(s).  The final line always reads
``... N invariant violation(s)`` for CI's trailing-window grep.
"""

from __future__ import annotations

import collections
import copy
import dataclasses
import os
import random
import threading
import time

FAULTS = ("probe_hang", "device_lost", "snapshot_corrupt",
          "slow_provider", "queue_storm",
          "watch_stall", "watch_gap", "watch_duplicate",
          "watch_reorder", "watch_flood", "promotion_storm")

# one-shot (``faults.take``) seams the scheduler re-arms between events
ONE_SHOT = ("device_lost", "snapshot_corrupt", "queue_storm",
            "watch_gap", "watch_duplicate", "watch_reorder")


@dataclasses.dataclass(frozen=True)
class ChaosEvent:
    t: float          # seconds from soak start
    fault: str
    duration: float   # how long the fault stays armed


def build_schedule(seed: int, duration_s: float,
                   warmup_s: float = 2.0) -> list[ChaosEvent]:
    """Deterministic fault timeline: a pure function of (seed,
    duration, warmup) — no wall clock, no global RNG — so a soak
    replays event-for-event.  Faults are drawn round-robin-ish from a
    seeded shuffle (every fault class appears before any repeats) with
    seeded durations and gaps."""
    rng = random.Random(seed)
    events: list[ChaosEvent] = []
    t = warmup_s
    pool: list[str] = []
    while t < duration_s - 1.0:
        if not pool:
            pool = list(FAULTS)
            rng.shuffle(pool)
        fault = pool.pop()
        dur = round(rng.uniform(0.5, 1.5), 3)
        events.append(ChaosEvent(t=round(t, 3), fault=fault, duration=dur))
        t += dur + rng.uniform(0.5, 2.0)
    return events


# ---------------------------------------------------------------------------
# workload fixture: a policy set spanning every enforcement action


_DENY_LABELS_REGO = """package k8srequiredlabels
violation[{"msg": msg, "details": {"missing_labels": missing}}] {
  provided := {label | input.review.object.metadata.labels[label]}
  required := {label | label := input.constraint.spec.parameters.labels[_]}
  missing := required - provided
  count(missing) > 0
  msg := sprintf("you must provide labels: %v", [missing])
}
"""

_WARN_TEAM_REGO = """package k8swarnteam
violation[{"msg": "namespace should declare a team label"}] {
  not input.review.object.metadata.labels.team
}
"""

_DRYRUN_COST_REGO = """package k8sdryruncost
violation[{"msg": "namespace has no cost-center label"}] {
  not input.review.object.metadata.labels["cost-center"]
}
"""

_EXT_SIG_REGO = """package k8schaossig
violation[{"msg": msg}] {
  image := input.review.object.spec.image
  verdict := object.get(external_data({"provider": "chaos-sig", "keys": [image]}), ["responses", image], "missing")
  verdict == "invalid"
  msg := sprintf("image %v rejected: %v", [image, verdict])
}
"""


def _template_doc(kind: str, rego: str) -> dict:
    return {"apiVersion": "templates.gatekeeper.sh/v1alpha1",
            "kind": "ConstraintTemplate",
            "metadata": {"name": kind.lower()},
            "spec": {"crd": {"spec": {"names": {"kind": kind}}},
                     "targets": [{"target": "admission.k8s.gatekeeper.sh",
                                  "rego": rego}]}}


def _constraint_doc(kind: str, name: str, action: str | None = None,
                    params: dict | None = None,
                    kinds: tuple[str, ...] = ("Namespace",)) -> dict:
    spec: dict = {"match": {"kinds": [{"apiGroups": [""],
                                       "kinds": list(kinds)}]}}
    if params:
        spec["parameters"] = params
    if action:
        spec["enforcementAction"] = action
    return {"apiVersion": "constraints.gatekeeper.sh/v1alpha1",
            "kind": kind, "metadata": {"name": name}, "spec": spec}


def _install_policy_set(client) -> None:
    client.add_template(_template_doc("K8sChaosLabels", _DENY_LABELS_REGO))
    client.add_constraint(_constraint_doc(
        "K8sChaosLabels", "ns-must-have-gk",
        params={"labels": ["gatekeeper"]}))
    client.add_template(_template_doc("K8sChaosWarnTeam", _WARN_TEAM_REGO))
    client.add_constraint(_constraint_doc(
        "K8sChaosWarnTeam", "ns-team-warn", action="warn"))
    client.add_template(_template_doc("K8sChaosDryrunCost",
                                      _DRYRUN_COST_REGO))
    client.add_constraint(_constraint_doc(
        "K8sChaosDryrunCost", "ns-cost-dryrun", action="dryrun"))
    client.add_template(_template_doc("K8sChaosSig", _EXT_SIG_REGO))
    client.add_constraint(_constraint_doc(
        "K8sChaosSig", "sig-check", kinds=("Pod",)))


def _ns_obj(name: str, labels: dict | None = None) -> dict:
    obj = {"apiVersion": "v1", "kind": "Namespace",
           "metadata": {"name": name}}
    if labels:
        obj["metadata"]["labels"] = labels
    return obj


def _pod_obj(name: str, image: str) -> dict:
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"image": image}}


def _review_request(obj: dict, uid: str) -> dict:
    return {"uid": uid,
            "kind": {"group": "", "version": "v1",
                     "kind": obj.get("kind", "")},
            "operation": "CREATE",
            "name": (obj.get("metadata") or {}).get("name", ""),
            "userInfo": {"username": "chaos", "groups": []},
            "object": obj}


def _build_corpus(n: int) -> list[dict]:
    """Deterministic request mix: namespaces that pass / trip the deny
    constraint (with/without warn+dryrun labels riding along) and pods
    that pass / trip the external-data signature check."""
    reqs: list[dict] = []
    for i in range(n):
        j = i % 6
        if j == 0:
            obj = _ns_obj(f"ok-{i}", {"gatekeeper": "on", "team": "a",
                                      "cost-center": "cc1"})
        elif j == 1:
            obj = _ns_obj(f"bad-{i}")                  # deny + warn + dryrun
        elif j == 2:
            obj = _ns_obj(f"warned-{i}", {"gatekeeper": "on"})  # warn only
        elif j == 3:
            obj = _pod_obj(f"pod-ok-{i}", "img-a")     # sig valid
        elif j == 4:
            obj = _pod_obj(f"pod-bad-{i}", "img-b")    # sig invalid -> deny
        else:
            obj = _ns_obj(f"bad2-{i}", {"team": "a"})  # deny
        reqs.append(_review_request(obj, uid=f"chaos-{i}"))
    return reqs


def _deny_lines(resp: dict) -> list[str]:
    if resp.get("allowed") or (resp.get("status") or {}).get("code") != 403:
        return []
    return sorted((resp["status"].get("message") or "").splitlines())


# ---------------------------------------------------------------------------
# promotion_storm: a brownout lands mid-rollout


def _storm_fixture(box: dict) -> dict:
    """Build (once, lazily) the promotion-storm side stack: its own
    client over the label policy set (no external data — the storm
    must not depend on the soak's provider runtime) plus a corpus
    synthesized from that client's own review verdicts, so the replay
    gate passes by construction and the storm exercises the install +
    rollback rungs, not the evidence gates."""
    if box:
        return box
    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.target.k8s import K8sValidationTarget
    tdocs = [_template_doc("K8sChaosLabels", _DENY_LABELS_REGO),
             _template_doc("K8sChaosWarnTeam", _WARN_TEAM_REGO),
             _template_doc("K8sChaosDryrunCost", _DRYRUN_COST_REGO)]
    cdocs = [_constraint_doc("K8sChaosLabels", "ns-must-have-gk",
                             params={"labels": ["gatekeeper"]}),
             _constraint_doc("K8sChaosWarnTeam", "ns-team-warn",
                             action="warn"),
             _constraint_doc("K8sChaosDryrunCost", "ns-cost-dryrun",
                             action="dryrun")]
    client = Backend(JaxDriver()).new_client([K8sValidationTarget()])
    for d in tdocs:
        client.add_template(d)
    for d in cdocs:
        client.add_constraint(d)
    for i in range(8):
        client.add_data(_ns_obj(
            f"ro-{i}", {"gatekeeper": "on"} if i % 2 else None))
    events = []
    for req in _build_corpus(12):
        if req["object"].get("kind") != "Namespace":
            continue
        results = client.review(dict(req)).results()
        allowed = not any(r.enforcement_action not in ("warn", "dryrun")
                          for r in results)
        events.append({
            "request": {k: req[k] for k in ("object", "kind", "name",
                                            "operation")},
            "allowed": allowed,
            "verdicts": [{"kind": (r.constraint or {}).get("kind"),
                          "name": ((r.constraint or {}).get("metadata")
                                   or {}).get("name"),
                          "action": r.enforcement_action,
                          "msg": r.msg} for r in results]})
    box.update(client=client, templates=tdocs, constraints=cdocs,
               candidate=cdocs[:-1], events=events)   # drop the dryrun one
    return box


def _promotion_storm(report, violation, box: dict) -> None:
    """Run one storm event: start a real promotion on the side client,
    wait for an enforcement rung to install, then pin the brownout
    ladder ≥ SHED_WARN (the pin is process-wide for the fault window —
    the soak's own ladder feeling it too IS the storm) and check
    invariants 9 and 10."""
    from gatekeeper_tpu.rollout import (ROLLED_BACK, PromotionController,
                                        live_enforcement_fingerprint)
    from gatekeeper_tpu.webhook.overload import OverloadController
    fix = _storm_fixture(box)
    client = fix["client"]
    report.promotion_storms += 1
    before = live_enforcement_fingerprint(client)
    ctrl = PromotionController(
        client, fix["templates"],
        [copy.deepcopy(c) for c in fix["candidate"]],
        name=f"storm-{report.promotion_storms}",
        events=fix["events"], soak_s=30.0)
    ovl = OverloadController(lambda: 0, capacity=10)
    ctrl.attach_overload(ovl)
    t = threading.Thread(target=ctrl.run, kwargs={"target_rung": "deny"},
                         daemon=True, name="chaos-promotion")
    t.start()
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and ctrl.installed is None \
            and ctrl.state not in ("rejected", ROLLED_BACK):
        time.sleep(0.01)
    if ctrl.installed is None:
        # a candidate that never installed must not have touched
        # enforcement either (invariant 9's rejected half)
        if live_enforcement_fingerprint(client) != before:
            violation("promotion_rejected_but_mutated",
                      state=ctrl.state)
        else:
            violation("promotion_never_installed", state=ctrl.state,
                      history=ctrl.history[-4:])
        return
    prev = os.environ.get("GATEKEEPER_BROWNOUT")
    os.environ["GATEKEEPER_BROWNOUT"] = "2"
    try:
        ovl.rung()                   # escalate -> listener -> rollback
    finally:
        if prev is None:
            os.environ.pop("GATEKEEPER_BROWNOUT", None)
        else:
            os.environ["GATEKEEPER_BROWNOUT"] = prev
    t.join(timeout=10.0)
    if ctrl.state != ROLLED_BACK:
        violation("promotion_storm_no_rollback", state=ctrl.state,
                  installed=ctrl.installed)
        return
    report.promotion_rollbacks += 1
    ev = ctrl.evidence.get(ROLLED_BACK, {})
    if not ev.get("restored"):
        violation("promotion_enforcement_not_restored", evidence=ev)
    if live_enforcement_fingerprint(client) != before:
        violation("promotion_fingerprint_drift", before=before,
                  after=live_enforcement_fingerprint(client))


# ---------------------------------------------------------------------------
# the soak


@dataclasses.dataclass
class SoakReport:
    seed: int
    duration_s: float
    events: list
    completed: int = 0
    rejected: int = 0            # 429/500/timeouts — acceptable under load
    denied_exact: int = 0        # 403 bit-identical to the oracle
    allowed: int = 0
    shed_total: int = 0
    max_rung: int = 0
    max_depth: int = 0
    queue_capacity: int = 0
    p99_s: float = 0.0
    p50_s: float = 0.0
    backend_degradations: int = 0
    backend_recoveries: int = 0
    backend_rejits: int = 0
    uncertified_retraces: int = 0  # jit dispatches outside the Stage-7
    #                                compile-surface certificate
    watch_events: int = 0        # frames the reactor ingested
    watch_pathologies: dict = dataclasses.field(default_factory=dict)
    reactor_resyncs: int = 0     # rung-2 + rung-3 ladder runs
    reactor_reconnects: int = 0
    ledger_checks: int = 0       # mirror==state==oracle checkpoints
    ledger_events: int = 0       # appear/clear deltas emitted
    churn_ops: int = 0
    promotion_storms: int = 0    # promotion_storm events run
    promotion_rollbacks: int = 0  # storms that rolled back cleanly
    violations: list = dataclasses.field(default_factory=list)
    warnings: list = dataclasses.field(default_factory=list)

    def headline(self) -> str:
        return (f"CHAOS seed={self.seed} dur={self.duration_s:.0f}s "
                f"events={len(self.events)} completed={self.completed} "
                f"rejected={self.rejected} denied={self.denied_exact} "
                f"max_rung={self.max_rung} max_depth={self.max_depth}"
                f"/{self.queue_capacity} p99={self.p99_s * 1e3:.1f}ms "
                f"recoveries={self.backend_recoveries} "
                f"rejits={self.backend_rejits} "
                f"uncertified_retraces={self.uncertified_retraces} "
                f"watch_ev={self.watch_events} "
                f"pathologies={sum(self.watch_pathologies.values())} "
                f"resyncs={self.reactor_resyncs} "
                f"ledger_checks={self.ledger_checks} "
                f"storms={self.promotion_rollbacks}/"
                f"{self.promotion_storms} "
                f"{len(self.warnings)} warning(s) "
                f"{len(self.violations)} invariant violation(s)")


def _percentile(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * (len(xs) - 1)))]


def run_soak(seed: int = 7, duration_s: float = 30.0, rps: float = 150.0,
             n_workers: int = 8, deadline_s: float = 0.75,
             queue_capacity: int = 64, max_batch: int = 16,
             schedule: list[ChaosEvent] | None = None,
             recovery_budget_s: float = 20.0,
             watchdog_s: float = 10.0) -> SoakReport:
    """Run the seeded soak and return the invariant report.  Builds a
    JaxDriver serving stack (bounded batcher + brownout ladder +
    webhook handler) and a LocalDriver oracle over the same policy set,
    drives ``n_workers`` admission threads plus an audit loop, and
    walks the fault schedule while a monitor enforces the invariants.
    """
    # fast supervisor cadence so recovery fits the soak window; only
    # defaults — an operator's explicit settings win
    os.environ.setdefault("GATEKEEPER_SUPERVISOR_BACKOFF_S", "0.5")
    os.environ.setdefault("GATEKEEPER_SUPERVISOR_REPROBE_TIMEOUT_S", "2.0")
    os.environ.setdefault("GATEKEEPER_FAULT_STALL_S", "0.3")
    # the soak IS the pages graduation gate: force the paged path on so
    # the ledger invariants are checked under injection (restored at
    # teardown), and tighten the reactor's timers so watch-fault
    # detection/recovery cycles fit inside ~1s fault windows
    prev_pages = os.environ.get("GATEKEEPER_PAGES")
    os.environ["GATEKEEPER_PAGES"] = "on"
    os.environ.setdefault("GATEKEEPER_PAGE_ROWS", "8")
    os.environ.setdefault("GATEKEEPER_REACTOR_QUEUE", "8")
    os.environ.setdefault("GATEKEEPER_REACTOR_STALL_S", "0.25")
    os.environ.setdefault("GATEKEEPER_REACTOR_BACKOFF_S", "0.25")
    os.environ.setdefault("GATEKEEPER_REACTOR_GAP_GRACE_S", "0.15")
    prev_fault = os.environ.get("GATEKEEPER_FAULT")
    os.environ["GATEKEEPER_FAULT"] = ""

    from gatekeeper_tpu.analysis import compilesurface as _cs
    from gatekeeper_tpu.api.config import GVK
    from gatekeeper_tpu.api.externaldata import IGNORE, Provider
    from gatekeeper_tpu.client.client import Backend
    from gatekeeper_tpu.client.interface import QueryOpts
    from gatekeeper_tpu.client.local_driver import LocalDriver
    from gatekeeper_tpu.cluster.fake import FakeCluster
    from gatekeeper_tpu.enforce.reactor import LIVE, Reactor
    from gatekeeper_tpu.engine.jax_driver import JaxDriver
    from gatekeeper_tpu.externaldata.fake import FakeProvider, register_fake
    from gatekeeper_tpu.externaldata.runtime import (ExternalDataRuntime,
                                                     set_runtime)
    from gatekeeper_tpu.obs.flightrecorder import (get_flight_recorder,
                                                   record_event)
    from gatekeeper_tpu.resilience import faults
    from gatekeeper_tpu.resilience.supervisor import (HEALTHY,
                                                      get_supervisor)
    from gatekeeper_tpu.target.k8s import TARGET_NAME, K8sValidationTarget
    from gatekeeper_tpu.webhook.batcher import MicroBatcher
    from gatekeeper_tpu.webhook.overload import OverloadController
    from gatekeeper_tpu.webhook.policy import ValidationHandler

    if schedule is None:
        schedule = build_schedule(seed, duration_s)
    report = SoakReport(seed=seed, duration_s=duration_s,
                        events=[dataclasses.asdict(e) for e in schedule],
                        queue_capacity=queue_capacity)

    def violation(kind: str, **fields) -> None:
        report.violations.append({"kind": kind, **fields})
        record_event("chaos_violation", kind=kind, **fields)
        get_flight_recorder().dump("chaos:invariant")

    # ---------------- fixture: external data + both engines ----------
    register_fake("chaos-sig", FakeProvider({"img-a": "valid",
                                             "img-b": "invalid"}))
    rt = ExternalDataRuntime()
    prev_rt = set_runtime(rt)
    # short cache TTL so slow_provider actually stalls live fetches
    # (an infinite-TTL cache would absorb the fault after warmup)
    rt.register(Provider(name="chaos-sig", url="fake://chaos-sig",
                         failure_policy=IGNORE, cache_ttl_s=1.0,
                         timeout_s=2.0))

    live_client = Backend(JaxDriver()).new_client([K8sValidationTarget()])
    oracle_client = Backend(LocalDriver()).new_client([K8sValidationTarget()])
    _install_policy_set(live_client)
    _install_policy_set(oracle_client)
    # a small inventory so the audit loop sweeps real rows (and the
    # mid-sweep device_lost seam has kinds to fire between); created
    # through the FakeCluster and list-synced into the store, so the
    # reactor's rung-2 relists see the same objects
    cluster = FakeCluster()
    ns_gvk = GVK(group="", version="v1", kind="Namespace")
    for i in range(16):
        live_client.add_data(cluster.create(_ns_obj(
            f"inv-{i}", {"gatekeeper": "on"} if i % 2 else None)))

    corpus = _build_corpus(48)
    oracle_handler = ValidationHandler(oracle_client)
    expected = [oracle_handler.handle(dict(r)) for r in corpus]
    expected_deny = [_deny_lines(r) for r in expected]

    # ---------------- the watch path: reactor + ledger mirror ---------
    # apply_objects=True makes the reactor the ONLY store writer for
    # cluster churn: a dropped frame is genuine store staleness that
    # only the resync ladder heals
    rx = Reactor(live_client, cluster=cluster, apply_objects=True,
                 seed=seed, name="chaos-reactor")
    rx.attach(ns_gvk)
    drv = live_client.driver
    drv.react_kind(TARGET_NAME, None)       # cold-build the ledger
    led = drv.state[TARGET_NAME].ledger
    if led is None:
        raise RuntimeError("chaos soak requires the paged sweep: no "
                           "VerdictLedger after react_kind (is every "
                           "kind pages-ineligible?)")

    def _led_multiset() -> collections.Counter:
        out: collections.Counter = collections.Counter()
        for kind, ent in led.entries.items():
            for _row, (ident, by_c) in ent.rows.items():
                ref = led._resource_ref(ident)
                for cname, rs in by_c.items():
                    for r in rs:
                        out[(kind, cname, ref, r.msg)] += 1
        return out

    mirror_lock = threading.Lock()
    mirror: collections.Counter = _led_multiset()   # primed pre-subscribe

    def _on_delta(ev: dict) -> None:
        with mirror_lock:
            key = (ev["kind"], ev["constraint"], ev["resource"], ev["msg"])
            if ev["op"] == "appear":
                mirror[key] += 1
            else:
                mirror[key] -= 1
                if not mirror[key]:
                    del mirror[key]

    led.subscribe(_on_delta)
    ledger_checks = [0]

    def ledger_checkpoint(tag: str) -> None:
        """Invariant 6: under the client write lock (no concurrent
        sweeps or reactor applies) the event-stream mirror, the
        ledger's state, and the pages-off oracle's evaluation of the
        same store must be one multiset."""
        with live_client._lock.write():
            drv.react_kind(TARGET_NAME, None)   # fold pending store dirt
            state = _led_multiset()
            with mirror_lock:
                mir = collections.Counter(
                    {k: v for k, v in mirror.items() if v})
            if mir != state:
                violation("ledger_stream_divergence", tag=tag,
                          missing=sorted(map(str, (state - mir))),
                          extra=sorted(map(str, (mir - state))))
            saved = os.environ.get("GATEKEEPER_PAGES")
            os.environ["GATEKEEPER_PAGES"] = "off"
            try:
                results, _ = drv.query_audit(
                    TARGET_NAME, QueryOpts(limit_per_constraint=100_000))
            finally:
                os.environ["GATEKEEPER_PAGES"] = saved
            oracle: collections.Counter = collections.Counter()
            for r in results:
                kind = (r.constraint or {}).get("kind", "")
                if kind not in led.entries:
                    continue        # non-paged kinds aren't ledgered
                cname = ((r.constraint or {}).get("metadata")
                         or {}).get("name", "")
                # the legacy sweep reports identity via the synthesized
                # review, the paged serve via the stored resource
                meta = (r.resource or {}).get("metadata") or {}
                rev = r.review or {}
                name = meta.get("name") or rev.get("name", "")
                ns = meta.get("namespace") or rev.get("namespace")
                ref = f"{ns}/{name}" if ns else str(name)
                oracle[(kind, cname, ref, r.msg)] += 1
            if oracle != state:
                violation("ledger_oracle_divergence", tag=tag,
                          missing=sorted(map(str, (oracle - state))),
                          extra=sorted(map(str, (state - oracle))))
        ledger_checks[0] += 1

    batcher = MicroBatcher(
        lambda reqs: live_client.review_batch(
            reqs, shed_actions=overload.shed_actions() or None),
        max_batch=max_batch, max_wait=0.002,
        submit_timeout=deadline_s, capacity=queue_capacity,
        prefetch=live_client.prefetch_external,
        predict_seconds=live_client.predict_review_seconds)
    overload = OverloadController(batcher.depth, queue_capacity)
    handler = ValidationHandler(live_client, batcher=batcher,
                                overload=overload, batch_mode="always")
    batcher.start()

    # ---------------- load + monitor threads --------------------------
    stop = threading.Event()
    completions = [0]
    comp_lock = threading.Lock()
    latencies: list[list[float]] = [[] for _ in range(n_workers)]
    per_req_interval = n_workers / max(rps, 1.0)

    def worker(w: int) -> None:
        k = w
        while not stop.is_set():
            i = k % len(corpus)
            k += n_workers
            t0 = time.monotonic()
            try:
                resp = handler.handle(dict(corpus[i]),
                                      deadline=t0 + deadline_s)
            except Exception as e:   # noqa: BLE001 — the handler owns
                violation("worker_exception", error=repr(e), req=i)
                resp = None          # errors; an escape is a bug
            lat = time.monotonic() - t0
            latencies[w].append(lat)
            with comp_lock:
                completions[0] += 1
            if resp is not None:
                code = (resp.get("status") or {}).get("code")
                if resp.get("allowed"):
                    report.allowed += 1
                    if expected_deny[i]:
                        # THE invariant: a deny verdict is never
                        # silently dropped, at any rung, under any fault
                        violation("silent_admit", req=i,
                                  expected=expected_deny[i])
                elif code == 403:
                    got = _deny_lines(resp)
                    if got == expected_deny[i]:
                        report.denied_exact += 1
                    else:
                        violation("verdict_mismatch", req=i, got=got,
                                  expected=expected_deny[i])
                else:               # 429 fail-closed / 500 / timeout
                    report.rejected += 1
            pause = per_req_interval - (time.monotonic() - t0)
            if pause > 0:
                stop.wait(pause)

    def auditor() -> None:
        cycles = 0
        while not stop.is_set():
            try:
                live_client.audit()
                cycles += 1
                if cycles % 5 == 0:
                    ledger_checkpoint("periodic")
            except Exception as e:   # noqa: BLE001
                violation("audit_exception", error=repr(e))
                return
            stop.wait(0.2)

    churn_ops = [0]

    def churner() -> None:
        """Continuous cluster mutation: the watch stream always has
        traffic for the armed fault to corrupt.  Single writer, so
        FakeCluster RV conflicts can't occur."""
        rng = random.Random(seed * 31 + 7)
        extras: list[str] = []
        n_created = 0
        while not stop.wait(0.02):
            try:
                r = rng.random()
                if r < 0.75:
                    cur = cluster.get(ns_gvk, f"inv-{rng.randrange(16)}")
                    obj = copy.deepcopy(cur)
                    labels = obj.setdefault("metadata", {}).setdefault(
                        "labels", {})
                    if "gatekeeper" in labels and rng.random() < 0.5:
                        labels.pop("gatekeeper")
                    else:
                        labels["gatekeeper"] = "on"
                    labels["churn"] = str(churn_ops[0])
                    cluster.update(obj)
                elif r < 0.92 or not extras:
                    name = f"churn-{n_created}"
                    n_created += 1
                    cluster.create(_ns_obj(name, {"team": "x"}))
                    extras.append(name)
                else:
                    cluster.delete(ns_gvk, extras.pop(
                        rng.randrange(len(extras))))
                churn_ops[0] += 1
            except Exception as e:   # noqa: BLE001 — churn must never
                violation("churn_exception", error=repr(e))   # wedge
                return

    def monitor() -> None:
        last = 0
        stalled = 0.0
        while not stop.wait(0.25):
            depth = batcher.depth()
            report.max_depth = max(report.max_depth, depth)
            if depth > queue_capacity:
                violation("queue_over_capacity", depth=depth,
                          capacity=queue_capacity)
            report.max_rung = max(report.max_rung, overload.rung())
            with comp_lock:
                cur = completions[0]
            if cur == last:
                stalled += 0.25
                if stalled >= watchdog_s:
                    violation("deadlock_watchdog", completions=cur,
                              stalled_s=stalled)
                    stop.set()
                    return
            else:
                stalled = 0.0
                last = cur

    threads = [threading.Thread(target=worker, args=(w,), daemon=True,
                                name=f"chaos-worker-{w}")
               for w in range(n_workers)]
    threads.append(threading.Thread(target=auditor, daemon=True,
                                    name="chaos-audit"))
    threads.append(threading.Thread(target=monitor, daemon=True,
                                    name="chaos-monitor"))
    threads.append(threading.Thread(target=churner, daemon=True,
                                    name="chaos-churn"))
    rx.start(interval=0.02)
    t_start = time.monotonic()
    for t in threads:
        t.start()

    # ---------------- the schedule ------------------------------------
    storm_box: dict = {}
    try:
        for ev in schedule:
            if stop.is_set():
                break
            delay = t_start + ev.t - time.monotonic()
            if delay > 0 and stop.wait(delay):
                break
            if ev.fault == "promotion_storm":
                # not a faults.py seam: the event runs a real rollout
                # on the side stack and browns it out mid-flight
                record_event("chaos_event", fault=ev.fault,
                             action="arm", t=ev.t, duration=ev.duration)
                try:
                    _promotion_storm(report, violation, storm_box)
                except Exception as e:   # noqa: BLE001 — a storm crash
                    violation("promotion_storm_exception",   # is a bug
                              error=repr(e))
                record_event("chaos_event", fault=ev.fault,
                             action="disarm", t=ev.t + ev.duration)
                continue
            if ev.fault in ONE_SHOT:
                faults.rearm(ev.fault)
            os.environ["GATEKEEPER_FAULT"] = ev.fault
            record_event("chaos_event", fault=ev.fault, action="arm",
                         t=ev.t, duration=ev.duration)
            stop.wait(ev.duration)
            os.environ["GATEKEEPER_FAULT"] = ""
            record_event("chaos_event", fault=ev.fault, action="disarm",
                         t=ev.t + ev.duration)
        # run out the remaining soak window fault-free
        remaining = t_start + duration_s - time.monotonic()
        if remaining > 0:
            stop.wait(remaining)
    finally:
        os.environ["GATEKEEPER_FAULT"] = ""
        stop.set()
        for t in threads:
            t.join(timeout=max(10.0, deadline_s * 4))
        for t in threads:
            if t.is_alive():
                violation("thread_wedged", thread=t.name)

    # ---------------- post-soak invariants ----------------------------
    # invariant 8: the reactor's state machine returns to live within
    # the recovery budget once the schedule stops injecting (its pump
    # thread is still running and drives reconnect/resync)
    t_rec = time.monotonic() + recovery_budget_s
    while time.monotonic() < t_rec and rx.state != LIVE:
        time.sleep(0.1)
    if rx.state != LIVE:
        violation("reactor_no_recovery", state=rx.state,
                  budget_s=recovery_budget_s,
                  transitions=list(rx.transitions)[-8:])
    rx.stop()
    # invariant 6, once more against the settled store
    ledger_checkpoint("final")
    # invariant 7: a forced rung-2 resync of EVERY kind against the
    # settled store must be event-free — resync never leaves phantom
    # verdicts (and never drops real ones)
    with live_client._lock.write():
        drv.react_kind(TARGET_NAME, None)
        seq0 = led.seq
        drv.resync_kind(TARGET_NAME, None)
        if led.seq != seq0:
            violation("resync_phantom_events", events=led.seq - seq0)
    report.watch_events = rx.counters.get("events", 0)
    report.watch_pathologies = {
        p[len("pathology_"):]: n for p, n in rx.counters.items()
        if p.startswith("pathology_")}
    report.reactor_resyncs = (rx.counters.get("rung2", 0)
                              + rx.counters.get("rung3", 0))
    report.reactor_reconnects = rx.counters.get("reconnects", 0)
    report.ledger_checks = ledger_checks[0]
    report.ledger_events = led.seq
    report.churn_ops = churn_ops[0]
    if not report.watch_events:
        report.warnings.append(
            "watch stream carried no events: churn worker never ran "
            "(reactor invariants were vacuous)")

    # Stage-7 compile-surface invariant: the whole soak — churn, review
    # batches, backend kills, promotion storms — must never demand a jit
    # signature outside the installed certificates.  One uncertified
    # retrace means the certifier missed a reachable signature (or the
    # caps are mis-sized for the workload): a violation either way.
    report.uncertified_retraces = getattr(
        live_client.driver.executor, "retrace_uncertified", 0)
    if report.uncertified_retraces:
        violation("uncertified_retrace",
                  count=report.uncertified_retraces,
                  mode=_cs.mode())

    sup = get_supervisor()
    report.backend_degradations = \
        sup.metrics.counter("backend_degradations").value
    if report.backend_degradations:
        t_rec = time.monotonic() + recovery_budget_s
        while time.monotonic() < t_rec and sup.state != HEALTHY:
            time.sleep(0.25)
        if sup.state != HEALTHY:
            violation("no_recovery", state=sup.state,
                      budget_s=recovery_budget_s)
        report.backend_recoveries = \
            sup.metrics.counter("backend_recoveries").value
        report.backend_rejits = \
            live_client.driver.metrics.counter("backend_rejits").value
        if report.backend_recoveries and not report.backend_rejits:
            violation("no_rejit_after_recovery",
                      recoveries=report.backend_recoveries)

    all_lat = [x for per in latencies for x in per]
    with comp_lock:
        report.completed = completions[0]
    report.p50_s = _percentile(all_lat, 0.50)
    report.p99_s = _percentile(all_lat, 0.99)
    # a request either finishes or is rejected near its deadline; a p99
    # far past the deadline means something hung instead of shedding
    p99_bound = deadline_s * 3 + 1.0
    if report.p99_s > p99_bound:
        violation("p99_unbounded", p99_s=report.p99_s, bound_s=p99_bound)

    # shed accounting may live across several registries (batcher,
    # handler, ladder); read it back through the public snapshots
    shed = 0
    for m in {id(batcher.metrics): batcher.metrics,
              id(handler.metrics): handler.metrics,
              id(overload.metrics): overload.metrics}.values():
        for key, val in m.snapshot().items():
            if key.startswith("admission_shed_total"):
                shed += int(val)
    report.shed_total = shed
    if report.max_rung == 0 and not shed:
        report.warnings.append(
            "brownout never engaged: load never pressured the queue "
            "(raise rps or shrink capacity)")
    fired = {e["fault"] for e in report.events}
    if "device_lost" in fired and not report.backend_degradations:
        report.warnings.append(
            "device_lost armed but the backend never degraded "
            "(audit loop may not have reached the seam)")

    # teardown
    batcher.stop()
    set_runtime(prev_rt)
    if prev_fault is None:
        os.environ.pop("GATEKEEPER_FAULT", None)
    else:
        os.environ["GATEKEEPER_FAULT"] = prev_fault
    if prev_pages is None:
        os.environ.pop("GATEKEEPER_PAGES", None)
    else:
        os.environ["GATEKEEPER_PAGES"] = prev_pages
    record_event("chaos_soak_done", violations=len(report.violations),
                 warnings=len(report.warnings))
    if report.violations:
        get_flight_recorder().dump("chaos:final")
    return report


def main(argv: list[str] | None = None) -> int:
    import argparse
    import json
    ap = argparse.ArgumentParser(description="seeded chaos soak")
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--rps", type=float, default=150.0)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--queue", type=int, default=64)
    ap.add_argument("--deadline", type=float, default=0.75)
    args = ap.parse_args(argv)
    report = run_soak(seed=args.seed, duration_s=args.duration,
                      rps=args.rps, n_workers=args.workers,
                      queue_capacity=args.queue,
                      deadline_s=args.deadline)
    print(json.dumps({"violations": report.violations,
                      "warnings": report.warnings}, indent=2,
                     default=str))
    print(report.headline())
    if report.violations:
        return 2
    return 1 if report.warnings else 0


if __name__ == "__main__":
    raise SystemExit(main())
