"""External-data provider subsystem.

Two-phase design: host-side *key collection + batched prefetch* (one
round per provider, single-flight, TTL-cached, circuit-broken) feeding
device-resident provider tables, so the evaluation kernel performs only
gathers.  See README "External data".
"""

from gatekeeper_tpu.externaldata.breaker import (CLOSED, HALF_OPEN, OPEN,
                                                 CircuitBreaker)
from gatekeeper_tpu.externaldata.cache import (ERROR_TTL_CAP_S, Outcome,
                                               TTLCache)
from gatekeeper_tpu.externaldata.client import (BreakerOpenError, FetchError,
                                                ProviderClient)
from gatekeeper_tpu.externaldata.fake import (FakeProvider, clear_fakes,
                                              fake_transport, get_fake,
                                              register_fake)
from gatekeeper_tpu.externaldata.runtime import (ExternalDataRuntime,
                                                 get_runtime, set_runtime)

__all__ = [
    "CLOSED", "HALF_OPEN", "OPEN", "CircuitBreaker",
    "ERROR_TTL_CAP_S", "Outcome", "TTLCache",
    "BreakerOpenError", "FetchError", "ProviderClient",
    "FakeProvider", "clear_fakes", "fake_transport", "get_fake",
    "register_fake",
    "ExternalDataRuntime", "get_runtime", "set_runtime",
]
