"""TTL'd key -> outcome cache with single-flight stampede protection.

Per-provider: bounded size (LRU eviction), per-entry TTL, and a
single-flight lease per key so N concurrent misses on the same key
produce exactly one upstream fetch — the other N-1 callers block on the
leader's lease and read the cached outcome it installs (groupcache's
singleflight shape, applied per key).

Both successes and failures are cached: a provider outage must not turn
every evaluation into a fresh timeout — the error outcome serves from
cache until its TTL lapses (errors use a shorter TTL so recovery is
observed promptly).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable


@dataclasses.dataclass(frozen=True)
class Outcome:
    """Result of one key's lookup: a value or an error reason."""

    value: object = None
    error: str | None = None

    @property
    def ok(self) -> bool:
        return self.error is None


ERROR_TTL_CAP_S = 5.0
"""Failure outcomes are cached at most this long regardless of the
provider's TTL: a long value-TTL must not pin an outage's errors past
the breaker's own recovery probe cadence."""


class TTLCache:
    def __init__(self, max_entries: int = 65536, ttl_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.max_entries = max(1, int(max_entries))
        self.ttl_s = ttl_s
        self._clock = clock
        # key -> (outcome, expires_at); OrderedDict gives O(1) LRU
        self._entries: collections.OrderedDict[str, tuple[Outcome, float]] = \
            collections.OrderedDict()
        # single-flight leases: key -> Event set when the leader resolves
        self._leases: dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # ------------------------------------------------------------------

    def get(self, key: str) -> Outcome | None:
        """Fresh outcome for key, or None (missing/expired)."""
        with self._lock:
            return self._get_locked(key)

    def _get_locked(self, key: str) -> Outcome | None:
        ent = self._entries.get(key)
        if ent is None:
            self.misses += 1
            return None
        outcome, expires = ent
        if self._clock() >= expires:
            del self._entries[key]
            self.misses += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        return outcome

    def put(self, key: str, outcome: Outcome) -> None:
        ttl = self.ttl_s if outcome.ok else min(self.ttl_s, ERROR_TTL_CAP_S)
        with self._lock:
            self._entries[key] = (outcome, self._clock() + ttl)
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    # ------------------------------------------------------------------
    # single-flight

    def lease(self, keys: list[str]) -> tuple[dict[str, Outcome],
                                              list[str],
                                              list[threading.Event]]:
        """Partition keys under one lock: (cached, mine, waits).

        ``cached``: keys already fresh.  ``mine``: keys this caller now
        leads (it MUST later call :meth:`complete` or :meth:`abandon`
        for every one).  ``waits``: other leaders' in-flight leases this
        caller should wait on, then re-read from cache."""
        cached: dict[str, Outcome] = {}
        mine: list[str] = []
        waits: list[threading.Event] = []
        with self._lock:
            for key in keys:
                out = self._get_locked(key)
                if out is not None:
                    cached[key] = out
                    continue
                ev = self._leases.get(key)
                if ev is not None:
                    waits.append(ev)
                else:
                    self._leases[key] = threading.Event()
                    mine.append(key)
        return cached, mine, waits

    def complete(self, key: str, outcome: Outcome) -> None:
        self.put(key, outcome)
        with self._lock:
            ev = self._leases.pop(key, None)
        if ev is not None:
            ev.set()

    def abandon(self, key: str) -> None:
        """Release a lease without caching (leader crashed mid-fetch)."""
        with self._lock:
            ev = self._leases.pop(key, None)
        if ev is not None:
            ev.set()

    # ------------------------------------------------------------------

    def hit_ratio(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
