"""In-process fake provider (sibling of cluster/fake.py).

Backs tests and the bench: a ``Provider`` whose ``url`` is
``fake://<name>`` resolves, at fetch time, to the :class:`FakeProvider`
registered under that name.  The fake records every batched call so
tests can assert batching (one round per provider per sweep) and
single-flight (concurrent misses collapse to one call), and can be
degraded on demand (latency, per-key failures, full outage) to drive
the breaker and failure-policy paths.
"""

from __future__ import annotations

import threading
import time


class FakeProvider:
    def __init__(self, data: dict | None = None, latency_s: float = 0.0):
        self.data = dict(data or {})
        self.latency_s = latency_s
        self.outage = False          # raise on every call
        self.fail_keys: set = set()  # omit these keys from responses
        self.calls = 0
        self.batches: list[list[str]] = []
        self._lock = threading.Lock()

    def __call__(self, provider, keys: list[str]) -> dict:
        with self._lock:
            self.calls += 1
            self.batches.append(list(keys))
            outage = self.outage
        if self.latency_s:
            time.sleep(self.latency_s)
        if outage:
            raise RuntimeError("fake provider outage")
        return {k: self.data[k] for k in keys
                if k in self.data and k not in self.fail_keys}


_FAKES: dict[str, FakeProvider] = {}
_lock = threading.Lock()


def register_fake(name: str, fake: FakeProvider) -> FakeProvider:
    with _lock:
        _FAKES[name] = fake
    return fake


def get_fake(name: str) -> FakeProvider | None:
    with _lock:
        return _FAKES.get(name)


def clear_fakes() -> None:
    with _lock:
        _FAKES.clear()


def fake_transport(provider, keys: list[str]) -> dict:
    """Transport bound by ExternalDataRuntime for ``fake://`` URLs."""
    name = provider.url[len("fake://"):]
    fake = get_fake(name)
    if fake is None:
        raise RuntimeError(f"no FakeProvider registered as {name!r}")
    return fake(provider, keys)
