"""Per-provider circuit breaker: closed -> open -> half-open.

Counts consecutive failed fetch *rounds* (a round is one batched call
after its own bounded retries).  After ``failure_threshold`` consecutive
failures the breaker opens: calls short-circuit without touching the
endpoint until ``cooldown_s`` elapses, then exactly one probe round is
admitted (half-open).  A successful probe closes the breaker; a failed
probe re-opens it for another cool-down (the classic Nygard shape —
release-it circuit breaker, same state machine Hystrix/gobreaker use).
"""

from __future__ import annotations

import threading
import time
from typing import Callable

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"

STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}
"""Numeric encoding for the metrics gauge (0 healthy .. 2 tripped)."""


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self.short_circuits = 0
        self.transitions: list[str] = [CLOSED]

    # ------------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def allow(self) -> bool:
        """May a fetch round proceed right now?  In half-open state only
        one probe is admitted at a time; concurrent callers short-circuit
        until the probe resolves."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                return True
            self.short_circuits += 1
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_inflight = False
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._probe_inflight = False
            if self._state == HALF_OPEN:
                # failed probe: straight back to open, fresh cool-down
                self._opened_at = self._clock()
                self._transition(OPEN)
                return
            self._consecutive_failures += 1
            if self._state == CLOSED and \
                    self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self._clock()
                self._transition(OPEN)

    # ------------------------------------------------------------------

    def _maybe_half_open(self) -> None:
        if self._state == OPEN and \
                self._clock() - self._opened_at >= self.cooldown_s:
            self._transition(HALF_OPEN)

    def _transition(self, state: str) -> None:
        if state != self._state:
            prev = self._state
            self._state = state
            self.transitions.append(state)
            # flight-record the flip (non-blocking append; safe under
            # the breaker lock)
            try:
                from gatekeeper_tpu.obs.flightrecorder import record_event
                record_event("breaker_flip", frm=prev, to=state)
            except Exception:   # noqa: BLE001
                pass

    def code(self) -> int:
        return STATE_CODES[self.state]
