"""External-data runtime: provider registry + cache + batched fetches.

The one object the rest of the engine talks to.  Registration comes
from the Provider controller (or tests directly); consumption comes
from three places:

- the **key-collection prefetch** hooks (``ir/prep.py`` table builds,
  the audit sweep's overlapped bulk warm, the webhook's per-batch warm)
  call :meth:`prefetch` — batched, single-flight, outcome-cached;
- the **scalar oracle** (``rego/builtins.py`` ``external_data``) calls
  :meth:`builtin_call` per review — by construction the prefetch hooks
  have already warmed every key the vectorized path will gather, so the
  oracle almost always serves from cache;
- the **audit report / metrics endpoint** call :meth:`stats`.

Failure policy is applied at :meth:`builtin_call` time, not at fetch
time: the cache stores raw outcomes (value or error) so one fetch can
serve providers' keys regardless of how each calling policy wants
failures interpreted.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable

from gatekeeper_tpu.api.externaldata import (FAIL, IGNORE, USE_DEFAULT,
                                             Provider)
from gatekeeper_tpu.errors import ExternalDataError
from gatekeeper_tpu.externaldata.cache import Outcome, TTLCache
from gatekeeper_tpu.externaldata.client import FetchError, ProviderClient


def _metric_name(provider: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_]", "_", provider)


def _http_transport(provider: Provider, keys: list[str]) -> dict:
    """Batched JSON POST, matching the reference provider protocol
    (ExternalData{Request,Response}: keys in, key/value items out).
    stdlib-only on purpose — no new dependencies."""
    import json
    import urllib.request
    body = json.dumps({"apiVersion": "externaldata.gatekeeper.sh/v1beta1",
                       "kind": "ProviderRequest",
                       "request": {"keys": list(keys)}}).encode()
    req = urllib.request.Request(
        provider.url, data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=provider.timeout_s) as resp:
        payload = json.loads(resp.read())
    items = (payload.get("response") or {}).get("items") or []
    out = {}
    for item in items:
        if item.get("error"):
            continue    # absent key -> error outcome at the caller
        out[item["key"]] = item.get("value")
    return out


class _ProviderEntry:
    __slots__ = ("provider", "transport", "cache", "fetch_batches",
                 "fetch_keys", "fetch_errors", "fetch_seconds")

    def __init__(self, provider: Provider, transport: Callable):
        self.provider = provider
        self.transport = transport
        self.cache = TTLCache(max_entries=provider.cache_max_entries,
                              ttl_s=provider.cache_ttl_s)
        self.fetch_batches = 0
        self.fetch_keys = 0
        self.fetch_errors = 0
        self.fetch_seconds = 0.0


class ExternalDataRuntime:
    def __init__(self, metrics=None,
                 client: ProviderClient | None = None):
        self.metrics = metrics
        self.client = client if client is not None else ProviderClient()
        self._entries: dict[str, _ProviderEntry] = {}
        self._lock = threading.Lock()

    # -- registry ------------------------------------------------------

    def register(self, provider: Provider,
                 transport: Callable | None = None) -> None:
        """Install (or replace) a provider.  Replacement drops the cache
        and breaker: a spec change means the old endpoint's history no
        longer predicts the new one's health."""
        provider.validate()
        if transport is None:
            transport = self._resolve_transport(provider)
        with self._lock:
            self._entries[provider.name] = _ProviderEntry(provider, transport)
        self.client.drop_breaker(provider.name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._entries.pop(name, None)
        self.client.drop_breaker(name)

    def provider(self, name: str) -> Provider | None:
        with self._lock:
            ent = self._entries.get(name)
            return ent.provider if ent else None

    def provider_names(self) -> list[str]:
        with self._lock:
            return sorted(self._entries)

    def _entry(self, name: str) -> _ProviderEntry | None:
        with self._lock:
            return self._entries.get(name)

    @staticmethod
    def _resolve_transport(provider: Provider) -> Callable:
        if provider.url.startswith("fake://"):
            from gatekeeper_tpu.externaldata.fake import fake_transport
            return fake_transport
        if provider.url.startswith(("http://", "https://")):
            return _http_transport
        raise ValueError(
            f"Provider {provider.name!r}: unsupported url scheme in "
            f"{provider.url!r} (expected fake:// or http(s)://)")

    # -- fetching ------------------------------------------------------

    def prefetch(self, name: str, keys) -> dict[str, Outcome]:
        """Resolve keys through cache + one batched fetch round for the
        misses (single-flight: concurrent callers of overlapping key
        sets produce one upstream round per key).  Returns every key's
        Outcome; never raises — errors are outcomes, policy is applied
        later at builtin_call time."""
        ent = self._entry(name)
        keys = [k for k in dict.fromkeys(keys)]     # dedupe, keep order
        if ent is None:
            return {k: Outcome(error=f"provider {name!r} not registered")
                    for k in keys}
        cached, mine, waits = ent.cache.lease(keys)
        out = dict(cached)
        if mine:
            out.update(self._fetch_round(ent, mine))
        for ev in waits:
            ev.wait(ent.provider.timeout_s * (ent.provider.retries + 2))
        for k in keys:
            if k not in out:
                got = ent.cache.get(k)
                out[k] = got if got is not None else \
                    Outcome(error="single-flight wait expired")
        return out

    def _fetch_round(self, ent: _ProviderEntry,
                     keys: list[str]) -> dict[str, Outcome]:
        t0 = time.perf_counter()
        out: dict[str, Outcome] = {}
        try:
            values = self.client.fetch(ent.provider, ent.transport, keys)
            for k in keys:
                out[k] = Outcome(value=values[k]) if k in values else \
                    Outcome(error="no value for key")
        except FetchError as e:
            reason = str(e)
            for k in keys:
                out[k] = Outcome(error=reason)
        finally:
            dt = time.perf_counter() - t0
            for k in keys:
                # complete() even on the error path: the lease must be
                # released and the (capped-TTL) error outcome cached
                ent.cache.complete(k, out[k])
            ent.fetch_batches += 1
            ent.fetch_keys += len(keys)
            ent.fetch_errors += sum(1 for o in out.values() if not o.ok)
            ent.fetch_seconds += dt
            self._observe(ent, dt, keys, out)
        return out

    def _observe(self, ent: _ProviderEntry, dt: float,
                 keys: list[str], out: dict[str, Outcome]) -> None:
        if self.metrics is None:
            return
        self.metrics.timer("external_fetch_seconds").observe(dt)
        self.metrics.counter("external_fetch_batches").inc()
        self.metrics.counter("external_fetch_keys").inc(len(keys))
        errs = sum(1 for o in out.values() if not o.ok)
        if errs:
            self.metrics.counter("external_fetch_errors").inc(errs)
        mname = _metric_name(ent.provider.name)
        self.metrics.gauge(f"external_breaker_state_{mname}").set(
            self.client.breaker(ent.provider).code())
        self.metrics.gauge(f"external_cache_hit_ratio_{mname}").set(
            round(ent.cache.hit_ratio(), 4))

    # -- the builtin ---------------------------------------------------

    def builtin_call(self, name: str, keys) -> dict:
        """``external_data({"provider": name, "keys": keys})`` semantics:
        resolve through the cache, then apply the provider's
        failurePolicy to each failed key.  Returns the reference's
        response shape (responses / errors / system_error)."""
        ent = self._entry(name)
        if ent is None:
            # unknown provider is a policy-authoring error, not an
            # endpoint failure: no failurePolicy to consult
            raise ExternalDataError(
                f"external_data: provider {name!r} not registered")
        outcomes = self.prefetch(name, keys)
        policy = ent.provider.failure_policy
        responses: dict[str, object] = {}
        errors: dict[str, str] = {}
        for k, o in outcomes.items():
            if o.ok:
                responses[k] = o.value
            elif policy == FAIL:
                raise ExternalDataError(
                    f"external_data: provider {name!r} key {k!r}: {o.error}")
            elif policy == USE_DEFAULT:
                responses[k] = ent.provider.default
                errors[k] = o.error or ""
            else:       # IGNORE: recorded, not substituted
                errors[k] = o.error or ""
        return {"responses": responses, "errors": errors,
                "system_error": ""}

    # -- observability -------------------------------------------------

    def stats(self) -> dict:
        """Per-provider health snapshot for the audit report."""
        with self._lock:
            entries = dict(self._entries)
        out: dict = {}
        for name, ent in sorted(entries.items()):
            br = self.client.breaker(ent.provider)
            out[name] = {
                "breaker_state": br.state,
                "breaker_transitions": list(br.transitions),
                "short_circuits": br.short_circuits,
                "cache_entries": len(ent.cache),
                "cache_hit_ratio": round(ent.cache.hit_ratio(), 4),
                "cache_evictions": ent.cache.evictions,
                "fetch_batches": ent.fetch_batches,
                "fetch_keys": ent.fetch_keys,
                "fetch_errors": ent.fetch_errors,
                "fetch_seconds": round(ent.fetch_seconds, 6),
            }
        return out


# -- process-global runtime handle -------------------------------------
#
# The builtin registry is a flat name->function table with no way to
# thread per-evaluation state, so the runtime the `external_data`
# builtin consults is process-global (same pattern as the JAX platform
# config).  cmd/manager.py installs the managed instance; tests install
# their own and reset to None in teardown.

_runtime: ExternalDataRuntime | None = None
_runtime_lock = threading.Lock()


def get_runtime() -> ExternalDataRuntime | None:
    return _runtime


def set_runtime(rt: ExternalDataRuntime | None) -> ExternalDataRuntime | None:
    """Install the process-global runtime; returns the previous one so
    tests can restore it."""
    global _runtime
    with _runtime_lock:
        prev = _runtime
        _runtime = rt
        return prev
