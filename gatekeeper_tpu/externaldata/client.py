"""Provider fetch client: deadline, bounded retry, breaker.

One *round* = one batched ``transport(provider, keys)`` call guarded by
the provider's per-call deadline, retried up to ``provider.retries``
times with exponential backoff + jitter.  Rounds are what the
per-provider circuit breaker counts: a round that exhausts its retries
records one consecutive failure; a successful round resets the count.

The deadline is enforced with a disposable worker thread joined against
the timeout — an in-process transport (the fake) or a socket read stuck
past its own timeout cannot be preempted from Python, so the caller
stops waiting and the zombie call is abandoned (daemon thread, its
result discarded).  This is the same containment posture as the bench
watchdog: never let one wedged call strand the serving path.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable

from gatekeeper_tpu.api.externaldata import Provider
from gatekeeper_tpu.externaldata.breaker import CircuitBreaker

BACKOFF_BASE_S = 0.05
BACKOFF_CAP_S = 2.0


class FetchError(Exception):
    """A fetch round failed (transport error, timeout, or breaker open)."""


class BreakerOpenError(FetchError):
    """Short-circuited: the provider's breaker is open."""


def _call_with_deadline(fn: Callable, args: tuple, timeout_s: float):
    """Run fn(*args) on a disposable daemon thread; raise FetchError on
    deadline.  The box is a plain dict — no locking needed, the join is
    the happens-before edge."""
    box: dict = {}

    def run():
        try:
            box["value"] = fn(*args)
        except Exception as e:      # noqa: BLE001 — transport errors
            box["error"] = e        # become fetch failures by contract

    t = threading.Thread(target=run, daemon=True,
                         name="external-data-fetch")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise FetchError(f"deadline exceeded ({timeout_s:.3f}s)")
    if "error" in box:
        e = box["error"]
        raise FetchError(f"{type(e).__name__}: {e}")
    return box.get("value")


class ProviderClient:
    """Batched fetches for one runtime; transports and breakers are
    per-provider, the backoff/jitter policy is shared."""

    def __init__(self, sleep: Callable[[float], None] = time.sleep,
                 rng: random.Random | None = None):
        self._sleep = sleep
        # deterministic default jitter source: reproducible test runs,
        # and the jitter's only job is decorrelating retry storms
        self._rng = rng if rng is not None else random.Random(0x5EED)
        self._breakers: dict[str, CircuitBreaker] = {}
        self._lock = threading.Lock()

    def breaker(self, provider: Provider) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(provider.name)
            if br is None:
                br = CircuitBreaker(
                    failure_threshold=provider.breaker_threshold,
                    cooldown_s=provider.breaker_cooldown_s)
                self._breakers[provider.name] = br
            return br

    def drop_breaker(self, name: str) -> None:
        with self._lock:
            self._breakers.pop(name, None)

    def _backoff(self, attempt: int) -> float:
        base = min(BACKOFF_CAP_S, BACKOFF_BASE_S * (2 ** attempt))
        return base * (0.5 + self._rng.random())     # 0.5x..1.5x jitter

    def fetch(self, provider: Provider, transport: Callable,
              keys: list[str]) -> dict:
        """One breaker-guarded round: transport(provider, keys) ->
        {key: value}.  Raises FetchError when the round fails after its
        bounded retries, BreakerOpenError when short-circuited."""
        br = self.breaker(provider)
        if not br.allow():
            raise BreakerOpenError(
                f"provider {provider.name!r}: circuit breaker open")
        # fault seam: slow_provider stalls every fetch while armed — a
        # saturated provider, not a broken one (no breaker trip): the
        # latency surfaces as deadline pressure on the admission path
        from gatekeeper_tpu.resilience import faults
        if faults.active("slow_provider"):
            import os as _os
            self._sleep(float(_os.environ.get(
                "GATEKEEPER_FAULT_STALL_S", "0.25")))
        last: Exception | None = None
        for attempt in range(provider.retries + 1):
            if attempt:
                self._sleep(self._backoff(attempt - 1))
            try:
                result = _call_with_deadline(
                    transport, (provider, list(keys)), provider.timeout_s)
                if not isinstance(result, dict):
                    raise FetchError(
                        f"provider {provider.name!r}: transport returned "
                        f"{type(result).__name__}, expected dict")
                br.record_success()
                return result
            except FetchError as e:
                last = e
        br.record_failure()
        raise FetchError(
            f"provider {provider.name!r}: fetch failed after "
            f"{provider.retries + 1} attempts: {last}")
