"""Webhook self-bootstrap: certs + in-cluster registration.

Reference: pkg/webhook/policy.go:81-100 — unless ``-enable-manual-deploy``
is set, the webhook installs its own serving secret, service, and
``ValidatingWebhookConfiguration`` so the apiserver starts calling back.
Here the same three objects are written through the cluster protocol
(works identically against the FakeCluster and a real apiserver), and
the self-signed serving cert is generated with the system openssl when
the cert dir is empty (no cert library is vendored).
"""

from __future__ import annotations

import base64
import os
import subprocess

from gatekeeper_tpu.api.config import GVK
from gatekeeper_tpu.errors import (AlreadyExistsError, ApiError,
                                   NotFoundError)

NAMESPACE = "gatekeeper-system"
SERVICE_NAME = "gatekeeper-webhook-service"
SECRET_NAME = "gatekeeper-webhook-server-secret"
DEFAULT_WEBHOOK_NAME = "validation.gatekeeper.sh"
VWC_GVK = GVK("admissionregistration.k8s.io", "v1beta1",
              "ValidatingWebhookConfiguration")


def ensure_certs(cert_dir: str, service: str = SERVICE_NAME,
                 namespace: str = NAMESPACE) -> str | None:
    """Generate a self-signed serving cert into cert_dir when absent;
    returns the PEM CA bundle (the cert itself — self-signed) or None
    when generation is unavailable."""
    crt = os.path.join(cert_dir, "tls.crt")
    key = os.path.join(cert_dir, "tls.key")
    if not (os.path.exists(crt) and os.path.exists(key)):
        os.makedirs(cert_dir, exist_ok=True)
        cn = f"{service}.{namespace}.svc"
        try:
            subprocess.run(
                ["openssl", "req", "-x509", "-newkey", "rsa:2048",
                 "-keyout", key, "-out", crt, "-days", "3650", "-nodes",
                 "-subj", f"/CN={cn}",
                 "-addext", f"subjectAltName=DNS:{cn},DNS:localhost,"
                            f"IP:127.0.0.1"],
                check=True, capture_output=True, timeout=60)
        except (OSError, subprocess.SubprocessError):
            return None
    with open(crt) as f:
        return f.read()


def _apply(cluster, obj: dict) -> None:
    """create-or-update through the cluster protocol."""
    try:
        cluster.create(obj)
    except AlreadyExistsError:
        gvk = GVK.from_api_version(obj["apiVersion"], obj["kind"])
        meta = obj.get("metadata") or {}
        current = cluster.try_get(gvk, meta.get("name", ""),
                                  meta.get("namespace"))
        if current is not None:
            obj = dict(obj)
            obj["metadata"] = dict(meta)
            obj["metadata"]["resourceVersion"] = \
                (current.get("metadata") or {}).get("resourceVersion")
            cluster.update(obj)


def apply_crd(cluster, name: str, group: str, version: str, kind: str,
              plural: str, namespaced: bool = True) -> None:
    """Install a CustomResourceDefinition, v1-first (apiextensions
    v1beta1 was removed in Kubernetes 1.22) with a v1beta1 fallback for
    older apiservers; idempotent."""
    from gatekeeper_tpu.errors import NotFoundError
    v1 = {"apiVersion": "apiextensions.k8s.io/v1",
          "kind": "CustomResourceDefinition",
          "metadata": {"name": name},
          "spec": {"group": group,
                   "names": {"kind": kind, "plural": plural},
                   "scope": "Namespaced" if namespaced else "Cluster",
                   "versions": [{"name": version, "served": True,
                                 "storage": True,
                                 "schema": {"openAPIV3Schema": {
                                     "type": "object",
                                     "x-kubernetes-preserve-unknown-fields":
                                         True}}}]}}
    try:
        _apply(cluster, v1)
        return
    except NotFoundError:
        pass                     # pre-1.16 apiserver: fall back
    _apply(cluster, {"apiVersion": "apiextensions.k8s.io/v1beta1",
                     "kind": "CustomResourceDefinition",
                     "metadata": {"name": name},
                     "spec": {"group": group, "version": version,
                              "names": {"kind": kind, "plural": plural}}})


def bootstrap_webhook(cluster, cert_dir: str, port: int,
                      webhook_name: str = DEFAULT_WEBHOOK_NAME,
                      namespace: str = NAMESPACE,
                      service: str = SERVICE_NAME) -> bool:
    """Install the serving secret + service + VWC (policy.go:81-100).
    Returns False (and installs nothing) when certs are unavailable —
    the operator then deploys manually, exactly the
    ``-enable-manual-deploy`` posture."""
    ca = ensure_certs(cert_dir, service, namespace)
    if ca is None:
        return False
    with open(os.path.join(cert_dir, "tls.key")) as f:
        key_pem = f.read()
    b64 = lambda s: base64.b64encode(s.encode()).decode()
    try:
        _apply(cluster, {
            "apiVersion": "v1", "kind": "Secret",
            "metadata": {"name": SECRET_NAME, "namespace": namespace},
            "type": "kubernetes.io/tls",
            "data": {"tls.crt": b64(ca), "tls.key": b64(key_pem)}})
        _apply(cluster, {
            "apiVersion": "v1", "kind": "Service",
            "metadata": {"name": service, "namespace": namespace},
            "spec": {"ports": [{"port": 443, "targetPort": port}],
                     "selector": {"control-plane": "controller-manager"}}})
        hook = {
            "name": webhook_name,
            "clientConfig": {
                "service": {"name": service, "namespace": namespace,
                            "path": "/v1/admit"},
                "caBundle": b64(ca)},
            "rules": [{"apiGroups": ["*"], "apiVersions": ["*"],
                       "operations": ["CREATE", "UPDATE"],
                       "resources": ["*"]}],
            "failurePolicy": "Ignore"}
        try:
            # v1 first: admissionregistration v1beta1 was removed in
            # Kubernetes 1.22 (v1 additionally requires sideEffects +
            # admissionReviewVersions)
            _apply(cluster, {
                "apiVersion": "admissionregistration.k8s.io/v1",
                "kind": "ValidatingWebhookConfiguration",
                "metadata": {"name": webhook_name,
                             "annotations": _backend_annotations()},
                "webhooks": [{**hook, "sideEffects": "None",
                              "admissionReviewVersions": ["v1", "v1beta1"]}]})
        except NotFoundError:
            _apply(cluster, {
                "apiVersion": "admissionregistration.k8s.io/v1beta1",
                "kind": "ValidatingWebhookConfiguration",
                "metadata": {"name": webhook_name,
                             "annotations": _backend_annotations()},
                "webhooks": [hook]})
    except ApiError:
        return False        # registration kinds not served: manual deploy
    _watch_backend_recovery(cluster, webhook_name)
    return True


def _backend_annotations() -> dict:
    """Serving-posture annotations on the VWC — the operator-visible
    analogue of the reference's ``status.byPod[]`` report (BASELINE.md):
    failurePolicy stays Ignore either way (a degraded webhook serves
    correct verdicts from the scalar fallback; it never fails closed),
    but the annotations say which engine answers admissions right now."""
    from gatekeeper_tpu.resilience.supervisor import get_supervisor
    sup = get_supervisor()
    st = sup.status()
    ann = {"gatekeeper.sh/backend-state": st["state"],
           "gatekeeper.sh/backend": st["backend"]}
    if st["state"] != "healthy" and st["reason"]:
        ann["gatekeeper.sh/backend-reason"] = st["reason"][:256]
    return ann


def _watch_backend_recovery(cluster, webhook_name: str) -> None:
    """Refresh the VWC's backend annotations when the supervisor
    transitions back to healthy, so the operating report recovers with
    the backend."""
    from gatekeeper_tpu.resilience.supervisor import get_supervisor

    def _refresh():
        for api in ("admissionregistration.k8s.io/v1",
                    "admissionregistration.k8s.io/v1beta1"):
            try:
                gvk = GVK.from_api_version(
                    api, "ValidatingWebhookConfiguration")
                obj = cluster.get(gvk, webhook_name)
                meta = obj.setdefault("metadata", {})
                ann = meta.setdefault("annotations", {})
                ann.pop("gatekeeper.sh/backend-reason", None)
                ann.update(_backend_annotations())
                cluster.update(obj)
                return
            except ApiError:
                continue

    get_supervisor().on_recovery(_refresh)
