"""Brownout ladder: graded admission degradation under overload.

Upstream Gatekeeper's only overload story is binary — the webhook
either answers or the apiserver's ``failurePolicy: Ignore`` drops
policy wholesale (bootstrap.py:135).  Between "healthy" and "ignore
everything" there is a ladder of cheaper service levels, ordered by
how much policy value each rung gives up:

    rung 0  HEALTHY      full evaluation, all enforcement actions
    rung 1  SHED_DRYRUN  skip ``enforcementAction: dryrun`` constraints
                         (observability-only; no admission effect)
    rung 2  SHED_WARN    also skip ``warn`` (advisory warnings lost,
                         verdicts unchanged)
    rung 3  SCALAR_ONLY  deny-only, scalar engine, batcher bypassed —
                         the floor that still enforces policy
    rung 4  FAIL_STATIC  stop evaluating; answer per-template
                         failurePolicy (warn/dryrun-only policy sets
                         fail open, ``deny`` NEVER fails open — those
                         requests are rejected 429)

Pressure is queue depth / queue capacity (the bounded batcher queue is
the one place load accumulates); the supervisor state adds a floor
(degraded/recovering backend ⇒ at least rung 1, poisoned ⇒ at least
SCALAR_ONLY, since the device path is gone anyway).  Escalation is
instant — overload is now; de-escalation is one rung per
``GATEKEEPER_BROWNOUT_DECAY_S`` of sustained pressure below the rung's
engage threshold minus a margin, so the ladder doesn't flap across a
load oscillation.  Every transition is flight-recorded; every shed
decision is counted (``admission_shed_total{reason=}``).

``GATEKEEPER_BROWNOUT`` = ``auto`` (default) | ``off`` | ``0``..``4``
(pin a rung — chaos/bench use this to hold a service level steady).
"""

from __future__ import annotations

import os
import threading
import time

from gatekeeper_tpu.utils.metrics import Metrics

HEALTHY = 0
SHED_DRYRUN = 1
SHED_WARN = 2
SCALAR_ONLY = 3
FAIL_STATIC = 4

RUNG_NAMES = {HEALTHY: "healthy", SHED_DRYRUN: "shed_dryrun",
              SHED_WARN: "shed_warn", SCALAR_ONLY: "scalar_only",
              FAIL_STATIC: "fail_static"}

# queue-pressure (depth/capacity) thresholds at which each rung engages
ENGAGE = {SHED_DRYRUN: 0.50, SHED_WARN: 0.70,
          SCALAR_ONLY: 0.85, FAIL_STATIC: 0.95}
# hysteresis margin below the engage threshold required to de-escalate
MARGIN = 0.10

# enforcement actions evaluation skips at each rung; deny is never a
# member — deny constraints are shed only by FAIL_STATIC's reject path
_SHED_AT = {HEALTHY: frozenset(),
            SHED_DRYRUN: frozenset({"dryrun"}),
            SHED_WARN: frozenset({"dryrun", "warn"}),
            SCALAR_ONLY: frozenset({"dryrun", "warn"}),
            FAIL_STATIC: frozenset({"dryrun", "warn"})}


def _decay_s() -> float:
    try:
        return float(os.environ.get("GATEKEEPER_BROWNOUT_DECAY_S", "1.0"))
    except ValueError:
        return 1.0


class OverloadController:
    """Computes the current brownout rung from queue pressure + the
    supervisor floor.  One instance per webhook handler; ``rung()`` is
    called on every admission request, so the hot path is a couple of
    float compares under a small lock."""

    def __init__(self, depth_fn, capacity: int,
                 metrics: Metrics | None = None):
        # depth_fn: () -> current pending-queue depth (batcher.depth)
        self.depth_fn = depth_fn
        self.capacity = max(1, capacity)
        self.metrics = metrics if metrics is not None else Metrics()
        self._lock = threading.Lock()
        self._rung = HEALTHY
        self.max_rung = HEALTHY        # high-water mark, for reports
        self._scalar_inflight = 0      # SCALAR_ONLY bypasses in flight
        self._calm_since: float | None = None
        self._listeners: list = []     # fn(frm, to, pressure) on change
        self._gauge(HEALTHY)

    def add_listener(self, fn) -> None:
        """Register ``fn(frm, to, pressure)`` to fire on every rung
        transition.  Listeners are invoked under the controller lock
        (keep them cheap and never call back into ``rung()``) and are
        best-effort: one failing listener cannot wedge the ladder.
        The rollout PromotionController uses this to roll a candidate
        back when a brownout escalation lands mid-promotion."""
        with self._lock:
            self._listeners.append(fn)

    # ------------------------------------------------------------------

    def _gauge(self, rung: int) -> None:
        self.metrics.gauge(
            "admission_brownout_rung",
            "current brownout ladder rung (0 healthy .. 4 fail-static)"
        ).set(rung)

    def _mode(self) -> str:
        return os.environ.get("GATEKEEPER_BROWNOUT", "auto")

    def _supervisor_floor(self) -> int:
        """Backend degradation sets a minimum rung: a degraded backend
        is already slower (scalar fallback), so start shedding
        observability-only work before the queue proves it; a poisoned
        backend has no device path at all.  peek_state never triggers
        the seed probe — this runs per admission request."""
        from gatekeeper_tpu.resilience import supervisor
        st = supervisor.peek_state()
        if st == supervisor.POISONED:
            return SCALAR_ONLY
        if st in (supervisor.DEGRADED, supervisor.RECOVERING):
            return SHED_DRYRUN
        return HEALTHY

    def scalar_begin(self) -> None:
        """A request entered the SCALAR_ONLY bypass — it still counts
        as backlog (see pressure)."""
        with self._lock:
            self._scalar_inflight += 1

    def scalar_end(self) -> None:
        with self._lock:
            self._scalar_inflight = max(0, self._scalar_inflight - 1)

    def pressure(self) -> float:
        """Backlog relative to the queue bound.  In-flight SCALAR_ONLY
        bypasses count too: at rung 3 the queue is out of the loop, so
        without them the signal would read calm the moment the rung
        engaged and the ladder could never reach FAIL_STATIC."""
        try:
            # unlocked read (callers may hold self._lock): a stale int
            # is fine, the signal is re-sampled every request
            inflight = self._scalar_inflight
            return min(1.0, (self.depth_fn() + inflight) / self.capacity)
        except Exception:   # noqa: BLE001 — a broken signal reads calm;
            return 0.0      # the queue bound still protects memory

    def rung(self) -> int:
        """Current rung; escalates instantly, de-escalates one rung per
        decay window of sustained calm."""
        mode = self._mode()
        if mode == "off":
            return HEALTHY
        if mode not in ("auto", ""):
            try:
                forced = max(HEALTHY, min(FAIL_STATIC, int(mode)))
            except ValueError:
                forced = HEALTHY
            with self._lock:
                if forced != self._rung:
                    self._transition(self._rung, forced, self.pressure())
                    self._rung = forced
            return forced
        p = self.pressure()
        floor = self._supervisor_floor()
        # highest rung whose engage threshold the pressure meets
        target = HEALTHY
        for r in (SHED_DRYRUN, SHED_WARN, SCALAR_ONLY, FAIL_STATIC):
            if p >= ENGAGE[r]:
                target = r
        target = max(target, floor)
        now = time.monotonic()
        with self._lock:
            cur = self._rung
            if target > cur:
                self._transition(cur, target, p)
                self._rung = target
                self._calm_since = None
                return target
            if cur == HEALTHY or cur <= floor:
                self._calm_since = None
                return cur
            # de-escalation: sustained pressure below (engage - margin)
            # of the CURRENT rung steps down one rung per decay window
            if p < ENGAGE[cur] - MARGIN:
                if self._calm_since is None:
                    self._calm_since = now
                elif now - self._calm_since >= _decay_s():
                    nxt = max(cur - 1, floor)
                    self._transition(cur, nxt, p)
                    self._rung = nxt
                    self._calm_since = None
                    return nxt
            else:
                self._calm_since = None
            return cur

    def _transition(self, frm: int, to: int, pressure: float) -> None:
        # called under self._lock; recording is best-effort
        self.max_rung = max(self.max_rung, to)
        self._gauge(to)
        self.metrics.counter(
            "admission_brownout_transitions",
            "brownout ladder rung changes",
            direction="up" if to > frm else "down").inc()
        try:
            from gatekeeper_tpu.obs.flightrecorder import record_event
            record_event("brownout_rung", frm=RUNG_NAMES[frm],
                         to=RUNG_NAMES[to], pressure=round(pressure, 3))
        except Exception:   # noqa: BLE001
            pass
        for fn in self._listeners:
            try:
                fn(frm, to, pressure)
            except Exception:   # noqa: BLE001 — listeners are advisory
                pass

    # ------------------------------------------------------------------
    # what a rung means for evaluation

    def shed_actions(self, rung: int | None = None) -> frozenset[str]:
        """Enforcement actions evaluation skips at ``rung`` (current
        rung when None).  Passed down as ``QueryOpts.shed_actions``."""
        return _SHED_AT[self.rung() if rung is None else rung]

    def count_shed(self, reason: str, n: int = 1) -> None:
        self.metrics.counter(
            "admission_shed_total",
            "admission requests shed by overload control",
            reason=reason).inc(n)
