"""Admission micro-batcher.

SURVEY §7 step 7: collect concurrent AdmissionReviews for up to
``max_wait`` seconds (or ``max_batch`` requests), then run the whole
batch through the engine in one pass.  The reference has nothing like
this — every HTTPS callback runs its own single-threaded topdown query —
but the TPU-shaped engine wants batches: one pass amortizes the client
lock, the constraint-set snapshot, and (for the device path) the kernel
dispatch.

Callers block in ``submit`` until their batch is evaluated; a dedicated
worker thread owns batch formation, so latency is bounded by
``max_wait + evaluation``.
"""

from __future__ import annotations

import threading
from typing import Callable

from gatekeeper_tpu.errors import GatekeeperError
from gatekeeper_tpu.utils.metrics import Metrics


class _Pending:
    __slots__ = ("request", "event", "response", "error", "ctx")

    def __init__(self, request, ctx=None):
        self.request = request
        self.event = threading.Event()
        self.response = None
        self.error: Exception | None = None
        # submitting request's (trace_id, span_id): the batch span on
        # the worker thread links back to every member request trace
        self.ctx = ctx


class SubmitTimeout(GatekeeperError):
    """submit() waited past its deadline for batch evaluation.  A
    GatekeeperError so the webhook handler's existing catch turns it
    into a clean deny-500 instead of a severed connection."""


class MicroBatcher:
    def __init__(self, evaluate_batch: Callable[[list[dict]], list],
                 max_batch: int = 64, max_wait: float = 0.002,
                 metrics: Metrics | None = None,
                 submit_timeout: float = 30.0,
                 prefetch: Callable[[list[dict]], None] | None = None):
        self.evaluate_batch = evaluate_batch
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.metrics = metrics if metrics is not None else Metrics()
        # deadline on the caller's wait: a wedged evaluation (hung
        # device dispatch, stuck external fetch) must not pin webhook
        # handler threads forever — the server derives this from its
        # own request deadline
        self.submit_timeout = submit_timeout
        # best-effort per-batch warm hook (external-data prefetch): runs
        # once per formed batch before evaluation so provider fetch
        # latency is paid once for the whole batch
        self.prefetch = prefetch
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="admission-batcher")
            self._thread.start()

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ------------------------------------------------------------------

    def submit(self, request: dict, timeout: float | None = None):
        """Block until the batch containing this request is evaluated,
        or until ``timeout`` (default: the batcher's submit_timeout)
        expires — then raise SubmitTimeout.  A timed-out request still
        queued is withdrawn so the worker never evaluates for a caller
        that already gave up; one already taken into a batch keeps
        evaluating (the result is discarded, the thread is freed)."""
        if self._thread is None:
            # no worker: degrade to a single-request batch inline
            return self.evaluate_batch([request])[0]
        from gatekeeper_tpu.obs.trace import get_tracer
        p = _Pending(request, ctx=get_tracer().current())
        with self._wake:
            self._queue.append(p)
            self._wake.notify()
        deadline = self.submit_timeout if timeout is None else timeout
        if not p.event.wait(deadline):
            with self._wake:
                try:
                    self._queue.remove(p)
                except ValueError:
                    pass    # already taken into a batch
            self.metrics.counter("admission_submit_timeouts").inc()
            raise SubmitTimeout(
                f"admission batch evaluation exceeded {deadline:.3f}s")
        if p.error is not None:
            raise p.error
        return p.response

    def _run(self) -> None:
        import time
        while True:
            with self._wake:
                while not self._queue and not self._stop:
                    self._wake.wait(timeout=0.5)
                if self._stop:
                    for p in self._queue:
                        p.error = RuntimeError("batcher stopped")
                        p.event.set()
                    self._queue.clear()
                    return
                # natural batching: under load, requests that arrived
                # while the previous batch evaluated are already queued
                # — take them all immediately (a timed window would only
                # add latency without growing the batch).  The window
                # engages solely for a singleton queue, giving one more
                # arrival the chance to share the evaluation pass.
                if self.max_wait > 0 and len(self._queue) == 1 \
                        and not self._stop:
                    self._wake.wait(self.max_wait)
                batch, self._queue = (self._queue[:self.max_batch],
                                      self._queue[self.max_batch:])
            if not batch:
                continue
            self.metrics.counter("admission_batches").inc()
            self.metrics.timer("admission_batch_size").observe(len(batch))
            from gatekeeper_tpu.obs.flightrecorder import record_event
            from gatekeeper_tpu.obs.trace import get_tracer
            record_event("admission_batch", size=len(batch))
            # one batch span on the worker thread; member_traces links
            # it back to every submitting request's own trace, and the
            # driver's dispatch span nests under it via the context var
            with get_tracer().span(
                    "admission.batch", cat="webhook",
                    batch_size=len(batch),
                    member_traces=sorted({p.ctx[0] for p in batch
                                          if p.ctx is not None})):
                if self.prefetch is not None:
                    try:
                        self.prefetch([p.request for p in batch])
                    except Exception:   # noqa: BLE001 — warm-up only;
                        pass            # evaluation applies real policy
                try:
                    responses = self.evaluate_batch(
                        [p.request for p in batch])
                    for p, r in zip(batch, responses):
                        p.response = r
                except Exception as e:
                    for p in batch:
                        p.error = e
            for p in batch:
                p.event.set()
