"""Admission micro-batcher.

SURVEY §7 step 7: collect concurrent AdmissionReviews for up to
``max_wait`` seconds (or ``max_batch`` requests), then run the whole
batch through the engine in one pass.  The reference has nothing like
this — every HTTPS callback runs its own single-threaded topdown query —
but the TPU-shaped engine wants batches: one pass amortizes the client
lock, the constraint-set snapshot, and (for the device path) the kernel
dispatch.

Callers block in ``submit`` until their batch is evaluated; a dedicated
worker thread owns batch formation, so latency is bounded by
``max_wait + evaluation``.

Overload control (PR 13): the pending queue is BOUNDED
(``GATEKEEPER_ADMISSION_QUEUE``) — a full queue rejects the submit with
``QueueFull`` instead of buffering unboundedly (the Podracer-style
feeder/evaluator split only works if the feeder sheds instead of
buffering; an unbounded list under a 100k rps storm is an OOM, not a
queue).  Each request carries its propagated deadline (apiserver
``?timeout=`` → server request deadline), and batch formation drops
entries that are already expired or withdrawn *before* device dispatch,
then sizes the batch so the cost-model-predicted evaluation latency
fits the tightest deadline in the batch.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable

from gatekeeper_tpu.errors import GatekeeperError
from gatekeeper_tpu.utils.metrics import Metrics

DEFAULT_QUEUE_CAPACITY = 2048


def queue_capacity_env(default: int = DEFAULT_QUEUE_CAPACITY) -> int:
    try:
        cap = int(os.environ.get("GATEKEEPER_ADMISSION_QUEUE", default))
    except ValueError:
        cap = default
    return max(1, cap)


class _Pending:
    __slots__ = ("request", "event", "response", "error", "ctx",
                 "deadline", "withdrawn")

    def __init__(self, request, ctx=None, deadline: float | None = None):
        self.request = request
        self.event = threading.Event()
        self.response = None
        self.error: Exception | None = None
        # submitting request's (trace_id, span_id): the batch span on
        # the worker thread links back to every member request trace
        self.ctx = ctx
        # absolute monotonic deadline propagated from the caller; batch
        # formation drops expired entries before dispatch
        self.deadline = deadline
        # caller gave up (SubmitTimeout) but the entry was already out
        # of reach of the remove() — formation must not evaluate it
        self.withdrawn = False


class SubmitTimeout(GatekeeperError):
    """submit() waited past its deadline for batch evaluation.  A
    GatekeeperError so the webhook handler's existing catch turns it
    into a clean deny-500 instead of a severed connection."""


class QueueFull(GatekeeperError):
    """The bounded pending queue is at capacity: the submit is REJECTED
    rather than buffered.  Distinct from SubmitTimeout so the webhook
    can ride the failurePolicy path (fail open for warn/dryrun-only
    policy sets, fail closed — 429 — when deny constraints are
    installed; policy.py)."""


class MicroBatcher:
    def __init__(self, evaluate_batch: Callable[[list[dict]], list],
                 max_batch: int = 64, max_wait: float = 0.002,
                 metrics: Metrics | None = None,
                 submit_timeout: float = 30.0,
                 prefetch: Callable[[list[dict]], None] | None = None,
                 capacity: int | None = None,
                 predict_seconds: Callable[[int], float | None]
                 | None = None,
                 certified_rungs: Callable[[], list[int] | None]
                 | None = None):
        self.evaluate_batch = evaluate_batch
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.metrics = metrics if metrics is not None else Metrics()
        # deadline on the caller's wait: a wedged evaluation (hung
        # device dispatch, stuck external fetch) must not pin webhook
        # handler threads forever — the server derives this from its
        # own request deadline
        self.submit_timeout = submit_timeout
        # best-effort per-batch warm hook (external-data prefetch): runs
        # once per formed batch before evaluation so provider fetch
        # latency is paid once for the whole batch
        self.prefetch = prefetch
        # bounded pending queue: reject-over-capacity, never buffer
        self.capacity = queue_capacity_env() if capacity is None \
            else max(1, capacity)
        # cost-model latency predictor (seconds for a batch of n
        # reviews, None while uncalibrated): batch formation shrinks the
        # batch until the prediction fits the tightest member deadline
        self.predict_seconds = predict_seconds
        # Stage-7 certified batch rungs (compile-surface certificates):
        # batch sizes whose padded review signature is provably inside
        # the certified surface.  Deadline shrinking steps along these
        # rungs — halving 50 -> 25 keeps the same padded signature
        # (bucket 32) and re-predicts the same latency, while stepping
        # 50 -> 32 -> 16 actually changes the executable the cost model
        # priced.  None (stage off / surface unbounded / no certs yet)
        # falls back to blind halving.
        self.certified_rungs = certified_rungs
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="admission-batcher")
            self._thread.start()

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ------------------------------------------------------------------

    def depth(self) -> int:
        """Current pending-queue depth (the brownout ladder's pressure
        signal; also exported as the admission_queue_depth gauge)."""
        with self._lock:
            return len(self._queue)

    def _gauge_depth(self, n: int) -> None:
        self.metrics.gauge(
            "admission_queue_depth",
            "pending admission requests awaiting batch formation").set(n)

    def submit(self, request: dict, timeout: float | None = None,
               deadline: float | None = None):
        """Block until the batch containing this request is evaluated,
        or until the effective deadline expires — then raise
        SubmitTimeout.  ``deadline`` is an absolute ``time.monotonic``
        instant propagated from the caller (apiserver ``?timeout=`` /
        server request deadline); ``timeout`` is a relative cap
        (default: the batcher's submit_timeout).  A full queue raises
        QueueFull immediately — the request is shed, not buffered.  A
        timed-out request still queued is withdrawn so the worker never
        evaluates for a caller that already gave up; one already taken
        into a batch keeps evaluating (the result is discarded, the
        thread is freed)."""
        if self._thread is None:
            # no worker: degrade to a single-request batch inline
            return self.evaluate_batch([request])[0]
        now = time.monotonic()
        wait = self.submit_timeout if timeout is None else timeout
        if deadline is not None:
            wait = min(wait, deadline - now)
        if wait <= 0:
            self.metrics.counter("admission_expired_dropped").inc()
            raise SubmitTimeout("admission deadline already expired "
                                "before evaluation")
        from gatekeeper_tpu.obs.trace import get_tracer
        p = _Pending(request, ctx=get_tracer().current(),
                     deadline=now + wait)
        with self._wake:
            if len(self._queue) >= self.capacity:
                self.metrics.counter(
                    "admission_shed_total",
                    "admission requests shed by overload control",
                    reason="queue_full").inc()
                raise QueueFull(
                    f"admission queue at capacity ({self.capacity})")
            self._queue.append(p)
            self._gauge_depth(len(self._queue))
            self._wake.notify()
        if not p.event.wait(wait):
            with self._wake:
                p.withdrawn = True
                try:
                    self._queue.remove(p)
                    self._gauge_depth(len(self._queue))
                except ValueError:
                    pass    # already taken into a batch
            self.metrics.counter("admission_submit_timeouts").inc()
            raise SubmitTimeout(
                f"admission batch evaluation exceeded {wait:.3f}s")
        if p.error is not None:
            raise p.error
        return p.response

    # ------------------------------------------------------------------
    # batch formation

    def _take_batch(self, now: float) -> list[_Pending]:
        """Pop up to max_batch live entries under the lock, dropping
        withdrawn and already-expired entries first — an expired entry
        would be evaluated for a caller whose apiserver already gave
        up, pure wasted device time under overload.  With certificates
        installed, formation additionally caps at the top certified
        rung — the rung ladder is already clipped to the largest batch
        whose Stage-8 memory surface fits the remaining HBM budget, so
        a batch that would blow the budget is never even formed."""
        cap = self.max_batch
        rungs = self._rungs()
        if rungs is not None and rungs[-1] < cap:
            cap = rungs[-1]
        take: list[_Pending] = []
        rest: list[_Pending] = []
        expired: list[_Pending] = []
        for p in self._queue:
            if p.withdrawn:
                continue
            if p.deadline is not None and p.deadline <= now:
                expired.append(p)
                continue
            (take if len(take) < cap else rest).append(p)
        if rest and cap < self.max_batch:
            self.metrics.counter(
                "admission_batch_budget_caps",
                "batch formations truncated at the largest certified "
                "rung fitting the HBM budget").inc()
        self._queue = rest
        self._gauge_depth(len(rest))
        if expired:
            self.metrics.counter(
                "admission_expired_dropped",
                "expired admission requests dropped at batch formation"
            ).inc(len(expired))
            for p in expired:
                p.error = SubmitTimeout(
                    "admission deadline expired before evaluation")
                p.event.set()
        return take

    def _rungs(self) -> list[int] | None:
        """Certified batch rungs (ascending), or None for the halving
        fallback.  Advisory: a broken provider must not shed."""
        if self.certified_rungs is None:
            return None
        try:
            rungs = self.certified_rungs()
        except Exception:   # noqa: BLE001
            return None
        if not rungs:
            return None
        return sorted({int(r) for r in rungs if int(r) >= 1})

    def _fit_to_deadline(self, take: list[_Pending]) -> list[_Pending]:
        """Shrink the batch until the cost-model-predicted evaluation
        latency fits the tightest member deadline (PR-5 static cost
        model, continuously re-calibrated by PR-9 attribution) —
        predicted-over-budget members beyond the cut stay queued for
        the next, smaller, batch.  The predictor is seeded with the
        static cost-model prior (costmodel.effective_scale), so
        shrinking has an opinion from the very first batch — it no
        longer no-ops through the uncalibrated window.  With
        Stage-7 certificates installed the shrink steps down the
        certified rung ladder (each step changes the padded signature
        the cost model priced); otherwise it halves blindly."""
        if self.predict_seconds is None or len(take) <= 1:
            return take
        deadlines = [p.deadline for p in take if p.deadline is not None]
        if not deadlines:
            return take
        budget = min(deadlines) - time.monotonic()
        rungs = self._rungs()
        n = len(take)
        while n > 1:
            try:
                pred = self.predict_seconds(n)
            except Exception:   # noqa: BLE001 — prediction is advisory;
                return take     # a broken predictor must not shed
            if pred is None or pred <= budget:
                break
            if rungs is not None:
                below = [r for r in rungs if r < n]
                n = below[-1] if below else 1
                self.metrics.counter(
                    "admission_batch_rung_shrinks",
                    "deadline shrinks stepped along certified "
                    "compile-surface rungs").inc()
            else:
                n = max(1, n // 2)
        if n == len(take):
            return take
        self.metrics.counter(
            "admission_batch_deadline_shrinks",
            "batches shrunk so predicted latency fits the tightest "
            "deadline").inc()
        keep, back = take[:n], take[n:]
        with self._wake:
            self._queue[:0] = back
            self._gauge_depth(len(self._queue))
            self._wake.notify()
        return keep

    def _run(self) -> None:
        from gatekeeper_tpu.resilience import faults
        while True:
            with self._wake:
                while not self._queue and not self._stop:
                    self._wake.wait(timeout=0.5)
                if self._stop:
                    for p in self._queue:
                        p.error = RuntimeError("batcher stopped")
                        p.event.set()
                    self._queue.clear()
                    self._gauge_depth(0)
                    return
                # natural batching: under load, requests that arrived
                # while the previous batch evaluated are already queued
                # — take them all immediately (a timed window would only
                # add latency without growing the batch).  The window
                # engages solely for a singleton queue, giving one more
                # arrival the chance to share the evaluation pass.
                if self.max_wait > 0 and len(self._queue) == 1 \
                        and not self._stop:
                    self._wake.wait(self.max_wait)
            # fault seam: queue_storm stalls batch formation once (a
            # simulated consumer stall) so the bounded queue absorbs —
            # and then sheds — a pressure spike; the sleep is outside
            # the lock so submits keep landing against the bound
            if faults.take("queue_storm"):
                time.sleep(float(os.environ.get(
                    "GATEKEEPER_FAULT_STALL_S", "0.25")))
            with self._wake:
                batch = self._take_batch(time.monotonic())
            batch = self._fit_to_deadline(batch)
            if not batch:
                continue
            self.metrics.counter("admission_batches").inc()
            self.metrics.timer("admission_batch_size").observe(len(batch))
            from gatekeeper_tpu.obs.flightrecorder import record_event
            from gatekeeper_tpu.obs.trace import get_tracer
            record_event("admission_batch", size=len(batch))
            # one batch span on the worker thread; member_traces links
            # it back to every submitting request's own trace, and the
            # driver's dispatch span nests under it via the context var
            with get_tracer().span(
                    "admission.batch", cat="webhook",
                    batch_size=len(batch),
                    member_traces=sorted({p.ctx[0] for p in batch
                                          if p.ctx is not None})):
                if self.prefetch is not None:
                    try:
                        self.prefetch([p.request for p in batch])
                    except Exception:   # noqa: BLE001 — warm-up only;
                        pass            # evaluation applies real policy
                try:
                    responses = self.evaluate_batch(
                        [p.request for p in batch])
                    for p, r in zip(batch, responses):
                        p.response = r
                except Exception as e:
                    for p in batch:
                        p.error = e
            for p in batch:
                p.event.set()
