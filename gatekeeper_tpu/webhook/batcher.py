"""Admission micro-batcher.

SURVEY §7 step 7: collect concurrent AdmissionReviews for up to
``max_wait`` seconds (or ``max_batch`` requests), then run the whole
batch through the engine in one pass.  The reference has nothing like
this — every HTTPS callback runs its own single-threaded topdown query —
but the TPU-shaped engine wants batches: one pass amortizes the client
lock, the constraint-set snapshot, and (for the device path) the kernel
dispatch.

Callers block in ``submit`` until their batch is evaluated; a dedicated
worker thread owns batch formation, so latency is bounded by
``max_wait + evaluation``.
"""

from __future__ import annotations

import threading
from typing import Callable

from gatekeeper_tpu.utils.metrics import Metrics


class _Pending:
    __slots__ = ("request", "event", "response", "error")

    def __init__(self, request):
        self.request = request
        self.event = threading.Event()
        self.response = None
        self.error: Exception | None = None


class MicroBatcher:
    def __init__(self, evaluate_batch: Callable[[list[dict]], list],
                 max_batch: int = 64, max_wait: float = 0.002,
                 metrics: Metrics | None = None):
        self.evaluate_batch = evaluate_batch
        self.max_batch = max_batch
        self.max_wait = max_wait
        self.metrics = metrics if metrics is not None else Metrics()
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._stop = False
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is None:
            self._stop = False
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="admission-batcher")
            self._thread.start()

    def stop(self) -> None:
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    # ------------------------------------------------------------------

    def submit(self, request: dict):
        """Block until the batch containing this request is evaluated."""
        if self._thread is None:
            # no worker: degrade to a single-request batch inline
            return self.evaluate_batch([request])[0]
        p = _Pending(request)
        with self._wake:
            self._queue.append(p)
            self._wake.notify()
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.response

    def _run(self) -> None:
        import time
        while True:
            with self._wake:
                while not self._queue and not self._stop:
                    self._wake.wait(timeout=0.5)
                if self._stop:
                    for p in self._queue:
                        p.error = RuntimeError("batcher stopped")
                        p.event.set()
                    self._queue.clear()
                    return
                # natural batching: under load, requests that arrived
                # while the previous batch evaluated are already queued
                # — take them all immediately (a timed window would only
                # add latency without growing the batch).  The window
                # engages solely for a singleton queue, giving one more
                # arrival the chance to share the evaluation pass.
                if self.max_wait > 0 and len(self._queue) == 1 \
                        and not self._stop:
                    self._wake.wait(self.max_wait)
                batch, self._queue = (self._queue[:self.max_batch],
                                      self._queue[self.max_batch:])
            if not batch:
                continue
            self.metrics.counter("admission_batches").inc()
            self.metrics.timer("admission_batch_size").observe(len(batch))
            try:
                responses = self.evaluate_batch([p.request for p in batch])
                for p, r in zip(batch, responses):
                    p.response = r
            except Exception as e:
                for p in batch:
                    p.error = e
            for p in batch:
                p.event.set()
