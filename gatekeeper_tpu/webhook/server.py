"""Webhook HTTP server: POST /v1/admit with an AdmissionReview envelope.

Reference: pkg/webhook/policy.go:56-79 — controller-runtime's webhook
server at path /v1/admit, port flag default 443.  This build serves the
same contract over stdlib http.server (threaded, one handler instance):
request body is a v1beta1 AdmissionReview; the response echoes the
request UID.  TLS/cert bootstrap (policy.go:81-100) is deployment
machinery a cluster would provide; the serving semantics live here.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from gatekeeper_tpu.utils.log import logger
from gatekeeper_tpu.webhook.policy import ValidationHandler

_log = logger("webhook")

WEBHOOK_PATH = "/v1/admit"
DEFAULT_PORT = 8443          # the reference defaults to 443 (policy.go:48)

# Hardening bounds (controller-runtime's webhook server enforces the
# same classes of limit — read timeouts and a bounded decoder —
# pkg/webhook/policy.go:57-79 rides that server):
REQUEST_TIMEOUT_S = 10.0     # idle timeout per read AND the wall-clock
#                              deadline for reading one request's body
MAX_BODY_BYTES = 10 << 20    # AdmissionReview objects are etcd-bounded
DRAIN_TIMEOUT_S = 15.0       # stop(): wait for in-flight admissions


def _parse_timeout_param(query: str) -> float | None:
    """Extract the apiserver's per-request timeout from the webhook
    URL query string (``timeout=10s`` — k8s Duration, but apiservers
    only ever send integer seconds; bootstrap.py registers the URL
    with it appended).  None when absent or unparseable."""
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k != "timeout" or not v:
            continue
        v = v.rstrip("s")
        try:
            t = float(v)
        except ValueError:
            return None
        return t if t > 0 else None
    return None


class _DeadlineBody:
    """Body reader with a hard wall-clock deadline.

    The handler-class ``timeout`` below is only an *idle* timeout
    (settimeout on the connection): a slowloris client trickling one
    byte every few seconds never goes idle and would pin a handler
    thread through an arbitrarily long body read.  This wrapper reads
    the body in single-recv slices (``read1``) and checks the wall
    clock between slices, so a request's body phase is cut off at
    ``deadline`` no matter how lively the trickle is.  (The
    header-line phase keeps the idle timeout: header sizes/counts are
    bounded by http.server itself.)"""

    def __init__(self, raw, conn, deadline: float):
        self._raw = raw
        self._conn = conn
        self._deadline = deadline

    def read(self, size: int) -> bytes:
        import socket as _socket
        out = bytearray()
        while len(out) < size:
            remaining = self._deadline - time.monotonic()
            if remaining <= 0:
                raise _socket.timeout("request body deadline exceeded")
            self._conn.settimeout(remaining)
            chunk = self._raw.read1(size - len(out))
            if not chunk:
                break               # EOF short read; caller json-fails
            out += chunk
        return bytes(out)


class WebhookServer:
    """Serves /v1/admit (+ /metrics).  With ``cert_dir`` holding
    tls.crt/tls.key the server speaks HTTPS — the reference serves the
    webhook over TLS from cert dir /certs (policy.go:76-79); an
    apiserver will not call back over plain HTTP."""

    def __init__(self, handler: ValidationHandler, port: int = DEFAULT_PORT,
                 host: str | None = None, metrics=None,
                 cert_dir: str | None = None,
                 request_timeout: float = REQUEST_TIMEOUT_S,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 drain_timeout: float = DRAIN_TIMEOUT_S):
        # Default bind: all interfaces when serving TLS (in-cluster the
        # apiserver calls back through a Service to the pod IP — a
        # loopback bind would refuse every callback and, with
        # failurePolicy: Ignore, silently disable admission), loopback
        # when plain HTTP (dev mode must not expose an unauthenticated
        # admit endpoint on the network).
        if host is None:
            host = "" if cert_dir else "127.0.0.1"
        self.handler = handler
        self.metrics = metrics if metrics is not None else handler.metrics
        self.cert_dir = cert_dir
        self.drain_timeout = drain_timeout
        # graceful drain: in-flight admissions finish before stop()
        # returns (the reference rides controller-runtime's server,
        # which drains on shutdown; a killed-mid-admission request
        # surfaces to the apiserver as a webhook failure and, with
        # failurePolicy: Ignore, silently skips policy)
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        outer = self

        class _HTTPHandler(BaseHTTPRequestHandler):
            # IDLE timeout per socket read (StreamRequestHandler applies
            # it via connection.settimeout) — cuts off a client that
            # stops sending, NOT one that trickles; the body read below
            # additionally enforces a wall-clock deadline (_DeadlineBody)
            timeout = request_timeout

            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                """GET /metrics — Prometheus text exposition of the
                shared registry (audit/admission/device counters) plus
                the backend supervisor's gauges.  GET /healthz — the
                supervisor's serving posture as JSON: 200 while the
                device backend is healthy, 503 when degraded/poisoned
                (admissions still serve, via the scalar fallback — the
                status code is for k8s readiness, which maps to the
                reference's failurePolicy escape hatch; BASELINE.md)."""
                if self.path == "/healthz":
                    import json as _json
                    from gatekeeper_tpu.resilience.supervisor import (
                        HEALTHY, get_supervisor)
                    from gatekeeper_tpu.resilience.snapshot import \
                        restart_report
                    sup = get_supervisor()
                    body = dict(sup.status())
                    rep = restart_report()
                    body["restart_persistent_cache_hits"] = \
                        rep["restart_persistent_cache_hits"]
                    body["restart_persistent_cache_misses"] = \
                        rep["restart_persistent_cache_misses"]
                    payload = _json.dumps(body).encode()
                    self.send_response(
                        200 if body["state"] == HEALTHY else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path.split("?", 1)[0] == "/debug/violations":
                    # the continuously-true violation set + the recent
                    # delta event stream (enforce/ledger.py): one JSON
                    # document per live VerdictLedger
                    import json as _json
                    from gatekeeper_tpu.enforce.ledger import export_all
                    from gatekeeper_tpu.enforce.reactor import export_state
                    doc = export_all()
                    doc["reactors"] = export_state()
                    payload = _json.dumps(doc, default=str).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path.split("?", 1)[0] == "/debug/trace":
                    # Chrome trace-event JSON of the tracer's span ring
                    # — load in Perfetto / chrome://tracing
                    import json as _json
                    from gatekeeper_tpu.obs.trace import get_tracer
                    payload = _json.dumps(get_tracer().export()).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                    return
                if self.path != "/metrics":
                    self.send_error(404)
                    return
                text = outer.metrics.render_prometheus()
                try:
                    from gatekeeper_tpu.resilience.supervisor import \
                        get_supervisor
                    # distinct prefix: the supervisor keeps its own
                    # registry, and several of its names (counters it
                    # shares spelling with the handler registry) would
                    # otherwise collide in one exposition
                    text += get_supervisor().metrics.render_prometheus(
                        prefix="gatekeeper_supervisor")
                except Exception:   # noqa: BLE001 — metrics must render
                    pass            # even if the supervisor can't seed
                payload = text.encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                # the apiserver appends its per-request timeout to the
                # registered URL (bootstrap.py: ?timeout=10s) — split it
                # off the path and turn it into the admission deadline
                path, _, query = self.path.partition("?")
                if path != WEBHOOK_PATH:
                    self.send_error(404)
                    return
                apiserver_timeout = _parse_timeout_param(query)
                if "chunked" in (self.headers.get(
                        "Transfer-Encoding") or "").lower():
                    # unbounded chunked bodies defeat the size cap; the
                    # apiserver always sends Content-Length
                    self.send_error(411, "Content-Length required")
                    return
                try:
                    length = int(self.headers.get("Content-Length", "0"))
                except ValueError:
                    self.send_error(400, "bad Content-Length")
                    return
                if length < 0:
                    # rfile.read(-1) would read to EOF — unbounded
                    self.send_error(400, "bad Content-Length")
                    return
                if length > max_body_bytes:
                    self.send_error(413, "request body too large")
                    return
                with outer._inflight_cv:
                    outer._inflight += 1
                try:
                    rbody = _DeadlineBody(
                        self.rfile, self.connection,
                        time.monotonic() + request_timeout)
                    try:
                        payload = rbody.read(length)
                    finally:
                        # restore the idle timeout the deadline reads
                        # shrank — in a finally, because when the read
                        # itself times out the 400 below would otherwise
                        # be written against a near-zero socket timeout
                        # and die mid-send (keep-alive: the next request
                        # starts fresh either way)
                        self.connection.settimeout(request_timeout)
                    body = json.loads(payload or b"{}")
                    request = body.get("request") or {}
                    # admission deadline: the tightest of the
                    # apiserver's ?timeout= and this server's own
                    # request budget — propagated so batch formation
                    # drops the request the moment it expires instead
                    # of evaluating for a caller that already gave up
                    budget = request_timeout
                    if apiserver_timeout is not None:
                        budget = min(budget, apiserver_timeout)
                    response = outer.handler.handle(
                        request, deadline=time.monotonic() + budget)
                    envelope = {
                        "apiVersion": body.get("apiVersion",
                                               "admission.k8s.io/v1beta1"),
                        "kind": "AdmissionReview",
                        "response": {"uid": request.get("uid", ""),
                                     **response},
                    }
                    payload = json.dumps(envelope).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(payload)))
                    self.end_headers()
                    self.wfile.write(payload)
                except Exception as e:  # malformed body etc.
                    try:
                        self.send_error(400, str(e))
                    except Exception:
                        pass   # client already gone
                finally:
                    with outer._inflight_cv:
                        outer._inflight -= 1
                        outer._inflight_cv.notify_all()

        self._server = ThreadingHTTPServer((host, port), _HTTPHandler)
        self.tls = False
        if cert_dir:
            import os
            import ssl
            crt = os.path.join(cert_dir, "tls.crt")
            key = os.path.join(cert_dir, "tls.key")
            if os.path.exists(crt) and os.path.exists(key):
                ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
                ctx.load_cert_chain(crt, key)
                self._server.socket = ctx.wrap_socket(
                    self._server.socket, server_side=True)
                self.tls = True
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        _log.info("webhook serving", port=self.port,
                  tls=getattr(self, "tls", False))
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="webhook-server")
            self._thread.start()

    def stop(self) -> None:
        """Stop accepting, drain in-flight admissions, then close."""
        self._server.shutdown()          # stop the accept loop
        deadline = time.monotonic() + self.drain_timeout
        with self._inflight_cv:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    _log.info("webhook drain timeout",
                              inflight=self._inflight)
                    break
                self._inflight_cv.wait(remaining)
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
