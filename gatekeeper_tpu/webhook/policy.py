"""Validating admission webhook — Handle semantics.

Reference: pkg/webhook/policy.go:125-277.  The latency-critical serving
path:

- requests from Gatekeeper's own service account are allowed through
  ("Gatekeeper does not self-manage", :127,199-207);
- DELETE reviews validate ``oldObject`` (apiservers ≥1.15 send it;
  otherwise error 500, :131-147);
- writes of ConstraintTemplate / constraint kinds are validated
  synchronously (CreateCRD / ValidateConstraint, :149,211-241) — user
  errors deny with 422, internal errors with 500;
- everything else is reviewed against the engine with per-user/kind
  trace toggles from the Config CR (:244-277); violations deny with 403
  and one ``[denied by <constraint>] <msg>`` line per result (:173-184).

Requests ride the micro-batcher when one is attached (SURVEY §7 step 7):
concurrent Handle calls coalesce into one engine pass per batch window.
"""

from __future__ import annotations

import time

from gatekeeper_tpu.api.config import (CONFIG_NAME, CONFIG_NAMESPACE, Config,
                                       GVK)
from gatekeeper_tpu.client.client import Client
from gatekeeper_tpu.controllers.config import CONFIG_GVK
from gatekeeper_tpu.errors import ClientError, GatekeeperError, RegoError
from gatekeeper_tpu.utils.metrics import Metrics

NAMESPACE = "gatekeeper-system"
TEMPLATE_GROUP = "templates.gatekeeper.sh"
CONSTRAINT_GROUP = "constraints.gatekeeper.sh"


def allow(message: str = "") -> dict:
    return {"allowed": True, "status": {"code": 200, "message": message}}


def deny(code: int, message: str) -> dict:
    return {"allowed": False, "status": {"code": code, "message": message}}


def is_gk_service_account(user_info: dict) -> bool:
    """policy.go:199-207: group system:serviceaccounts:gatekeeper-system."""
    groups = (user_info or {}).get("groups") or []
    return f"system:serviceaccounts:{NAMESPACE}" in groups


class ValidationHandler:
    def __init__(self, client: Client, cluster=None, injected_config=None,
                 batcher=None, metrics: Metrics | None = None,
                 log=lambda *_: None, batch_mode: str = "auto",
                 overload=None):
        self.client = client
        self.cluster = cluster
        self.injected_config = injected_config  # test hook (policy.go:121)
        self.batcher = batcher
        self.metrics = metrics if metrics is not None else Metrics()
        self.log = log
        # "auto": batch only when a full batch clears the device
        # engine's small-workload threshold; "always"/"never" force it
        self.batch_mode = batch_mode
        # brownout ladder controller (webhook/overload.py); None keeps
        # the pre-ladder behavior (always rung 0)
        self.overload = overload
        # cached Counter of installed enforcement actions (see
        # _installed_actions): (expires_at_monotonic, counter)
        self._actions_cache: tuple[float, dict] = (0.0, {})

    # ------------------------------------------------------------------

    def handle(self, request: dict, deadline: float | None = None) -> dict:
        """AdmissionRequest dict -> AdmissionResponse dict.
        ``deadline`` is an absolute ``time.monotonic`` instant derived
        from the apiserver's per-request timeout (server.py parses the
        webhook URL's ``?timeout=``); batch formation drops the request
        once it passes."""
        from gatekeeper_tpu.obs.trace import get_tracer
        t0 = time.perf_counter()
        kind = request.get("kind") or {}
        # request root span: each admission gets its own trace; the
        # batcher records which request traces each batch served
        with get_tracer().span(
                "admission.request", cat="webhook",
                operation=request.get("operation", ""),
                kind=kind.get("kind", "")) as sp:
            try:
                resp = self._handle(request, deadline)
                if sp is not None:
                    sp.args["allowed"] = bool(resp.get("allowed"))
                return resp
            finally:
                self.metrics.timer("admission_seconds").observe(
                    time.perf_counter() - t0)
                self.metrics.counter("admission_requests").inc()

    def _installed_actions(self) -> dict:
        """Count of installed constraints per enforcement action — the
        failurePolicy decision ("does a rejected request lose a deny
        constraint?") and the shed accounting both need it.  Cached
        ~0.5s: the constraint set changes at reconcile cadence, not
        per request."""
        now = time.monotonic()
        expires, cached = self._actions_cache
        if now < expires:
            return cached
        from gatekeeper_tpu.client.types import enforcement_action_of
        counts: dict = {}
        for by_name in self.client.constraints.values():
            for c in by_name.values():
                a = enforcement_action_of(c)
                counts[a] = counts.get(a, 0) + 1
        self._actions_cache = (now + 0.5, counts)
        return counts

    def _fail_per_policy(self, reason: str) -> dict:
        """The failurePolicy path for a request that will NOT be
        evaluated (queue full / fail-static rung).  Upstream's webhook
        registration says ``failurePolicy: Ignore`` (bootstrap.py:135)
        — but blanket Ignore silently admits everything a deny
        constraint would have caught.  Per-template instead: if any
        ``enforcementAction: deny`` constraint is installed, fail
        CLOSED (429, retriable — the apiserver retries with backoff);
        a warn/dryrun-only policy set fails open, losing only advisory
        output."""
        if self._installed_actions().get("deny", 0) > 0:
            self.metrics.counter(
                "admission_failclosed",
                "unevaluated requests rejected because deny "
                "constraints are installed").inc()
            if self.overload is not None:
                self.overload.count_shed("fail_closed")
            return deny(429, f"admission overloaded ({reason}); "
                             "deny policies are enforced, retry")
        self.metrics.counter(
            "admission_failopen",
            "unevaluated requests admitted (no deny constraints "
            "installed)").inc()
        if self.overload is not None:
            self.overload.count_shed("fail_open")
        return allow(f"admission overloaded ({reason}); "
                     "no deny policies installed, failing open")

    def _handle(self, request: dict,
                deadline: float | None = None) -> dict:
        if is_gk_service_account(request.get("userInfo") or {}):
            return allow("Gatekeeper does not self-manage")

        if request.get("operation") == "DELETE":
            if request.get("oldObject") is None:
                return deny(500, "For admission webhooks registered for "
                                 "DELETE operations, please use Kubernetes "
                                 "v1.15.0+.")
            request = dict(request)
            request["object"] = request["oldObject"]

        user_err, err = self._validate_gatekeeper_resources(request)
        if err is not None:
            return deny(422 if user_err else 500, err)

        # brownout ladder: pick the service level for THIS request
        rung = 0
        shed: frozenset | None = None
        if self.overload is not None:
            from gatekeeper_tpu.webhook.overload import FAIL_STATIC
            rung = self.overload.rung()
            if rung >= FAIL_STATIC:
                out = self._fail_per_policy("brownout: fail-static rung")
                self._record_admission(request, out, [], [])
                return out
            shed = self.overload.shed_actions(rung) or None
            if shed:
                installed = self._installed_actions()
                for a in sorted(shed):
                    if installed.get(a, 0):
                        self.overload.count_shed(f"shed_{a}")

        from gatekeeper_tpu.webhook.batcher import QueueFull
        try:
            resp = self._review(request, deadline=deadline, shed=shed,
                                rung=rung)
        except QueueFull as e:
            out = self._fail_per_policy(str(e))
            self._record_admission(request, out, [], [])
            return out
        except GatekeeperError as e:
            return deny(500, str(e))
        results = resp.results()
        # enforcementAction routing (reference webhook validateGatekeeper
        # resources + getValidationMessages): deny blocks the request;
        # warn admits it with AdmissionResponse warnings; dryrun admits
        # silently — all three still report the violation (metrics +
        # admission corpus), which is what makes dryrun a shadow rollout
        # primitive (see whatif/shadow.py for the sweep-scale version).
        denials = [r for r in results if r.enforcement_action
                   not in ("warn", "dryrun")]
        warns = [r for r in results if r.enforcement_action == "warn"]
        dryruns = [r for r in results if r.enforcement_action == "dryrun"]
        if warns:
            self.metrics.counter("admission_warn_violations").inc(len(warns))
        if dryruns:
            self.metrics.counter("admission_dryrun_violations").inc(
                len(dryruns))
        warnings = [f"[warn by {(r.constraint.get('metadata') or {}).get('name', '')}] "
                    f"{r.msg}" for r in warns]
        if denials:
            msgs = [f"[denied by {(r.constraint.get('metadata') or {}).get('name', '')}] "
                    f"{r.msg}" for r in denials]
            self.metrics.counter("admission_denied").inc()
            out = deny(403, "\n".join(msgs))
        else:
            out = allow()
        if warnings:
            out["warnings"] = warnings
        self._record_admission(request, out, results, warnings)
        return out

    def _record_admission(self, request, out, results, warnings) -> None:
        """Feed the flight recorder's replayable admission corpus
        (opt-in, GATEKEEPER_FLIGHT_ADMISSION=1); never raises.  The
        corpus now lands in the durable capture log (rollout/capture)
        via a bounded queue — this seam only pays an enqueue, and the
        log's health (segments written, records dropped under flood)
        is surfaced as webhook gauges so a starved capture path is
        visible before a promotion gate starves with it."""
        try:
            from gatekeeper_tpu.obs.flightrecorder import get_flight_recorder
            rec = get_flight_recorder()
            rec.record_admission(
                request, bool(out.get("allowed")), verdicts=results,
                warnings=warnings)
            st = rec.capture_stats()
            if st is not None:
                self.metrics.gauge(
                    "admission_capture_segments",
                    "capture-log segments on disk").set(st["segments"])
                self.metrics.gauge(
                    "admission_capture_drops",
                    "corpus records dropped by the bounded capture "
                    "queue").set(st["dropped"])
        except Exception:
            pass

    # ------------------------------------------------------------------

    def _validate_gatekeeper_resources(self, request) -> tuple[bool, str | None]:
        """policy.go:211-241: (user_error, message)."""
        kind = request.get("kind") or {}
        obj = request.get("object")
        if kind.get("group") == TEMPLATE_GROUP and \
                kind.get("kind") == "ConstraintTemplate":
            try:
                self.client.create_crd(obj)
            except (RegoError, ClientError) as e:
                return True, str(e)
            return False, None
        if kind.get("group") == CONSTRAINT_GROUP:
            try:
                self.client.validate_constraint(obj)
            except ClientError as e:
                return True, str(e)
            return False, None
        return False, None

    def _get_config(self) -> Config:
        """policy.go:188-197 getConfig (injected test hook first)."""
        if self.injected_config is not None:
            return Config.from_dict(self.injected_config)
        if self.cluster is not None:
            obj = self.cluster.try_get(CONFIG_GVK, CONFIG_NAME,
                                       CONFIG_NAMESPACE)
            if obj is not None:
                return Config.from_dict(obj)
        return Config()

    def _trace_switch(self, request: dict) -> tuple[bool, bool]:
        cfg = self._get_config()
        kind = request.get("kind") or {}
        gvk = GVK(kind.get("group", ""), kind.get("version", ""),
                  kind.get("kind", ""))
        username = (request.get("userInfo") or {}).get("username", "")
        enabled = dump = False
        for trace in cfg.spec.traces:
            if trace.user != username or trace.kind != gvk:
                continue
            enabled = True
            if trace.dump == "All":
                dump = True
        return enabled, dump

    def _batching_pays(self) -> bool:
        """Micro-batching helps only when a coalesced batch can clear
        the device engine's small-workload threshold — below it, the
        batcher would serialize scalar evaluations that the client's
        read lock already runs concurrently (reference RWMutex,
        local.go:43-48), costing ~10x on p50."""
        if self.batch_mode != "auto":
            return self.batch_mode == "always"
        if not hasattr(self.client.driver, "query_review_batch"):
            return False
        from gatekeeper_tpu.engine.jax_driver import REVIEW_BATCH_MIN_EVALS
        n_cons = sum(len(v) for v in self.client.constraints.values())
        return n_cons * self.batcher.max_batch >= REVIEW_BATCH_MIN_EVALS

    def _review(self, request: dict, deadline: float | None = None,
                shed: frozenset | None = None, rung: int = 0):
        """reviewRequest (policy.go:244-277).  ``deadline`` rides into
        the batcher so formation drops the request once it expires;
        ``shed``/``rung`` come from the brownout ladder — at
        SCALAR_ONLY and above the batcher is bypassed (its queue is the
        thing that's congested) and the request runs deny-only through
        the scalar path."""
        tracing, dump = self._trace_switch(request)
        scalar_rung = False
        if rung:
            from gatekeeper_tpu.webhook.overload import SCALAR_ONLY
            scalar_rung = rung >= SCALAR_ONLY
        if self.batcher is not None and not tracing and not scalar_rung \
                and self._batching_pays():
            resp = self.batcher.submit(request, deadline=deadline)
        elif scalar_rung and self.overload is not None:
            # the bypass must stay visible to the pressure signal: with
            # the queue out of the loop, in-flight scalar reviews ARE
            # the backlog — without this, rung 3 empties the queue,
            # pressure reads calm, and FAIL_STATIC can never engage
            self.overload.scalar_begin()
            try:
                resp = self.client.review(request, tracing=tracing,
                                          shed_actions=shed)
            finally:
                self.overload.scalar_end()
        else:
            resp = self.client.review(request, tracing=tracing,
                                      shed_actions=shed)
        if tracing:
            self.log(resp.trace_dump())
        if dump:
            self.log(self.client.dump())
        return resp
