"""Dynamic watch manager.

Reference: pkg/watch/manager.go:25-467.  The reference maintains an
*intent* roster of GVKs per registrar (recordKeeper, :364-439), diffs it
against running watches every 5 s (:165-178), filters GVKs whose CRDs
aren't served yet via discovery (:303-327), and restarts a child
controller-runtime manager to change the watch set (:220-249).  Pause
exists so the config controller can wipe data without sync racing
(:194-216).

This build keeps the same contract — intent roster, Registrar handles,
pending-CRD filtering, pause/unpause, periodic reconciliation via
``poll_once`` — but applies watch-set deltas by (un)subscribing
individual cluster watches instead of restarting a child manager; every
(re)subscribe re-lists the GVK, giving the same resync-on-restart
semantics.  ``generation`` counts watch-set changes (the analogue of
child-manager restarts) for observability.
"""

from __future__ import annotations

import threading
from typing import Callable

from gatekeeper_tpu.api.config import GVK
from gatekeeper_tpu.controllers.runtime import ControllerManager, Reconciler


class WatchManager:
    def __init__(self, cluster, mgr: ControllerManager):
        self.cluster = cluster
        self.mgr = mgr
        self._lock = threading.RLock()
        # serializes delta APPLICATION (subscribe re-lists the GVK —
        # slow against a real API server) without holding _lock, so
        # roster reads/mutations never block behind in-flight listings
        self._poll_lock = threading.Lock()
        # registrar name -> intended GVK set (recordKeeper)
        self._intent: dict[str, set[GVK]] = {}
        self._add_fns: dict[str, Callable[[GVK], Reconciler]] = {}
        # (registrar, gvk) -> (reconciler, unsubscribe)
        self._active: dict[tuple[str, GVK], tuple] = {}
        self._paused = False
        self.generation = 0

    # ------------------------------------------------------------------

    def new_registrar(self, name: str,
                      add_fn: Callable[[GVK], Reconciler]) -> "Registrar":
        """manager.go:442-467 NewRegistrar: parent name + the function
        that builds the per-GVK reconciler when its watch starts."""
        with self._lock:
            if name in self._intent:
                raise ValueError(f"registrar {name!r} already exists")
            self._intent[name] = set()
            self._add_fns[name] = add_fn
        return Registrar(self, name)

    def watched_gvks(self) -> set[GVK]:
        """The running watch set.  Besides observability this is the
        roster the event reactor (enforce/reactor.py) mirrors via
        ``sync_subscriptions`` after every poll: the reference feeds
        informer events only to the sync *cache*, while here the same
        roster also drives page-granular verdict maintenance."""
        with self._lock:
            return {gvk for (_, gvk) in self._active}

    def pending_gvks(self) -> set[GVK]:
        """Intended but not served by discovery yet (filterPendingResources)."""
        with self._lock:
            out = set()
            for gvks in self._intent.values():
                out |= {g for g in gvks if not self.cluster.kind_served(g)}
            return out

    # ------------------------------------------------------------------
    # roster mutation (called through Registrar)

    def _add_watch(self, registrar: str, gvk: GVK) -> None:
        with self._lock:
            self._intent[registrar].add(gvk)
        self.poll_once()

    def _remove_watch(self, registrar: str, gvk: GVK) -> None:
        with self._lock:
            self._intent[registrar].discard(gvk)
        self.poll_once()

    def _replace_watch(self, registrar: str, gvks: list[GVK]) -> None:
        with self._lock:
            self._intent[registrar] = set(gvks)
        self.poll_once()

    def pause(self) -> None:
        """Stop all watches so data can be wiped without sync racing
        (manager.go:194-206)."""
        with self._lock:
            if self._paused:
                return
            self._paused = True
            for _, unsub in self._active.values():
                unsub()
            self._active.clear()
            self.generation += 1

    def unpause(self) -> None:
        """Resume; the next poll re-subscribes everything, re-listing
        each GVK (restart resync semantics, manager.go:208-216)."""
        with self._lock:
            self._paused = False
        self.poll_once()

    # ------------------------------------------------------------------

    def poll_once(self) -> None:
        """Reconcile running watches against intent (updateManagerLoop,
        :165-178, minus the 5 s sleep — callers own the cadence).  GVKs
        not yet served by discovery stay pending and are retried on the
        next poll.

        The delta is COMPUTED under ``_lock`` but APPLIED outside it:
        ``mgr.watch`` re-lists the GVK, which against a real API server
        is orders of magnitude slower than any roster mutation, and
        holding the roster lock across it would block every registrar
        (and ``pause``) behind the listing.  ``_poll_lock`` keeps
        appliers single-file; a started watch is only installed if its
        intent still stands when the lock is retaken — otherwise it is
        unsubscribed on the spot (pause or intent churn mid-listing)."""
        with self._poll_lock:
            unsubs: list = []
            with self._lock:
                if self._paused:
                    return
                desired: set[tuple[str, GVK]] = set()
                for registrar, gvks in self._intent.items():
                    for gvk in gvks:
                        if self.cluster.kind_served(gvk):
                            desired.add((registrar, gvk))
                current = set(self._active)
                to_stop = current - desired
                to_start = sorted(desired - current,
                                  key=lambda k: (k[0], k[1]))
                if not to_stop and not to_start:
                    return
                for key in to_stop:
                    _, unsub = self._active.pop(key)
                    unsubs.append(unsub)
                add_fns = dict(self._add_fns)
            for unsub in unsubs:
                unsub()
            started: list[tuple[tuple[str, GVK], tuple]] = []
            for registrar, gvk in to_start:
                reconciler = add_fns[registrar](gvk)
                unsub = self.mgr.watch(gvk, reconciler)
                started.append(((registrar, gvk), (reconciler, unsub)))
            with self._lock:
                for (registrar, gvk), entry in started:
                    if self._paused or \
                            gvk not in self._intent.get(registrar, ()):
                        entry[1]()      # stale: intent moved on mid-listing
                    else:
                        self._active[(registrar, gvk)] = entry
                self.generation += 1


class Registrar:
    """Per-parent handle on the watch manager (manager.go:442-467)."""

    def __init__(self, manager: WatchManager, name: str):
        self._manager = manager
        self.name = name

    def add_watch(self, gvk: GVK) -> None:
        self._manager._add_watch(self.name, gvk)

    def remove_watch(self, gvk: GVK) -> None:
        self._manager._remove_watch(self.name, gvk)

    def replace_watch(self, gvks: list[GVK]) -> None:
        self._manager._replace_watch(self.name, gvks)

    def pause(self) -> None:
        self._manager.pause()

    def unpause(self) -> None:
        self._manager.unpause()
