#!/usr/bin/env bash
# demo/basic: the reference's demo flow (demo/basic/demo.sh) against the
# in-memory cluster — sync config -> template -> constraint -> 1k
# namespaces -> one audit sweep -> constraint status written.
set -euo pipefail
cd "$(dirname "$0")"
exec python demo.py
