"""demo/basic: the reference's basic walkthrough (demo/basic/demo.sh —
sync config, required-labels template + constraint, a unique-label
inventory template, good/bad namespaces, and malformed gatekeeper
resources rejected synchronously), replayed kubectl-style against the
in-memory cluster with real AdmissionReview round-trips.

Run: python demo/basic/demo.py
"""

from __future__ import annotations

import glob
import json
import os
import sys
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import yaml

from gatekeeper_tpu.cmd.manager import Manager, parse_args

HERE = os.path.dirname(os.path.abspath(__file__))


def load(path: str) -> dict:
    with open(path) as f:
        return yaml.safe_load(f)


def say(line: str) -> None:
    print(line, flush=True)


def admit(port: int, obj: dict) -> dict:
    meta = obj.get("metadata") or {}
    gv = obj.get("apiVersion", "v1")
    group, _, version = gv.rpartition("/")
    req = {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
           "request": {"uid": "demo",
                       "kind": {"group": group, "version": version,
                                "kind": obj.get("kind", "")},
                       "name": meta.get("name", ""),
                       "namespace": meta.get("namespace"),
                       "operation": "CREATE", "object": obj,
                       "userInfo": {"username": "demo-user"}}}
    r = urllib.request.urlopen(
        urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/admit",
            data=json.dumps(req).encode(),
            headers={"Content-Type": "application/json"}),
        timeout=60)
    return json.load(r)["response"]


def kubectl_apply(port: int, cluster, path: str, denied: list) -> None:
    """kubectl-apply one fixture: webhook admission first; allowed
    objects land in the cluster (and sync to the engine)."""
    obj = load(path)
    name = f"{obj.get('kind', '?').lower()}/{obj['metadata']['name']}"
    say(f"$ kubectl apply -f {os.path.relpath(path, HERE)}")
    resp = admit(port, obj)
    if resp["allowed"]:
        cluster.create(obj)
        say(f"{name} created\n")
    else:
        denied.append(os.path.basename(path))
        st = resp["status"]
        say(f"Error from server (Forbidden): admission webhook denied "
            f"{name}: [{st['code']}] {st['message']}\n")


def main() -> int:
    args = parse_args(["--port", "0"])
    mgr = Manager(args)
    mgr.plane.run_until_idle()
    assert mgr.webhook is not None
    mgr.webhook.start()
    mgr.batcher.start()
    cluster, port = mgr.cluster, mgr.webhook.port
    settle = 2.0 if mgr.async_cluster else 0.0
    denied: list[str] = []

    say("===== basic demo: sync + policy install =====")
    say("$ kubectl apply -f sync.yaml")
    cluster.create(load(os.path.join(HERE, "sync.yaml")))
    say("config/config created\n")

    say("$ kubectl create ns no-label        # before any policy")
    cluster.create({"apiVersion": "v1", "kind": "Namespace",
                    "metadata": {"name": "no-label"}})
    say("namespace/no-label created\n")
    mgr.plane.run_until_idle(settle=settle)

    for rel in ("templates/k8srequiredlabels_template.yaml",
                "constraints/all_ns_must_have_gatekeeper.yaml"):
        doc = load(os.path.join(HERE, rel))
        say(f"$ kubectl apply -f {rel}")
        cluster.create(doc)
        say(f"{doc['kind'].lower()}/{doc['metadata']['name']} created\n")
        mgr.plane.run_until_idle(settle=settle)

    say("===== the required-labels constraint at admission =====")
    kubectl_apply(port, cluster, os.path.join(HERE, "bad", "bad_ns.yaml"),
                  denied)
    kubectl_apply(port, cluster, os.path.join(HERE, "good", "good_ns.yaml"),
                  denied)
    mgr.plane.run_until_idle(settle=settle)   # sync the payments ns

    say("===== unique-label policy (data.inventory lookup) =====")
    for rel in ("templates/k8suniquelabel_template.yaml",
                "constraints/all_ns_gatekeeper_label_unique.yaml"):
        doc = load(os.path.join(HERE, rel))
        say(f"$ kubectl apply -f {rel}")
        cluster.create(doc)
        say(f"{doc['kind'].lower()}/{doc['metadata']['name']} created\n")
        mgr.plane.run_until_idle(settle=settle)
    kubectl_apply(port, cluster,
                  os.path.join(HERE, "good", "no_dupe_ns.yaml"), denied)
    kubectl_apply(port, cluster,
                  os.path.join(HERE, "bad", "no_dupe_ns_2.yaml"), denied)

    say("===== malformed gatekeeper resources are rejected =====")
    for rel in ("bad/bad_template.yaml", "bad/bad_schema.yaml",
                "bad/bad_constraint_labelselector.yaml"):
        kubectl_apply(port, cluster, os.path.join(HERE, rel), denied)

    say("===== audit: the pre-policy namespace is reported =====")
    report = mgr.audit.audit_once()
    say(f"audit sweep: {report.get('violations')} violation(s)")
    say("$ kubectl get k8srequiredlabels ns-must-have-gk -o yaml  # status")
    from gatekeeper_tpu.audit.manager import gvk_of_constraint
    con = load(os.path.join(HERE, "constraints",
                            "all_ns_must_have_gatekeeper.yaml"))
    obj = cluster.get(gvk_of_constraint(con), "ns-must-have-gk")
    viols = (obj.get("status") or {}).get("violations", [])
    for v in viols:
        say(f"  - name: {v.get('name')}: {v.get('message')}")
    audited_names = {v.get("name") for v in viols}

    ok = True
    expect_denied = ["bad_ns.yaml", "no_dupe_ns_2.yaml",
                     "bad_template.yaml", "bad_schema.yaml",
                     "bad_constraint_labelselector.yaml"]
    if sorted(denied) != sorted(expect_denied):
        ok = False
        say(f"FAIL: denied {sorted(denied)} != {sorted(expect_denied)}")
    if "no-label" not in audited_names:
        ok = False
        say(f"FAIL: audit missed the pre-policy namespace: {audited_names}")
    mgr.webhook.stop()
    mgr.batcher.stop()
    say("\nDEMO PASS" if ok else "\nDEMO FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
