#!/usr/bin/env bash
# demo/agilebank: multi-policy scenario (required owner labels with
# regex, container limits, prod repo allowlist, unique service
# selector) against the in-memory cluster; pass --kubeconfig for a
# real apiserver.
set -euo pipefail
cd "$(dirname "$0")"
exec python demo.py "$@"
