"""demo/agilebank: the richer multi-policy scenario (reference
demo/agilebank/demo.sh) against this framework.

Story (mirroring the reference's narrative): a developer creates a
namespace nobody can later attribute; the admin responds by installing
policy — required owner labels with a value regex, container limits,
an approved-repo allowlist for production, and one-Service-per-selector
(an inventory join).  Every bad resource is then denied at admission
with the reference's 403 message shape, the good ones are admitted and
synced, and the audit sweep reports the pre-policy namespace that
started the story.

Run: python demo/agilebank/demo.py            (in-memory cluster)
     python demo/agilebank/demo.py --kubeconfig ~/.kube/config
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import yaml

from gatekeeper_tpu.api.config import GVK
from gatekeeper_tpu.cmd.manager import Manager, parse_args

HERE = os.path.dirname(os.path.abspath(__file__))


def load(path: str) -> dict:
    with open(path) as f:
        return yaml.safe_load(f)


def say(line: str) -> None:
    print(line, flush=True)


def admit(port: int, obj: dict, operation: str = "CREATE") -> dict:
    """POST a real AdmissionReview envelope to the webhook."""
    meta = obj.get("metadata") or {}
    req = {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
           "request": {"uid": "demo",
                       "kind": {"group": "", "version": "v1",
                                "kind": obj.get("kind", "")},
                       "name": meta.get("name", ""),
                       "namespace": meta.get("namespace"),
                       "operation": operation, "object": obj,
                       "userInfo": {"username": "demo-user"}}}
    r = urllib.request.urlopen(
        urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/admit",
            data=json.dumps(req).encode(),
            headers={"Content-Type": "application/json"}),
        timeout=60)
    return json.load(r)["response"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--kubeconfig", default=None)
    opts = ap.parse_args(argv)
    args = parse_args(["--port", "0"] +
                      (["--kubeconfig", opts.kubeconfig]
                       if opts.kubeconfig else []))
    mgr = Manager(args)
    mgr.plane.run_until_idle()
    if mgr.webhook is None:
        raise SystemExit("webhook required for the demo")
    mgr.webhook.start()
    mgr.batcher.start()
    settle = 2.0 if mgr.async_cluster else 0.0
    cluster, port = mgr.cluster, mgr.webhook.port

    say("===== ENTER developer =====")
    say("$ kubectl create ns advanced-transaction-system")
    cluster.create({"apiVersion": "v1", "kind": "Namespace",
                    "metadata": {"name": "advanced-transaction-system"}})
    say("namespace/advanced-transaction-system created  (no policy yet)\n")

    say("===== ENTER admin: installing policy =====")
    cluster.create(load(os.path.join(HERE, "sync.yaml")))
    for path in sorted(glob.glob(os.path.join(HERE, "templates", "*.yaml"))):
        doc = load(path)
        cluster.create(doc)
        say(f"constrainttemplate/{doc['metadata']['name']} created")
    mgr.plane.run_until_idle(settle=settle)
    for path in sorted(glob.glob(os.path.join(HERE, "constraints", "*.yaml"))):
        doc = load(path)
        cluster.create(doc)
        say(f"{doc['kind'].lower()}/{doc['metadata']['name']} created")
    mgr.plane.run_until_idle(settle=settle)
    say("")

    say("===== ENTER developer: the bad resources =====")
    denied = 0
    for path in sorted(glob.glob(os.path.join(HERE, "bad_resources",
                                              "*.yaml"))):
        if path.endswith("duplicate-service.yaml"):
            continue    # only bad once the payments Service is synced
        obj = load(path)
        resp = admit(port, obj)
        name = os.path.basename(path)
        assert not resp["allowed"], f"{name} should have been denied"
        denied += 1
        say(f"$ kubectl apply -f bad_resources/{name}")
        say(f"DENIED ({resp['status']['code']}): "
            f"{resp['status']['message'].splitlines()[0]}\n")

    say("===== the good resources =====")
    for path in sorted(glob.glob(os.path.join(HERE, "good_resources",
                                              "*.yaml"))):
        obj = load(path)
        resp = admit(port, obj)
        name = os.path.basename(path)
        assert resp["allowed"], \
            f"{name} should have been admitted: {resp.get('status')}"
        cluster.create(obj)
        say(f"$ kubectl apply -f good_resources/{name}  ->  admitted")
    mgr.plane.run_until_idle(settle=settle)
    say("")

    say("===== the inventory join: one Service per selector =====")
    dup = load(os.path.join(HERE, "bad_resources", "duplicate-service.yaml"))
    resp = admit(port, dup)
    assert not resp["allowed"], "duplicate selector must be denied"
    say("duplicate selector denied now that payments Service is synced:")
    say(f"  {resp['status']['message'].splitlines()[0]}\n")

    say("===== the audit finds the forgotten namespace =====")
    report = mgr.audit.audit_once()
    say(f"audit sweep: {report['violations']} violation(s) in "
        f"{report.get('total_seconds', 0):.3f}s")
    con = cluster.get(GVK("constraints.gatekeeper.sh", "v1alpha1",
                          "K8sAgileLabels"), "all-must-have-owner")
    for v in (con.get("status") or {}).get("violations") or []:
        say(f"  {v.get('kind')}/{v.get('name')}: {v.get('message')}")
    names = [v.get("name") for v in (con.get("status") or {})
             .get("violations") or []]
    assert "advanced-transaction-system" in names, names
    say("\nDEMO PASS")
    mgr.stop() if hasattr(mgr, "stop") else None
    return 0


if __name__ == "__main__":
    sys.exit(main())
